"""Device-tier observability: compile ledger, HBM ledger, profiler capture.

PR 8's flight recorder (runtime/trace.py) made the HOST side legible —
spans, /metrics, the per-iteration step timeline — but the device stayed
a black box: nothing watched for post-warmup recompiles at runtime
(dlgrind's fingerprint gate is static-only), nobody accounted HBM by
category (the number ROADMAP item 1 needs to auto-size ``--serve-batch``
and ``--prefix-blocks``), and device time was attributable only by
hand-running ``jax.profiler`` offline. This module is the device half:

  * **Compile ledger + recompile sentinel** (``COMPILES``) — every
    executable the engine mints routes through :meth:`CompileLedger.watch`
    (``Engine._mint``), which times the first call (trace + compile wall
    ms) and records (key, wall ms, count). After ``Scheduler.warmup()``
    marks an engine's serving set warm, any NEW compile key emits a
    ``compile_after_warmup`` trace event + counter — the runtime twin of
    dlgrind's static fingerprint gate — and, under ``--freeze-compiles``,
    raises a structured ``RequestError`` BEFORE the compile runs. The
    ledger exports the ``dllama_compiles_total`` / ``dllama_compile_ms``
    /metrics families and the ``compiles`` /stats block, in every tier
    (replica workers run their own ledger; its block rides their stats
    reply like every other per-replica block).
  * **HBM ledger** (:func:`hbm_ledger`) — per-category live bytes from
    the engine's KNOWN array shapes (weights / KV slot cache / prefix
    arena / logits+workspace), reconciled against
    ``device.memory_stats()`` where the backend provides it (TPU/GPU;
    CPU test runs report the exact shape-derived bytes with device
    fields null), plus the headroom estimate — ``slots_addable`` /
    ``prefix_blocks_addable`` — that item 1's auto-sizing consumes.
    Exported as ``dllama_hbm_bytes{category=}`` gauges, the ``hbm``
    /stats block, and a block on every BENCH row.
  * **On-demand capture** (:meth:`Profiler.capture`) — the
    ``POST /admin/profile?ms=`` body: one bounded ``jax.profiler`` trace
    written to a directory, refusals instead of concurrent captures
    (``jax.profiler`` is process-global). ``RMSG_PROFILE`` relays the
    verb into replica worker processes (per-worker capture dirs).
  * **Sampled device-time attribution** (:meth:`Profiler.step_begin` /
    ``step_end``) — every ``--profile-sample``-th scheduler step runs
    under a short ``jax.profiler`` trace parsed by ``netstats``'
    ProfileData reader into per-entry-point device ms (the engine's
    role-specific wrapper names: ``slot_decode_step``,
    ``slot_prefill_chunk_16``, ...). Disabled (the default) it is
    allocation-free like the tracer: call sites guard on
    ``PROFILER.sample_every`` before calling anything.

Everything here is host code running strictly pre/post device dispatch —
no jitted program changes, and the dlgrind fingerprint set is invariant
by construction (the watch wrapper swaps itself out of ``Engine._steps``
after the first call, so the steady-state hot path is the raw jitted
callable again). Docs: docs/observability.md ("Device tier").
"""

from __future__ import annotations

import threading
import time

from .trace import TRACER

# -- compile ledger ---------------------------------------------------------


def _key_elem(x) -> str:
    if isinstance(x, tuple):  # nested shape/stop-id tuples: 16x2x4
        return "x".join(_key_elem(e) for e in x)
    return str(x)


def compile_key_str(key) -> str:
    """Engine compile-cache key -> a bounded, label-safe string (the
    ``key=`` label of ``dllama_compiles_total``). Tuple keys join with
    ':' (nested tuples with 'x'); bare ints are forward-segment widths;
    anything outside [0-9A-Za-z_:.x-] flattens to '_' so the string is
    a clean Prometheus label value and JSONL field."""
    import re

    if isinstance(key, tuple):
        s = ":".join(_key_elem(x) for x in key)
    elif isinstance(key, int):
        s = f"seg:{key}"
    else:
        s = str(key)
    return re.sub(r"[^0-9A-Za-z_:.x-]", "_", s)[:120]


class _CompileWatch:
    """First-call timer around one freshly-jitted executable: the first
    invocation is trace + compile + dispatch (jax compiles synchronously;
    execution is async), so its wall ms IS the number an operator needs —
    how long minting this key stalled serving. After that call the watch
    swaps the raw jitted callable back into ``engine._steps[key]``, so
    the steady-state hot path pays nothing; a caller holding a stale
    reference to the watch itself pays one attribute check."""

    __slots__ = ("_fn", "_key", "_engine", "_done")

    def __init__(self, engine, key, fn):
        self._engine = engine
        self._key = key
        self._fn = fn
        self._done = False

    def __call__(self, *args):
        if self._done:
            return self._fn(*args)
        eng = self._engine
        # sentinel BEFORE the compile: a frozen serving set refuses the
        # mint outright rather than paying for it first
        COMPILES.pre_compile(eng, self._key)
        t0 = time.perf_counter()
        out = self._fn(*args)
        ms = (time.perf_counter() - t0) * 1e3
        self._done = True
        COMPILES.record(eng, self._key, ms)
        steps = getattr(eng, "_steps", None)
        if steps is not None and steps.get(self._key) is self:
            steps[self._key] = self._fn  # steady state: zero wrapper cost
        return out


class CompileLedger:
    """Process-wide record of every executable mint (module singleton:
    ``COMPILES``). Compiles are rare by the fixed-compilation-key
    discipline the whole engine keeps, so an always-on ledger costs
    nothing on the hot path — only the mint moment is instrumented.
    The warm flag lives on the ENGINE (``Engine._compile_warm``), not
    here: a supervisor rebuild mints a fresh engine whose own warmup
    legitimately recompiles the serving set, and a global flag would
    misread those as post-warmup compiles."""

    MAX_KEYS = 256  # label-cardinality bound on the by_key map

    def __init__(self):
        self._lock = threading.Lock()
        self.freeze = False        # --freeze-compiles
        self.total = 0
        self.total_ms = 0.0
        self.after_warmup = 0      # compiles on an already-warm engine
        self.key_overflow = 0
        self.by_key: dict[str, dict] = {}  # dlrace: guarded-by(self._lock)

    def watch(self, engine, key, fn):
        """Wrap one freshly-jitted callable (the ``Engine._mint`` hook)."""
        return _CompileWatch(engine, key, fn)

    def pre_compile(self, engine, key) -> None:
        """The recompile sentinel, fired before a compile on a WARM
        engine: trace event + counter always; a structured error under
        ``--freeze-compiles`` (the runtime twin of dlgrind's static
        fingerprint gate — the offending caller fails, the compile never
        runs, the serving executables stay exactly the warmed set)."""
        if not getattr(engine, "_compile_warm", False):
            return
        ks = compile_key_str(key)
        with self._lock:
            self.after_warmup += 1
        if TRACER.enabled:
            TRACER.event("compile_after_warmup", 0, key=ks,
                         frozen=self.freeze)
        if self.freeze:
            from .scheduler import RequestError

            raise RequestError(
                "compile_after_warmup",
                f"new compile key {ks!r} after warmup with "
                "--freeze-compiles (the serving set is frozen; see "
                "docs/operations.md 'Recompile storms')",
                retryable=False)

    def record(self, engine, key, ms: float) -> None:
        ks = compile_key_str(key)
        warm = bool(getattr(engine, "_compile_warm", False))
        with self._lock:
            self.total += 1
            self.total_ms += ms
            rec = self.by_key.get(ks)
            if rec is None:
                if len(self.by_key) >= self.MAX_KEYS:
                    self.key_overflow += 1
                else:
                    rec = self.by_key[ks] = {"count": 0, "ms": 0.0}
            if rec is not None:
                rec["count"] += 1
                rec["ms"] = round(rec["ms"] + ms, 3)
                rec["last_ms"] = round(ms, 3)
        if TRACER.enabled:
            TRACER.event("compile", 0, key=ks, ms=round(ms, 3), warm=warm)

    def summary(self) -> dict:
        """The ``compiles`` /stats block (and the /metrics source)."""
        with self._lock:
            return {"total": self.total,
                    "total_ms": round(self.total_ms, 3),
                    "after_warmup": self.after_warmup,
                    "frozen": self.freeze,
                    "key_overflow": self.key_overflow,
                    "by_key": {k: dict(v) for k, v in self.by_key.items()}}

    def reset(self) -> None:
        """Test/bench isolation; the singleton survives."""
        with self._lock:
            self.freeze = False
            self.total = 0
            self.total_ms = 0.0
            self.after_warmup = 0
            self.key_overflow = 0
            self.by_key = {}


COMPILES = CompileLedger()


# -- HBM ledger -------------------------------------------------------------


def _tree_bytes(tree) -> int:
    """PER-DEVICE live bytes of a pytree (max across devices): sharded
    leaves count only the shard a device actually holds, replicated
    leaves count fully on every device. This is the number the 2.42
    GB/chip budget talks about — global ``nbytes`` would overstate a
    tp-sharded weight tp-fold (and understate what vocab sharding
    frees). On mesh-less engines every leaf lives whole on one device
    and this equals the old global sum."""
    import jax

    per_dev: dict = {}
    plain = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            plain += int(getattr(leaf, "nbytes", 0) or 0)
            continue
        this_leaf: dict = {}
        try:
            for sh in shards:
                d = sh.device.id
                this_leaf[d] = this_leaf.get(d, 0) + int(sh.data.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated buffers:
            # fall back to the leaf's PER-DEVICE share (global nbytes /
            # shard count), discarding the partial walk — adding global
            # bytes here would inflate a per-device sum up to
            # mesh-size-fold and shrink the auto-sizers' headroom
            n = max(len(shards), 1)
            plain += int(getattr(leaf, "nbytes", 0) or 0) // n
            continue
        for d, b in this_leaf.items():
            per_dev[d] = per_dev.get(d, 0) + b
    return (max(per_dev.values()) if per_dev else 0) + plain


def device_memory_stats():
    """{bytes_in_use, bytes_limit} from the first local device, or None
    where the backend has no allocator stats (CPU test runs)."""
    import jax

    try:
        ms = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — backend-dependent surface
        return None
    if not ms or "bytes_in_use" not in ms:
        return None
    return {"bytes_in_use": int(ms["bytes_in_use"]),
            "bytes_limit": int(ms.get("bytes_limit", 0)) or None}


def hbm_ledger(engine, prefix_cache=None, *, block_len: int | None = None,
               device_stats: dict | None | bool = True) -> dict:
    """Per-category live-bytes for one engine — the ``hbm`` block of
    /stats and every BENCH row.

    Categories, all derived from KNOWN allocated shapes (exact for
    weights / KV slots / arena — they are real array ``nbytes``;
    logits+workspace is the modeled transient: the (B, vocab) f32 logits
    fetch plus one (B, chunk, dim) activation segment):

      * ``weights_bytes``      — every LAYER/norm param leaf (quantized
        tensors count their packed bytes). Cached on the engine: weights
        never change size. NOTE: thread-tier replicas SHARE weight
        buffers, so summing this across replica blocks multi-counts one
        allocation — the per-replica truth is kv+arena, the weights are
        per-process.
      * ``vocab_bytes``        — the embedding table + logits head
        (tok_emb/wcls), split out of weights so vocab sharding's freed
        bytes are VISIBLE: replicated they cost the full table per
        device, sharded 1/S of it — and the difference lands directly
        in ``slots_addable``/``prefix_blocks_addable`` below.
      * ``kv_slot_bytes``      — the batched slot cache (all B rows).
      * ``prefix_arena_bytes`` — the radix cache's K/V block arena.
      * ``logits_workspace_bytes`` — modeled per-step transient (a
        vocab-sharded head fetches candidate summaries, so the modeled
        logits transient is vocab/S there).

    All categories are PER-DEVICE bytes (max across devices): sharded
    leaves count their shard, replicated ones their full copy — the
    chip-budget number, not the global array size.

    Reconciliation: ``device_bytes_in_use``/``device_bytes_limit`` from
    ``device.memory_stats()`` where the backend provides it (None on
    CPU), with ``unaccounted_bytes`` = in_use - accounted when both
    sides exist (XLA scratch, compiled executables, fusion temps).

    Headroom (what ROADMAP item 1's auto-sizing consumes):
    ``per_slot_bytes`` (one more batch row's K/V) and
    ``per_block_bytes`` (one more arena block) are always reported;
    ``slots_addable``/``prefix_blocks_addable`` = free HBM divided by
    those, when the backend reports a limit."""
    spec = engine.spec
    weights = getattr(engine, "_hbm_weights_bytes", None)
    vocab_b = getattr(engine, "_hbm_vocab_bytes", None)
    if weights is None or vocab_b is None:
        params = engine.params
        vocab_b = _tree_bytes([params[k] for k in ("tok_emb", "wcls")
                               if k in params])
        weights = _tree_bytes({k: v for k, v in params.items()
                               if k not in ("tok_emb", "wcls")})
        try:
            engine._hbm_weights_bytes = weights
            engine._hbm_vocab_bytes = vocab_b
        except AttributeError:  # a read-only engine shim: skip the cache
            pass
    kv = _tree_bytes(engine.cache)
    arena = 0
    n_blocks = 0
    bl = block_len
    if prefix_cache is not None:
        arena = (int(prefix_cache.arena_k.nbytes)
                 + int(prefix_cache.arena_v.nbytes))
        n_blocks = prefix_cache.num_blocks
        bl = prefix_cache.block_len
    import jax.numpy as jnp

    cache_itemsize = jnp.dtype(engine.cache_dtype).itemsize
    compute_itemsize = jnp.dtype(engine.compute_dtype).itemsize
    # vocab-sharded engines keep logits vocab/S per device and fetch
    # candidate summaries instead of the (B, vocab) array
    n_vshards = 1
    if getattr(engine, "shard_vocab", False):
        mesh = getattr(engine, "mesh", None)
        for a in getattr(engine, "_vocab_axes", ()) or ():
            n_vshards *= mesh.shape[a]
    logits_ws = (engine.batch * spec.vocab_size * 4 // n_vshards
                 + engine.batch * engine.prefill_chunk * spec.dim
                 * compute_itemsize)
    per_slot = (kv // engine.batch if engine.batch else 0) or (
        2 * spec.n_layers * spec.n_kv_heads * engine.seq_len
        * spec.head_size * cache_itemsize)
    per_block = (arena // n_blocks) if n_blocks else (
        2 * spec.n_layers * spec.n_kv_heads * int(bl or 32)
        * spec.head_size * cache_itemsize)
    accounted = weights + vocab_b + kv + arena + logits_ws
    dev = (device_memory_stats() if device_stats is True
           else (device_stats or None))
    if dev is not None and "bytes_in_use" not in dev:
        # a caller supplying only a budget ({"bytes_limit": L}) gets the
        # MODELED in-use — the accounted bytes — so headroom questions
        # ("what does vocab sharding free?") answer on backends without
        # allocator stats (CPU) and in what-if sizing
        dev = {"bytes_in_use": accounted,
               "bytes_limit": int(dev.get("bytes_limit") or 0) or None}
    out = {
        "weights_bytes": weights,
        "vocab_bytes": vocab_b,
        "kv_slot_bytes": kv,
        "prefix_arena_bytes": arena,
        "logits_workspace_bytes": logits_ws,
        "accounted_bytes": accounted,
        "per_slot_bytes": per_slot,
        "per_block_bytes": per_block,
        "device_bytes_in_use": None,
        "device_bytes_limit": None,
        "unaccounted_bytes": None,
        "headroom_bytes": None,
        "slots_addable": None,
        "prefix_blocks_addable": None,
    }
    if dev is not None:
        out["device_bytes_in_use"] = dev["bytes_in_use"]
        out["device_bytes_limit"] = dev["bytes_limit"]
        out["unaccounted_bytes"] = max(dev["bytes_in_use"] - accounted, 0)
        if dev["bytes_limit"]:
            free = max(dev["bytes_limit"] - dev["bytes_in_use"], 0)
            out["headroom_bytes"] = free
            out["slots_addable"] = free // per_slot if per_slot else None
            out["prefix_blocks_addable"] = (free // per_block
                                            if per_block else None)
    return out


# -- auto-sizing (the measurement→decision half of ROADMAP item 1) ----------

AUTOTUNE_VERSION = 1
AUTOTUNE_KIND = "dllama-autotune"
# heuristic knee when no calibration artifact is given: decode is
# weight-read-bound, so batching keeps paying until KV traffic competes
# with the weight read — 32 rows is the conservative cross-model default
# the ladder bench rows support; calibrate with tools/autotune.py for the
# real number on YOUR silicon (docs/serving.md "Auto-sizing")
DEFAULT_KNEE_ROWS = 32


def validate_autotune(art) -> list[str]:
    """Schema problems of one AUTOTUNE.json artifact (empty = valid).
    Shared contract with tools/autotune.py (the producer) and
    tools/dlprof.py (which re-validates standalone — it must run with no
    repo on the path)."""
    problems = []
    if not isinstance(art, dict):
        return ["not a JSON object"]
    if art.get("kind") != AUTOTUNE_KIND:
        problems.append(f"kind must be {AUTOTUNE_KIND!r}, "
                        f"got {art.get('kind')!r}")
    if art.get("version") != AUTOTUNE_VERSION:
        problems.append(f"version must be {AUTOTUNE_VERSION}, "
                        f"got {art.get('version')!r}")
    knee = art.get("knee")
    if not isinstance(knee, dict) or not knee.get("knee_rows"):
        problems.append("missing knee.knee_rows (re-run the calibration "
                        "with >= 1 measured batch size)")
    if not isinstance(art.get("decode_curve"), list):
        problems.append("missing decode_curve list")
    return problems


def load_autotune(path: str) -> dict:
    """Read + validate an AUTOTUNE.json calibration artifact
    (tools/autotune.py). Raises ValueError with every schema problem
    named — a bad artifact must be a clear startup error, never a wrong
    silent batch size."""
    import json

    with open(path) as f:
        art = json.load(f)
    problems = validate_autotune(art)
    if problems:
        raise ValueError("invalid autotune artifact: " + "; ".join(problems))
    return art


def resolve_auto_shape(engine, *, serve_batch, prefix_blocks=0,
                       prefix_block_len: int = 32, replicas: int = 1,
                       autotune: dict | None = None,
                       default_knee: int = DEFAULT_KNEE_ROWS,
                       slo_itl_ms: float | None = None,
                       itl_budget_frac: float = 0.2,
                       device_stats=True) -> dict:
    """Resolve the ``--serve-batch auto`` / ``--prefix-blocks auto``
    sentinels at engine-build time: HBM-ledger headroom capped by the
    calibrated batch knee (vLLM's size-from-measured-memory precedent
    composed with the dlprof knee estimate).

      * serve_batch  — the calibrated target capped by the slots the
        free HBM can hold, split across `replicas` (thread replicas
        share weights but each owns a B-row cache). Where the backend
        reports no allocator stats (CPU), the target stands alone.
        The target itself: the knee (where marginal throughput per
        added row halves), RAISED to the largest measured batch whose
        decode-step p50 still fits ``itl_budget_frac`` of
        ``slo_itl_ms`` when an ITL SLO and a calibration curve are
        both present — the knee is an EFFICIENCY floor, but an SLO
        budget can afford capacity past it. The budget fraction is
        deliberately small (default 0.2): a mixed iteration's wall is
        the decode forward PLUS one (B, C) chunk forward (measured at
        2-4 decode-forwards' cost — the artifact's
        ``prefill_ms_by_width``), and the admission policy must be
        able to hold the WIDEST rung without shrinking, p99 noise
        included. This is the "re-derive with your own threshold" use
        the knee estimator's curve exists for.
      * prefix_blocks — the existing 2×B×context heuristic target,
        capped at HALF the blocks the free HBM could hold (the arena
        must not eat the headroom the slots were just granted).

    `engine` is the already-built template (any batch) — per-slot /
    per-block bytes come from its real array shapes via ``hbm_ledger``.
    Raises ValueError when the engine cannot be ledgered (a weightless
    front-door template): ``auto`` needs a local engine, and the caller
    owes the operator a clear startup error, not a crash mid-build.

    Returns the full decision record — chosen values, every input, and
    the basis ("autotune" | "default_heuristic" | "hbm_cap" | "static")
    — which the API server logs at startup and exports on /stats and
    /metrics so an operator can always see WHAT was chosen and WHY."""
    if getattr(engine, "params", None) is None or not hasattr(engine,
                                                              "cache"):
        raise ValueError(
            "auto sizing needs a ledger-capable local engine (the "
            "process tier's workers own their engines — pass explicit "
            "sizes there; calibrate with tools/autotune.py and read the "
            "recommendation)")
    ledger = hbm_ledger(engine, block_len=prefix_block_len,
                        device_stats=device_stats)
    replicas = max(int(replicas), 1)
    knee = None
    knee_basis = "default_heuristic"
    if autotune is not None:
        k = (autotune.get("knee") or {}).get("knee_rows")
        if k:
            knee = int(k)
            knee_basis = "autotune"
    if knee is None:
        knee = int(default_knee)
    target, target_basis = knee, knee_basis
    rows_under_slo = None
    if slo_itl_ms and autotune is not None:
        budget = float(itl_budget_frac) * float(slo_itl_ms)
        afford = [int(p["rows"]) for p in autotune.get("decode_curve") or ()
                  if p.get("p50_ms") is not None and p["p50_ms"] <= budget]
        if afford:
            rows_under_slo = max(afford)
            if rows_under_slo > target:
                target, target_basis = rows_under_slo, "slo_curve"
    inputs = {
        "knee_rows": knee,
        "knee_basis": knee_basis,
        "slo_itl_ms": slo_itl_ms,
        "rows_under_itl_slo": rows_under_slo,
        "replicas": replicas,
        "per_slot_bytes": ledger["per_slot_bytes"],
        "per_block_bytes": ledger["per_block_bytes"],
        "headroom_bytes": ledger["headroom_bytes"],
        "slots_addable": ledger["slots_addable"],
        "prefix_blocks_addable": ledger["prefix_blocks_addable"],
    }
    out = {"inputs": inputs}
    if serve_batch == "auto":
        cap = None
        if ledger["slots_addable"] is not None:
            cap = max(int(ledger["slots_addable"]) // replicas, 1)
        b = min(target, cap) if cap is not None else target
        out["serve_batch"] = max(int(b), 1)
        out["serve_batch_basis"] = ("hbm_cap"
                                    if cap is not None and cap < target
                                    else target_basis)
    else:
        out["serve_batch"] = int(serve_batch)
        out["serve_batch_basis"] = "static"
    b = out["serve_batch"]
    if prefix_blocks == "auto":
        bl = max(int(prefix_block_len), 1)
        target = max(2 * b * engine.seq_len // bl, 1)
        cap = None
        if ledger["prefix_blocks_addable"] is not None:
            cap = max(int(ledger["prefix_blocks_addable"])
                      // (2 * replicas), 1)
        out["prefix_blocks"] = min(target, cap) if cap is not None \
            else target
        out["prefix_blocks_basis"] = ("hbm_cap"
                                      if cap is not None and cap < target
                                      else "context_heuristic")
    else:
        out["prefix_blocks"] = (int(prefix_blocks)
                                if prefix_blocks else prefix_blocks)
        out["prefix_blocks_basis"] = "static"
    return out


# -- build info -------------------------------------------------------------


def mesh_label(mesh) -> str:
    if mesh is None:
        return "single"
    try:
        return "x".join(f"{k}{v}" for k, v in mesh.shape.items())
    except Exception:  # noqa: BLE001 — shim engines without a real mesh
        return "unknown"


def build_info(engine=None) -> dict:
    """The ``dllama_build_info`` label set / ``build`` healthz block:
    package version, jax version, active backend, mesh shape. Works for
    every tier including the weightless --replica-hosts front template
    (engine may be a shape shim or None)."""
    import jax

    from .. import __version__

    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend initialized yet
        backend = "uninitialized"
    return {"version": __version__,
            "jax": jax.__version__,
            "backend": backend,
            "mesh": mesh_label(getattr(engine, "mesh", None))}


# -- sampled device-time attribution + on-demand capture --------------------


class DeviceTimeStats:
    """Per-entry-point device-ms histograms fed by the sampled step
    captures: {module name: bounded window of summed device ms within
    one sampled step}. Module names are the engine's role-specific
    wrapper names (``jit_slot_decode_step``...) as the XLA trace spells
    them."""

    def __init__(self, window: int = 512, max_keys: int = 64):
        from collections import deque  # noqa: F401 — used below

        self.window = int(window)
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        self._hist: dict[str, object] = {}  # dlrace: guarded-by(self._lock)
        self.overflow = 0

    def record(self, name: str, ms: float) -> None:
        from collections import deque

        with self._lock:
            d = self._hist.get(name)
            if d is None:
                if len(self._hist) >= self.max_keys:
                    self.overflow += 1
                    return
                d = self._hist[name] = deque(maxlen=self.window)
            d.append(ms)

    def summary(self) -> dict:
        from .stats import percentile

        with self._lock:
            items = [(k, list(d)) for k, d in self._hist.items()]
        out = {}
        for name, xs in sorted(items, key=lambda kv: -len(kv[1])):
            out[name] = {"n": len(xs),
                         "p50_ms": round(percentile(xs, 50), 4),
                         "mean_ms": round(sum(xs) / len(xs), 4)}
        return out


class SyncStats:
    """Per-sampled-step device sync/compute split — the reference's
    per-token I/T/S columns reborn for XLA (fed by
    ``netstats.per_step_op_ms``: device time of collective ops —
    all-reduce / all-gather / reduce-scatter / all-to-all /
    collective-permute — bucketed per executed module, vs the module's
    total device ms). One (sync_ms, device_ms, wall_ms) record per
    sampled step; the summary is the ``sync`` half of the
    ``device_time`` /stats block and the ``dllama_step_sync_ms`` /
    ``dllama_step_sync_share`` /metrics families."""

    def __init__(self, window: int = 512):
        from collections import deque

        self.window = int(window)
        self._lock = threading.Lock()
        self._sync = deque(maxlen=self.window)  # dlrace: guarded-by(self._lock)
        self._device = deque(maxlen=self.window)  # dlrace: guarded-by(self._lock)
        self._wall = deque(maxlen=self.window)  # dlrace: guarded-by(self._lock)

    def record(self, sync_ms: float, device_ms: float,
               wall_ms: float | None = None) -> None:
        with self._lock:
            self._sync.append(float(sync_ms))
            self._device.append(float(device_ms))
            if wall_ms is not None:
                self._wall.append(float(wall_ms))

    def summary(self) -> dict:
        from .stats import percentile

        with self._lock:
            sync = list(self._sync)
            dev = list(self._device)
            wall = list(self._wall)
        if not sync:
            return {"n": 0}
        rnd = lambda v: None if v is None else round(v, 4)  # noqa: E731
        total_dev = sum(dev)
        return {
            "n": len(sync),
            "sync_p50_ms": rnd(percentile(sync, 50)),
            "sync_p99_ms": rnd(percentile(sync, 99)),
            "device_p50_ms": rnd(percentile(dev, 50)),
            # window-mean share, sums not means-of-ratios: a near-idle
            # step's ratio must not swamp the loaded steps' story
            "sync_share": rnd(sum(sync) / total_dev) if total_dev else None,
            "wall_p50_ms": rnd(percentile(wall, 50)) if wall else None,
        }


class Profiler:
    """On-demand jax.profiler capture + sampled per-step device-time
    attribution (module singleton: ``PROFILER``).

    Disabled (``sample_every == 0``, the default) the hot path pays ONE
    attribute read per scheduler iteration — call sites guard with
    ``if PROFILER.sample_every:`` before calling ``step_begin`` (the
    tracer's guard-before-kwargs discipline; asserted allocation-free in
    tests/test_profiler.py). Enabled, every Nth working step runs under
    a short trace whose per-module device ms feed ``device_time``; the
    N-1 unsampled steps pay one counter increment.

    ``jax.profiler`` is process-global, so exactly one trace may run at
    a time: ``capture()`` (the /admin/profile body) and a due step
    sample contend on one flag — the loser skips, never blocks."""

    def __init__(self):
        self.sample_every = 0       # 0 = attribution off
        self._n = 0                 # working-step counter (sampling phase)
        self.sampled = 0            # sampled steps that produced a trace
        self.sample_failures = 0    # start/stop/parse errors (backend-dep)
        self.captures = 0           # /admin/profile captures completed
        self.device_time = DeviceTimeStats()
        self.sync = SyncStats()     # sampled sync/compute split (dlwire)
        self._lock = threading.Lock()
        self._busy = False  # dlrace: guarded-by(self._lock)

    # -- the /admin/profile body ----------------------------------------

    def capture(self, directory: str, ms: float) -> dict:
        """Write one jax.profiler trace of the next `ms` milliseconds to
        `directory` (created). Synchronous — the caller's thread sleeps
        out the window (the threaded HTTP server keeps serving), so a
        200 means the trace is on disk. Returns {"dir", "ms"}; raises
        RuntimeError("capture busy") when a trace is already running."""
        import os

        import jax

        with self._lock:
            if self._busy:
                raise RuntimeError("capture busy: a profiler trace is "
                                   "already running in this process")
            self._busy = True
        try:
            os.makedirs(directory, exist_ok=True)
            jax.profiler.start_trace(directory)
            try:
                time.sleep(max(float(ms), 0.0) / 1e3)
            finally:
                jax.profiler.stop_trace()
            self.captures += 1
            if TRACER.enabled:
                TRACER.event("profile", 0, dir=directory, ms=float(ms))
            return {"dir": directory, "ms": float(ms)}
        finally:
            with self._lock:
                self._busy = False

    # -- sampled step attribution ----------------------------------------

    def step_begin(self) -> str | None:
        """Called at the top of a WORKING scheduler step (never idle
        iterations) when sampling is on. Returns the capture dir when
        THIS step is the sampled one, else None."""
        self._n += 1
        if self._n % self.sample_every:
            return None
        with self._lock:
            if self._busy:
                return None  # an /admin/profile capture owns the slot
            self._busy = True
        import tempfile

        import jax

        try:
            d = tempfile.mkdtemp(prefix="dlprof-step-")
            jax.profiler.start_trace(d)
            return d
        except Exception:  # noqa: BLE001 — backend without profiling
            self.sample_failures += 1
            with self._lock:
                self._busy = False
            return None

    def step_end(self, directory: str, wall_ms: float | None = None) -> None:
        """Stop the step trace, then hand parse + cleanup to a short
        daemon thread: per_module_ms walks an xplane protobuf (tens of
        ms to seconds on a big trace), and the scheduler thread calling
        this must get back to serving — the sampled step's serving-side
        cost is the capture itself, never the analysis. Parse errors
        count, never raise — attribution is best-effort observability,
        the step itself already succeeded. ``wall_ms`` is the sampled
        step's host wall (rides the sync record so the report can show
        device sync next to the step wall it lived in)."""
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            self.sample_failures += 1
            with self._lock:
                self._busy = False
            return
        with self._lock:
            self._busy = False
        threading.Thread(target=self._ingest, args=(directory, wall_ms),
                         name="dlprof-ingest", daemon=True).start()

    def _ingest(self, directory: str, wall_ms: float | None = None) -> None:
        import shutil

        try:
            from .netstats import per_trace_attribution

            # ONE xplane walk for both halves (per-module device ms AND
            # summed collective ms) — the separate parsers would each
            # re-read the whole protobuf per sampled step
            per_mod, sync_ms = per_trace_attribution(directory)
            for name, ms in per_mod.items():
                self.device_time.record(name, ms)
            # the sync/compute split: collective device ms over total
            # device ms for the sampled window. The parser returns
            # empty on traces with no device plane (CPU runs) — the
            # split is then honestly absent, never 0%.
            device_ms = sum(per_mod.values())
            if per_mod:
                self.sync.record(sync_ms, device_ms, wall_ms)
                if TRACER.enabled:
                    TRACER.event(
                        "sync", 0, sync_ms=round(sync_ms, 4),
                        device_ms=round(device_ms, 4),
                        wall_ms=(None if wall_ms is None
                                 else round(wall_ms, 4)),
                        share=(round(sync_ms / device_ms, 4)
                               if device_ms else None))
            self.sampled += 1
        except Exception:  # noqa: BLE001 — malformed/absent trace plane
            self.sample_failures += 1
        finally:
            shutil.rmtree(directory, ignore_errors=True)

    def summary(self) -> dict:
        """The ``device_time`` /stats block (present when sampling on)."""
        return {"sample_every": self.sample_every,
                "sampled_steps": self.sampled,
                "sample_failures": self.sample_failures,
                "captures": self.captures,
                "by_entry": self.device_time.summary(),
                "sync": self.sync.summary()}

    def reset(self) -> None:
        self.sample_every = 0
        self._n = 0
        self.sampled = 0
        self.sample_failures = 0
        self.captures = 0
        self.device_time = DeviceTimeStats()
        self.sync = SyncStats()


PROFILER = Profiler()
