"""Replica failover: a fault-tolerant multi-replica serving tier.

One supervised engine (runtime/resilience.py) survives its own crashes,
but it is still ONE replica: a crash, stall, or tripped breaker takes the
whole service down for its recovery window, and the ROADMAP's "heavy
traffic" target cannot ride a single batch=B cache. This module puts a
host-side router in front of N supervised replicas — threads on one host,
each replica its own ``EngineSupervisor`` + ``Scheduler`` + radix prefix
cache over SHARED weight buffers (the engine factory reuses the template
engine's params, so N replicas cost N KV caches + arenas, never N weight
copies) — and makes replica failure invisible to clients:

  * CACHE-AWARE ROUTING in the SGLang style (PAPERS.md): each request is
    placed on the replica whose radix tree holds its longest prefix
    (``PrefixCache.match_len`` — a read-only peek), falling back to
    least-loaded; ``session`` keys add stickiness so a conversation keeps
    hitting the replica that already caches its history.
  * BOUNDED AUTOMATIC RETRY: a request failed with a *retryable*
    structured frame (``RequestError.retryable`` — crash/stall recovery
    marks exactly these) BEFORE its first token streamed is resubmitted
    onto a different healthy replica, up to ``retry_budget`` times, with
    a fresh sampler rebuilt from the submit-time RNG snapshot — greedy
    retries are therefore TOKEN-IDENTICAL to the run the dead replica
    would have produced (tests/test_router.py pins this). A request that
    already streamed tokens is NEVER silently replayed: the client gets
    the structured frame re-raised with ``retryable=False`` (a partial
    stream cannot be transparently retried; the client owns that choice).
  * PER-REPLICA CIRCUIT BREAKERS with half-open probes, ABOVE the
    supervisor's own engine-level breaker: a replica that keeps failing
    requests while still claiming ready (flapping) is unrouted for
    ``circuit_cooldown`` seconds, then offered exactly ONE probe request;
    success closes the circuit, failure re-opens it.
  * ROLLING DRAIN: ``drain_replica``/``restart_replica`` (and the
    ``rolling_restart`` convenience) take replicas out of rotation one at
    a time, finish their in-flight work, rebuild, and re-admit — an
    operator restarts every replica with ZERO failed requests while the
    service stays ready throughout (docs/operations.md runbook).

``Router`` duck-types the ``EngineSupervisor`` surface the API server
uses (``submit``, ``engine``, ``exclusive()``, ``ready``/``state``,
``summary()``, ``drain()``, ``reset_breaker()``, ``close()``), so
apps/api_server's handlers serve 1 or N replicas unchanged —
``build_front_door`` below is the single constructor both paths share
(the "engine owner" refactor that used to live inside ``ApiState``).

Everything here is host-side thread scheduling: no new jitted entry
points exist (each replica runs the same pinned slot_* executables), so
the dlgrind fingerprint set is unchanged by construction.

Chaos surface: each replica's scheduler carries ``fault_key="r{i}"``, so
the ``replica_raise``/``replica_stall`` sites (runtime/faults.py) kill or
wedge ONE replica deterministically mid-trace (tests/test_router.py, the
``BENCH_ROUTER=1`` bench row).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from .resilience import _COUNTER_KEYS, EngineSupervisor, EngineUnready
from .scheduler import QueueFull, RequestError, SchedulerClosed
from .stats import RouterStats, percentile
from .trace import TRACER

POLICIES = ("cache_aware", "least_loaded", "round_robin")

# session-affinity map bound: conversations are transient, and an
# unbounded dict on a long-lived router is a leak — the oldest stickiness
# entries fall off first (losing one only costs a cold placement)
_AFFINITY_CAP = 4096


class ReplicaHandle:
    """One supervised engine replica and its router-side health record —
    the reusable "engine owner" split out of apps/api_server.ApiState:
    it owns supervisor construction/rebuild for exactly one replica, so
    the HTTP layer never touches an engine directly again.

    The breaker fields (``fails``/``open_until``/``probing``) belong to
    the ROUTER's circuit (guarded by the router's lock), layered above
    the supervisor's own engine-level breaker: the supervisor answers
    "can this engine serve at all", the router circuit answers "should
    traffic go here right now"."""

    has_local_engine = True  # Router.exclusive may borrow our engine

    def __init__(self, rid: int, engine_factory, sup_kwargs: dict,
                 tier: str = "mixed"):
        self.id = rid
        # disaggregation role (runtime/kv_transfer.py): "prefill" keeps
        # this replica OUT of request placement — it only runs the
        # router's prefill passes and donates blocks; "decode"/"mixed"
        # serve requests (decode == mixed for a thread replica: the
        # role's value is that the ROUTER never places prefill-heavy
        # passes on it)
        self.tier = tier if tier in ("prefill", "decode", "mixed") \
            else "mixed"
        self._factory = engine_factory
        self._sup_kwargs = dict(sup_kwargs)
        self.sup = EngineSupervisor(engine_factory,
                                    fault_key=f"r{rid}", **self._sup_kwargs)
        self.draining = False   # router-level: out of rotation
        # fleet-controller scale-down mark (runtime/fleet.py): a replica
        # draining FOR REAP is a capacity decision, not a health event —
        # /readyz and Router.state exclude it instead of reporting
        # "draining"/unready for the whole tier
        self.reap = False
        # router circuit breaker (see class docstring)
        self.fails = 0
        self.open_until = 0.0   # 0 = closed; else half-open past it
        self.probing = False
        # counter carry across restart(): the replaced supervisor's
        # lifetime totals fold in here, so /stats aggregation never
        # resets or double-counts across a rolling restart (the same
        # contract SupervisorStats keeps across engine rebuilds)
        self._carry = {k: 0 for k in _COUNTER_KEYS}

    # -- health / placement signals ---------------------------------------

    @property
    def ready(self) -> bool:
        return self.sup.ready

    @property
    def state(self) -> str:
        return self.sup.state

    def load(self) -> int:
        """Live slots + queued requests — the least-loaded signal. Lock-
        free reads of the current generation's scheduler (deque len and
        slot scans are GIL-atomic enough for a placement heuristic)."""
        sched = self.sup._sched
        return (len(sched._queue)
                + sum(1 for s in sched.slots if s.req is not None))

    def match_len(self, tokens: list[int]) -> int:
        """Longest prefix this replica's radix tree caches (0 with the
        prefix cache off) — the cache-aware placement signal."""
        pc = self.sup.prefix_cache
        return pc.match_len(tokens) if pc is not None else 0

    # -- lifecycle (rolling restart) --------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop routing here (the router checks ``draining``) and wait
        for in-flight + queued work to finish. ROUTER-level only — the
        supervisor stays READY underneath, so ``undrain`` can re-admit
        without a rebuild (unlike EngineSupervisor.drain, whose DRAINING
        state is one-way). Lock-free busy check, same discipline as the
        supervisor's."""
        self.draining = True
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            sched = self.sup._sched
            if not sched._queue and all(s.req is None for s in sched.slots):
                return True
            time.sleep(0.02)
        return False

    def restart(self, timeout: float = 30.0) -> None:
        """Tear down and rebuild this replica's supervisor (fresh engine,
        cache, empty prefix tree — weights still shared) and re-enter
        rotation. Call after ``drain`` for a zero-failure rolling
        restart; calling it hot aborts in-flight work with structured
        shutdown frames (close()'s contract) first."""
        self.draining = True
        try:
            # close FIRST, swap after: `sup` always points at a live
            # object (the closed one answers ready=False/state=closed to
            # concurrent health reads during the window — never None)
            self.sup.close(timeout=timeout)
            # fold the dead supervisor's lifetime counters (close() is
            # final: no writer outlives it) so /stats totals carry
            old = self.sup.summary()
            for k in _COUNTER_KEYS:
                self._carry[k] += old.get(k) or 0
            self.sup = EngineSupervisor(self._factory,
                                        fault_key=f"r{self.id}",
                                        **self._sup_kwargs)
            self.fails = 0
            self.open_until = 0.0
            self.probing = False
        finally:
            self.draining = False

    def undrain(self) -> None:
        self.draining = False

    def note_routed(self, prompt: list[int]) -> None:
        """Placement hook: in-process replicas need nothing (match_len
        peeks the REAL radix tree); the remote handle overrides this to
        feed its shadow index."""

    def close(self, timeout: float = 30.0) -> None:
        self.draining = True
        if self.sup is not None:
            self.sup.close(timeout=timeout)

    def summary(self) -> dict:
        s = self.sup.summary()
        for k in _COUNTER_KEYS:
            s[k] = (s.get(k) or 0) + self._carry[k]
        s["replica"] = self.id
        s["tier"] = self.tier
        s["draining"] = self.draining
        s["reap"] = self.reap
        s["breaker_open"] = self.open_until > 0.0
        return s


class ShadowPrefixIndex:
    """Router-side shadow of a PROCESS replica's radix tree: cache-aware
    placement must survive the process boundary WITHOUT an RPC on the hot
    path (the SGLang router keeps placement cache-aware the same way —
    by shadowing what it routed, PAPERS.md), so the router records every
    prompt it places on a replica at the replica's own block granularity
    and walks this local index at pick time.

    It is an approximation by design: it tracks what was ROUTED, the
    worker's real tree tracks what was PUBLISHED and EVICTED — a stale
    entry costs one suboptimal placement (the worker's own lookup_pin is
    the ground truth at admission), never correctness. The monitor
    clears it whenever the worker's supervisor generation changes
    (``recoveries`` in the health payload — a rebuild empties the real
    tree) and on process respawn. Entries are whole-block token paths in
    an LRU-capped OrderedDict; eviction of a mid-path entry merely
    shortens a future match."""

    def __init__(self, block_len: int = 32, cap: int = 4096):
        self.block_len = int(block_len)
        self.cap = int(cap)
        self._paths: OrderedDict[tuple, None] = OrderedDict()  # dlrace: guarded-by(self._lock)
        self._lock = threading.Lock()

    def publish(self, tokens: list[int]) -> None:
        usable = max(len(tokens) - 1, 0) // self.block_len
        if usable <= 0:
            return
        with self._lock:
            for i in range(1, usable + 1):
                key = tuple(tokens[: i * self.block_len])
                self._paths[key] = None
                self._paths.move_to_end(key)
            while len(self._paths) > self.cap:
                self._paths.popitem(last=False)

    def match_len(self, tokens: list[int]) -> int:
        """Longest shadowed whole-block prefix, len-1-capped — the same
        rule as PrefixCache.match_len so thread and process replicas
        compare on one scale."""
        usable = max(len(tokens) - 1, 0) // self.block_len
        n = 0
        with self._lock:
            for i in range(1, usable + 1):
                if tuple(tokens[: i * self.block_len]) not in self._paths:
                    break
                n = i
        return n * self.block_len

    def truncate(self, tokens: list[int], keep_tokens: int) -> int:
        """Drop the shadowed paths of ``tokens`` BEYOND ``keep_tokens``
        — the shadow-staleness fix (runtime/kv_transfer.py): a donor's
        RMSG_BLOCK_QUERY answered with less than this shadow promised,
        which means the worker EVICTED part of the path the shadow still
        advertises. Left alone, the stale entries would keep attracting
        placements and fetches of dead blocks; the miss answer is the
        ground truth, so the entries past it go. Returns entries
        dropped."""
        usable = max(len(tokens) - 1, 0) // self.block_len
        dropped = 0
        missing = object()  # stored values are None — a None pop result
        # cannot distinguish hit from miss
        with self._lock:
            for i in range(max(keep_tokens, 0) // self.block_len + 1,
                           usable + 1):
                if self._paths.pop(tuple(tokens[: i * self.block_len]),
                                   missing) is not missing:
                    dropped += 1
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._paths.clear()


class _RemoteEngineInfo:
    """The slice of the Engine surface the HTTP handlers read off a
    PROCESS replica — a shape/context template (``seq_len``/``batch``),
    sourced from the worker's HELLO ack via the client cache. There is
    no local engine to step: anything beyond the template is refused."""

    def __init__(self, client):
        self._client = client

    def _field(self, name: str) -> int:
        v = getattr(self._client, name)
        if v is None:
            # no successful handshake yet (connect-mode worker not up):
            # the handlers map EngineUnready to a retryable 503
            raise EngineUnready("replica shape unknown (worker "
                                "unreachable)", 1.0)
        return v

    @property
    def seq_len(self) -> int:
        return self._field("seq_len")

    @property
    def batch(self) -> int:
        return self._field("batch")


class RemoteReplicaHandle:
    """One OUT-OF-PROCESS replica: a worker process (local-spawn mode —
    ``WorkerProc`` + respawn supervision) or a pre-started remote worker
    (connect mode, ``--replica-hosts``) behind the framed replica
    protocol (runtime/replica_worker.py). Duck-types ``ReplicaHandle``
    for the router AND the slice of the supervisor surface the router
    reaches through ``.sup`` (``sup is self``): submit, stats, drain,
    reset_breaker, _retry_after — so ``Router``'s placement, failover,
    circuit, and /stats code serve thread and process replicas through
    identical paths.

    Supervision (local-spawn mode): a monitor thread watches the process
    and a health probe (RMSG_PING — also the source of the cached
    ``load``/``busy``/counters, so the submit hot path never RPCs for
    health). A dead process is CLASSIFIED by exit code
    (``classify_exit`` — ``signal:SIGKILL`` vs ``config_error`` vs
    crash), its last-polled counters fold into a carry (totals never
    reset or double-count across a respawn), its shadow index clears,
    and it is respawned under exponential backoff — until
    ``spawn_breaker`` consecutive SHORT-LIVED spawns open the per-replica
    spawn breaker (state ``broken``; ``reset_breaker`` is the operator
    half-open, same as every other breaker in this stack). A SIGKILLed
    replica is routable again once the respawned worker's port handshake
    and warmup complete — the bound the chaos tests assert."""

    has_local_engine = False  # Router.exclusive must never pick us

    def __init__(self, rid: int, *, proc=None, address: tuple | None = None,
                 block_len: int = 32, shadow_cap: int = 4096,
                 io_timeout: float = 30.0, poll_interval: float = 0.25,
                 spawn_timeout: float = 180.0, respawn_timeout: float = 180.0,
                 spawn_backoff_base: float = 0.2,
                 spawn_backoff_max: float = 5.0, spawn_breaker: int = 3,
                 min_uptime: float = 5.0, tier: str = "mixed"):
        from .replica_worker import WorkerClient
        from .stats import ProcStats

        assert (proc is None) != (address is None), \
            "exactly one of proc (local spawn) or address (connect)"
        self.id = rid
        # disaggregation role: spawn mode stamps it from the shipped
        # worker config; connect mode starts at the default and adopts
        # whatever the worker's PONG advertises (pre-started workers own
        # their configs — _refresh_health below)
        self.tier = tier if tier in ("prefill", "decode", "mixed") \
            else "mixed"
        self.sup = self
        self.draining = False
        self.reap = False  # fleet scale-down mark (see ReplicaHandle)
        self.fails = 0
        self.open_until = 0.0
        self.probing = False
        self.shadow = ShadowPrefixIndex(block_len=block_len, cap=shadow_cap)
        self.proc_stats = ProcStats()
        self._proc = proc
        self._io = float(io_timeout)
        self._poll = float(poll_interval)
        self._respawn_timeout = float(respawn_timeout)
        self._backoff_base = float(spawn_backoff_base)
        self._backoff_max = float(spawn_backoff_max)
        self._spawn_breaker = int(spawn_breaker)
        self._min_uptime = float(min_uptime)
        self._lock = threading.RLock()
        self._closed = False
        self._broken = False  # dlrace: guarded-by(self._lock)
        self._spawn_fails = 0  # dlrace: guarded-by(self._lock)
        self._health = {"ready": False, "state": "starting", "load": 0,
                        "busy": False, "recoveries": 0}  # dlrace: guarded-by(self._lock)
        self._last_counters = {k: 0 for k in _COUNTER_KEYS}  # dlrace: guarded-by(self._lock)
        self._carry = {k: 0 for k in _COUNTER_KEYS}  # dlrace: guarded-by(self._lock)
        self._last_summary: dict | None = None  # dlrace: guarded-by(self._lock)
        # fold epoch: bumped by every death fold so a counter snapshot
        # RPC'd from the dying generation can never be re-installed into
        # the caches afterwards (it would be folded a second time on the
        # next death — double-counting /stats totals)
        self._fold_epoch = 0  # dlrace: guarded-by(self._lock)
        if proc is not None:
            proc.spawn()
            try:
                port = proc.wait_ready(timeout=spawn_timeout)
            except BaseException:
                # a worker that outlived its startup deadline (or a ctrl-C
                # during the wait) must not leak the process
                proc.stop(timeout=5.0)
                raise
            self.client = WorkerClient(proc.host, port,
                                       io_timeout=io_timeout)
        else:
            self.client = WorkerClient(address[0], address[1],
                                       io_timeout=io_timeout)
        self._spawned_at = time.perf_counter()  # dlrace: guarded-by(self._lock)
        self._refresh_health()
        self._monitor_thread = threading.Thread(
            target=self._monitor, name=f"dllama-replica-proc-r{rid}",
            daemon=True)
        self._monitor_thread.start()

    # -- supervisor surface (sup is self) ----------------------------------

    @property
    def stats(self):
        """Client-side latency window (timings only — counters come from
        the worker's RSTATS, so the router's merge never double-counts)."""
        return self.client.stats

    @property
    def prefix_cache(self):
        return None  # match_len is overridden; the real tree is remote

    @property
    def engine(self):
        """Shape template only (see _RemoteEngineInfo) — the worker owns
        the real Engine on its side of the process boundary."""
        return _RemoteEngineInfo(self.client)

    def submit(self, prompt, max_tokens, sampler, eos_id=None,
               deadline=None, trace_id=None, fill=None, tenant=None,
               priority="normal"):
        if self._broken or self._closed:
            raise EngineUnready(self.state, self._retry_after())
        if not self._health.get("ready"):
            # cached health says no: refuse at the door without a TCP
            # round-trip (at most one poll interval stale — a recovered
            # worker is routable again within self._poll)
            raise EngineUnready(self.state, self._retry_after())
        return self.client.submit(prompt, max_tokens, sampler,
                                  eos_id=eos_id, deadline=deadline,
                                  trace_id=trace_id or 0, fill=fill,
                                  tenant=tenant, priority=priority)

    def exclusive(self):
        raise EngineUnready("remote replica: no borrowable local engine",
                            1.0)

    def _retry_after(self) -> float:
        return 30.0 if self._broken else 1.0

    def reset_breaker(self) -> None:
        """Operator half-open for BOTH process-level breakers: the spawn
        breaker here (the monitor resumes respawning) and the worker's
        own engine breaker over the wire (best-effort — the worker may be
        the very thing that is dead)."""
        with self._lock:
            self._spawn_fails = 0
            self._broken = False
            if self._health.get("state") == "broken":
                self._health = {**self._health, "state": "resetting"}
        self.client.reset_breaker()

    def profile(self, ms: float) -> dict | None:
        """RMSG_PROFILE relay: capture in the WORKER process (its own
        jax runtime owns the device work), into its per-worker capture
        dir. None when the worker is unreachable/busy."""
        if self._closed:
            return None
        return self.client.profile(ms)

    # -- handle surface ----------------------------------------------------

    @property
    def ready(self) -> bool:
        return (not self._closed and not self._broken
                and bool(self._health.get("ready")))

    @property
    def state(self) -> str:
        if self._closed:
            return "closed"
        if self._broken:
            return "broken"
        return str(self._health.get("state", "unknown"))

    def load(self) -> int:
        return int(self._health.get("load", 0))

    def match_len(self, tokens: list[int]) -> int:
        return self.shadow.match_len(tokens)

    def note_routed(self, prompt: list[int]) -> None:
        self.shadow.publish(prompt)

    def drain(self, timeout: float = 30.0) -> bool:
        """Router-level drain: stop routing here, then wait for the
        worker to report idle (the ``busy`` bit of its health payload).
        The worker's supervisor stays READY underneath — undrain
        re-admits without a rebuild, same as the thread handle."""
        self.draining = True
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            h = self.client.ping(timeout=2.0)
            if h is not None and not h.get("busy"):
                return True
            if h is None and self._proc is not None \
                    and self._proc.poll() is not None:
                return True  # dead = idle; the monitor owns the respawn
            time.sleep(0.05)
        return False

    def restart(self, timeout: float = 30.0) -> None:
        """Rolling-restart step: RMSG_REBUILD swaps the worker's
        supervisor in place (fresh engine + cache + empty radix tree,
        weights shared inside the process; counters carry worker-side)
        and blocks until the fresh one is warmed. A worker too dead to
        answer is stopped and left to the monitor's respawn path."""
        self.draining = True
        try:
            ok = self.client.rebuild(timeout=max(timeout,
                                                 self._respawn_timeout))
            self.shadow.clear()
            if not ok and self._proc is not None and not self._closed:
                self._proc.stop(timeout=5.0)  # monitor detects + respawns
            self._refresh_health()
        finally:
            self.draining = False

    def undrain(self) -> None:
        self.draining = False

    def close(self, timeout: float = 30.0) -> None:
        self._closed = True
        self.draining = True
        if self._proc is not None:
            self._proc.stop(timeout=min(timeout, 10.0))
        else:
            # connect mode: the worker belongs to its own operator —
            # just detach (a graceful shutdown of a shared remote worker
            # is an ADMIN decision, not a client disconnect side effect)
            pass
        self.client.close()
        # the monitor checks _closed every poll, but a death fold can hold
        # it in respawn backoff for a while — bound the wait rather than
        # let interpreter teardown race its health probes into a closed
        # client (join(None) could hang close() behind a full breaker run)
        monitor = self._monitor_thread
        if monitor.is_alive() and monitor is not threading.current_thread():
            monitor.join(timeout=min(timeout, 5.0) + self._poll)

    def summary(self) -> dict:
        with self._lock:
            epoch = self._fold_epoch
        live = None if self._closed else self.client.stats_summary()
        with self._lock:  # the death fold reads/resets these caches
            if live is not None and epoch != self._fold_epoch:
                # the worker died between the RPC and here: the fold
                # already absorbed these counts into _carry — installing
                # (or reporting) the stale snapshot would double-count
                live = None
            if live is not None:
                self._last_summary = live
                self._last_counters = {k: live.get(k) or 0
                                       for k in _COUNTER_KEYS}
            base = dict(live or self._last_summary or {})
            for k in _COUNTER_KEYS:
                base[k] = (base.get(k) or 0) + self._carry[k]
        base["state"] = self.state
        base["replica"] = self.id
        base["tier"] = self.tier
        base["draining"] = self.draining
        base["reap"] = self.reap
        base["breaker_open"] = self.open_until > 0.0
        proc = self.proc_stats.summary()
        proc["mode"] = "spawn" if self._proc is not None else "connect"
        proc["pid"] = self._proc.pid if self._proc is not None else None
        proc["addr"] = list(self.client.addr)
        base["proc"] = proc
        return base

    # -- supervision internals ---------------------------------------------

    def _refresh_health(self) -> None:
        with self._lock:
            epoch = self._fold_epoch
        payload = self.client.ping(timeout=3.0)
        with self._lock:
            if epoch != self._fold_epoch:
                # the worker died while the PING was in flight: the fold
                # owns the caches now — installing this stale payload
                # would double-count counters on the next fold and mark
                # a corpse ready
                return
            if payload is None:
                self._health = {**self._health, "ready": False,
                                "state": "unreachable"}
                return
            if payload.get("recoveries", 0) != self._health.get(
                    "recoveries", 0):
                # the worker's supervisor rebuilt (crash/stall recovery):
                # its radix tree is empty — stop claiming warm prefixes
                self.shadow.clear()
            self._last_counters = payload.get("counters",
                                              self._last_counters)
            if payload.get("tier") in ("prefill", "decode", "mixed"):
                # connect-mode workers own their configs: the PONG is
                # where the router learns (and tracks) their role
                self.tier = payload["tier"]
            self._health = payload

    def _monitor(self) -> None:
        while not self._closed:
            proc = self._proc
            rc = proc.poll() if proc is not None else None
            if proc is not None and rc is not None:
                self._supervise_death(rc)
                continue
            self._refresh_health()
            time.sleep(self._poll)

    def _supervise_death(self, rc: int) -> None:
        """Monitor-thread-only: classify and fold ONE real worker death,
        then drive respawn attempts to success (or the spawn breaker).
        The whole death — including every failed respawn attempt — is
        handled inside this one call, so a reaped straggler is never
        re-classified as a second 'exit', and failed attempts count once
        (as ``spawn_failures``, never as worker deaths). Blocking work
        (spawn, port-handshake wait, backoff sleeps) runs OUTSIDE
        ``self._lock`` — /stats and reset_breaker stay responsive for the
        full (possibly minutes-long) respawn."""
        from .replica_worker import classify_exit

        t_detect = time.perf_counter()
        cls = classify_exit(rc)
        if TRACER.enabled:
            # the classified exit ON the timeline: with the casualty
            # span's replica_lost error and the sibling retry's route
            # event this is the cross-process kill story in one place
            TRACER.event("worker_exit", 0, replica=self.id, cls=cls,
                         rc=rc)
        with self._lock:
            if self._closed:
                return
            # fold the dead process's last-polled counters: totals are a
            # <=1-poll-interval lower bound across a SIGKILL and can
            # never double-count (the respawned worker starts at zero;
            # the epoch bump keeps in-flight PING/STATS snapshots of the
            # dead generation out of the caches)
            self._fold_epoch += 1
            for k in _COUNTER_KEYS:
                self._carry[k] += self._last_counters.get(k, 0)
            self._last_counters = {k: 0 for k in _COUNTER_KEYS}
            self._last_summary = None
            self.shadow.clear()
            self._health = {"ready": False, "state": f"exited:{cls}",
                            "load": 0, "busy": False, "recoveries": 0}
            self.proc_stats.note_exit(cls)
            uptime = t_detect - self._spawned_at
            # streak = consecutive SHORT-LIVED spawns: a long-healthy
            # worker SIGKILLed by an operator/OOM respawns on the base
            # backoff; a crash-looping one escalates into the breaker
            self._spawn_fails = (self._spawn_fails + 1
                                 if uptime < self._min_uptime else 0)
            if self._spawn_fails >= self._spawn_breaker:
                self._broken = True
                self._health = {**self._health, "state": "broken"}
                if TRACER.enabled:
                    TRACER.event("circuit", 0, scope="spawn",
                                 replica=self.id, state="open",
                                 fails=self._spawn_fails)
        while not self._closed:
            while self._broken and not self._closed:
                time.sleep(self._poll)  # breaker open: reset_breaker
            if self._closed:
                return
            time.sleep(min(self._backoff_base * (2 ** self._spawn_fails),
                           self._backoff_max))
            with self._lock:
                if self._closed or self._proc.poll() is None:
                    return  # closed, or already respawned
            try:
                self._proc.spawn()
                port = self._proc.wait_ready(
                    timeout=self._respawn_timeout)
            except RuntimeError:
                # reap a startup-deadline straggler, stamp the ATTEMPT
                # (uptime must be measured from this failed spawn, not
                # the last healthy one — otherwise a crash loop reads as
                # "long uptime" and the breaker can never trip), and go
                # around again
                rc_f = self._proc.stop(timeout=5.0)
                with self._lock:
                    self._spawned_at = time.perf_counter()
                    self._spawn_fails += 1
                    self.proc_stats.note_spawn_failure(
                        None if rc_f is None else classify_exit(rc_f))
                    if self._spawn_fails >= self._spawn_breaker:
                        self._broken = True
                        self._health = {**self._health, "state": "broken"}
                        if TRACER.enabled:
                            TRACER.event("circuit", 0, scope="spawn",
                                         replica=self.id, state="open",
                                         fails=self._spawn_fails)
                continue
            with self._lock:
                if self._closed:
                    self._proc.stop(timeout=5.0)
                    return
                self.client.set_addr(self._proc.host, port)
                self._spawned_at = time.perf_counter()
                self.proc_stats.respawns += 1
                respawn_ms = (time.perf_counter() - t_detect) * 1e3
                self.proc_stats.respawn_ms.append(respawn_ms)
            if TRACER.enabled:
                TRACER.event("respawn", 0, replica=self.id,
                             ms=round(respawn_ms, 1), port=port)
            self._refresh_health()
            return


class RouterRequest:
    """One client request as the router sees it: a thin stream wrapper
    that owns the failover decision. ``tokens()`` streams the current
    replica's events; a retryable structured failure BEFORE the first
    token re-places the request (fresh sampler from the submit-time RNG
    snapshot — token streams are attempt-invariant); any failure AFTER
    tokens streamed re-raises the frame with ``retryable=False``.

    Duck-types the consumer surface of ``ServeRequest``: ``tokens()``,
    ``cancel()``, ``finished``, ``finish_reason``, ``stats``."""

    def __init__(self, router: "Router", prompt: list[int], max_tokens: int,
                 eos_id, deadline, sampler_spec: tuple, session,
                 trace_id: int = 0, tenant=None, priority="normal"):
        # one span id for the WHOLE request: every failover attempt's
        # scheduler/worker events carry it, so the casualty and its
        # sibling retry share a timeline (runtime/trace.py)
        self.trace_id = trace_id
        self._router = router
        self._prompt = prompt
        self._max_tokens = max_tokens
        self._eos_id = eos_id
        self._deadline = deadline      # absolute: shared across attempts
        self._sampler_spec = sampler_spec  # (vocab, temp, topp, rng_state)
        self._session = session
        # fairness tags: shared by every failover attempt (a retry rides
        # the same tenant's share + the same priority band)
        self._tenant = tenant
        self._priority = priority
        self._inner = None             # current ServeRequest
        self._handle: ReplicaHandle | None = None
        self._probe = False            # current attempt IS the half-open probe
        self._cancelled = False
        self.retries = 0
        self.emitted = 0
        self.finished = threading.Event()
        self.finish_reason: str | None = None

    @property
    def replica_id(self) -> int | None:
        h = self._handle
        return h.id if h is not None else None

    @property
    def stats(self):
        """The CURRENT attempt's RequestStats (a failover's final stats
        describe the attempt that actually served the client)."""
        return self._inner.stats

    def cancel(self) -> None:
        self._cancelled = True
        if self._inner is not None:
            self._inner.cancel()
        if self._probe and self.emitted == 0 and not self.finished.is_set():
            # cancelled before any token AND before (or instead of) the
            # stream being consumed: tokens()'s settlement may never run,
            # so release the armed probe here — idempotent if it does
            self._router._release_probe(self._handle)

    def _fresh_sampler(self):
        from ..sampler import Sampler

        vocab, temp, topp, rng_state = self._sampler_spec
        return Sampler(vocab, temperature=temp, topp=topp, seed=rng_state)

    def tokens(self, timeout: float = 600.0):
        """Yield token ids to the terminal event, failing over between
        replicas underneath (see class docstring). Raises RequestError
        with the structured frame when the request ultimately fails."""
        try:
            yield from self._tokens(timeout)
        finally:
            if not self.finished.is_set():
                # consumer abandoned the stream mid-flight (stop sequence,
                # chat end-marker, client disconnect -> GeneratorExit): no
                # terminal verdict will ever run _on_result, so settle the
                # circuit accounting HERE. Tokens streamed = the replica
                # served fine (success: resets fails, closes a probe);
                # nothing streamed = no verdict — just release a probe so
                # it can't leak probing=True and unroute the replica.
                if self.emitted > 0:
                    self._router._on_result(self._handle, ok=True,
                                            retried=self.retries > 0)
                elif self._probe:
                    self._router._release_probe(self._handle)
                self.finished.set()

    def _tokens(self, timeout: float):
        while True:
            try:
                for tok in self._inner.tokens(timeout=timeout):
                    self.emitted += 1
                    yield tok
                self.finish_reason = self._inner.finish_reason
                self._router._on_result(self._handle, ok=True,
                                        retried=self.retries > 0)
                self.finished.set()
                return
            except RequestError as e:
                failed = self._handle
                # breaker attribution: deadline/queue-budget expiries are
                # the CLIENT's budget or the tier's load, not the
                # replica's health — they must not open a healthy
                # replica's circuit under pressure
                if e.code not in ("deadline", "queue_timeout"):
                    self._router._on_result(failed, ok=False)
                elif self._probe:
                    # the probe expired on the client's budget: no health
                    # verdict either way — return the circuit to half-open
                    # instead of leaking probing=True (which would unroute
                    # the replica until a manual reset)
                    self._router._release_probe(failed)
                if self.emitted > 0:
                    # mid-stream kill: the client already holds a partial
                    # stream — surface the structured frame, explicitly
                    # NON-retryable at this layer (a transparent replay
                    # would re-emit tokens the client already rendered)
                    with self._router._lock:  # counter discipline: every
                        # RouterStats mutation rides the router lock
                        self._router.stats.midstream_failures += 1
                    self._terminal_error()
                    raise RequestError(
                        e.code, f"{e} [{self.emitted} tokens already "
                                "streamed; not replayed — resubmit to "
                                "regenerate]", retryable=False) from e
                if (not e.retryable or self._cancelled
                        or self.retries >= self._router.retry_budget):
                    self._terminal_error()
                    raise
                if TRACER.enabled:
                    TRACER.event("failover", self.trace_id,
                                 replica=failed.id if failed else None,
                                 code=e.code, attempt=self.retries + 1)
                try:
                    self._router._place(
                        self, exclude=(failed.id,) if failed else (),
                        sampler=self._fresh_sampler())
                except Exception:
                    # no healthy replica to retry on: deliver the ORIGINAL
                    # structured frame (still retryable — the client may
                    # come back after recovery)
                    self._terminal_error()
                    raise e from None
                self.retries += 1
                with self._router._lock:
                    self._router.stats.retries += 1

    def _terminal_error(self) -> None:
        self.finish_reason = "error"
        self.finished.set()


class Router:
    """N supervised replicas behind one submit/stream surface. See the
    module docstring for the policy and failure semantics; see
    ``build_front_door`` for how the API server constructs one."""

    def __init__(self, engine_factory, *, replicas: int = 2,
                 policy: str = "cache_aware", retry_budget: int = 1,
                 circuit_threshold: int = 3, circuit_cooldown: float = 5.0,
                 handle_factories=None, kv_transfer: bool = False,
                 fill_min_tokens: int = 32, tiers=None, **sup_kwargs):
        # circuit_* name the ROUTER-level breaker so the supervisor's own
        # breaker_threshold still rides **sup_kwargs without a collision
        assert policy in POLICIES, policy
        from .stats import KVTransferStats

        # cross-replica KV block transfer (runtime/kv_transfer.py): when
        # armed, placement also decides FILLS (the placed replica fetches
        # a warmer sibling's blocks instead of re-prefilling) and runs
        # the prefill/decode disaggregation (prefill-tier replicas take
        # the prompt pass, decode-tier replicas admit already-seeded).
        # fill_min_tokens (default: one block) is the minimum cache
        # advantage worth a transfer.
        self._kv_transfer = bool(kv_transfer)
        self._fill_min = max(int(fill_min_tokens), 1)
        self.kvx = KVTransferStats(enabled=self._kv_transfer,
                                   tier="router",
                                   block_len=int(fill_min_tokens))
        # thread replicas' supervisors arm the prefix cache's transfer
        # warmup off the ROUTER's flag (the router owns it — one home,
        # so build_front_door cannot pass it twice)
        sup_kwargs = dict(sup_kwargs, kv_transfer=self._kv_transfer)
        if handle_factories is not None:
            # PROCESS/REMOTE tier: the caller supplies zero-arg factories
            # building RemoteReplicaHandles (build_front_door's
            # --replica-procs/--replica-hosts paths); engine_factory is
            # unused — each worker process owns its own engine
            replicas = len(handle_factories)
        assert replicas >= 1, replicas
        self.policy = policy
        self.retry_budget = max(int(retry_budget), 0)
        self.circuit_threshold = int(circuit_threshold)
        self.circuit_cooldown = float(circuit_cooldown)
        self.stats = RouterStats(replicas=replicas, policy=policy)
        # the tier-level deadline default: resolved ONCE per request in
        # submit() so a failover retry continues the ORIGINAL end-to-end
        # budget — per-scheduler minting would grant each attempt a fresh
        # window (x(1+retry_budget) the documented bound)
        self._request_deadline = sup_kwargs.get("request_deadline")
        self._lock = threading.RLock()  # placement + breaker + affinity
        self._rr = 0  # dlrace: guarded-by(self._lock)
        self._affinity: OrderedDict[str, int] = OrderedDict()  # dlrace: guarded-by(self._lock)
        self._closed = False
        # fleet-controller surface (runtime/fleet.py): `scaling` is the
        # in-flight scale direction ("scaling_up"/"scaling_down"/None)
        # the /readyz state report surfaces; `_spawn_factory(rid, tier)`
        # is stashed by build_front_door so the controller can mint
        # replicas the same way the constructor did; `_recent_prompts`
        # is the warm-fill material a fresh replica replays (string/
        # bool stores are GIL-atomic; the ring rides the router lock)
        self.scaling: str | None = None
        self._spawn_factory = None
        self._recent_prompts: deque = deque(maxlen=32)  # dlrace: guarded-by(self._lock)
        # lifetime counters of reaped replicas: fold-on-reap so /stats
        # totals never reset when the controller scales down (the same
        # carry contract restart()/respawn keep within one handle)
        self._reap_carry = {k: 0 for k in _COUNTER_KEYS}  # dlrace: guarded-by(self._lock)
        # replicas build sequentially: each EngineSupervisor warms its
        # executables before returning, and the XLA compile cache makes
        # replicas 1..N-1 reuse replica 0's compilations
        self.replicas: list[ReplicaHandle] = []
        try:
            if handle_factories is not None:
                for f in handle_factories:
                    self.replicas.append(f())
            else:
                for i in range(replicas):
                    self.replicas.append(
                        ReplicaHandle(i, engine_factory, sup_kwargs,
                                      tier=(tiers[i] if tiers
                                            else "mixed")))
        except BaseException:
            # replica K failed to build (e.g. the K+1-th KV cache/arena
            # OOMs): close the K already-running supervisors — their step
            # loop + watchdog threads and device memory must not outlive
            # the constructor that raised
            for h in self.replicas:
                try:
                    h.close(timeout=5.0)
                except Exception:  # noqa: BLE001 — best-effort unwind
                    pass
            raise

    # -- the supervisor surface the API server already speaks -------------

    @property
    def engine(self):
        """A shape/context template the handlers read (seq_len etc.);
        never step it directly without exclusive(). Prefers a replica
        with a LOCAL engine; an all-process tier serves the remote shape
        shim (_RemoteEngineInfo) instead."""
        for h in self.replicas:
            if getattr(h, "has_local_engine", True):
                return h.sup.engine
        return self.replicas[0].sup.engine

    @property
    def ready(self) -> bool:
        """/readyz contract: the SERVICE is ready while >= 1 replica can
        take traffic — single-replica failure must not unready the tier."""
        now = time.perf_counter()
        with self._lock:
            return any(self._routable(h, now) for h in self.replicas)

    @property
    def state(self) -> str:
        """Advisory tier state, CONSISTENT with ``ready``: "ready" iff
        some replica is actually routable (supervisor-ready, not drained,
        circuit allows) — a tier whose /readyz answers 503 must never
        report state="ready" back at the operator. A fleet-controller
        scale event in flight reports ``scaling_up``/``scaling_down``
        instead (the tier is still serving — capacity is changing, not
        health), and a replica marked ``reap`` is EXCLUDED from the
        unhealthy walk: draining-for-reap is the controller's decision,
        not a reason to call the tier draining."""
        now = time.perf_counter()
        scaling = self.scaling
        with self._lock:
            if any(self._routable(h, now) for h in self.replicas):
                return scaling or "ready"
            live = [h for h in self.replicas if not h.reap]
            if not live:
                return scaling or "draining"
            states = [h.state for h in live]
            for s in ("recovering", "draining"):
                if s in states:
                    return s
            if any(h.open_until > 0.0 for h in live):
                # router circuits hold traffic off supervisor-ready
                # replicas (the flapping case) — surface it, don't claim
                # the supervisors' "ready"
                return "degraded"
            if any(h.draining for h in live):
                # router-level drain leaves the supervisor READY
                return "draining"
            return states[0] if len(set(states)) == 1 else "degraded"

    def submit(self, prompt, max_tokens, sampler, eos_id=None,
               deadline=None, session=None, tenant=None,
               priority="normal") -> RouterRequest:
        """Place one request (PromptTooLong/QueueFull/EngineUnready
        surface here, exactly like the single-supervisor front door).
        ``sampler`` is consumed by the first attempt; its (temperature,
        topp, rng_state) snapshot — taken NOW, before any draw — rebuilds
        an identical sampler for each failover attempt."""
        if self._closed:
            raise SchedulerClosed("router is closed")
        if deadline is None and self._request_deadline:
            deadline = time.perf_counter() + self._request_deadline
        spec = (sampler.vocab_size, sampler.temperature, sampler.topp,
                sampler.rng_state)
        tid = TRACER.new_id() if TRACER.enabled else 0
        req = RouterRequest(self, [int(t) for t in prompt], max_tokens,
                            eos_id, deadline, spec, session, trace_id=tid,
                            tenant=tenant, priority=priority)
        with self._lock:
            # warm-fill material for fleet scale-ups (runtime/fleet.py):
            # a fresh replica replays the most recent prompts through
            # the PR-14 fill path so its cache starts warm
            self._recent_prompts.append(req._prompt)
        if self._kv_transfer:
            # prefill/decode disaggregation: run the prompt through a
            # prefill-tier replica first (publishes its blocks), so the
            # decode placement below admits already-seeded via a fill
            # from that donor. No prefill worker routable -> the mixed
            # path below serves unchanged.
            self._prefill_pass(req)
        self._place(req, exclude=(), sampler=sampler)
        return req

    def exclusive(self):
        """Borrow ONE routable replica's engine (Scheduler.exclusive via
        its supervisor) — the legacy whole-batch endpoint's path. Lowest
        routable id wins so repeat borrows hit a warm engine. PROCESS
        replicas are never borrowable (their engine lives across the
        process boundary) — an all-process tier refuses with a
        structured 503 instead."""
        now = time.perf_counter()
        with self._lock:
            targets = [h for h in self.replicas
                       if self._routable(h, now)
                       and getattr(h, "has_local_engine", True)]
        if not targets:
            raise EngineUnready("no_replica", 1.0)
        return targets[0].sup.exclusive()

    def drain(self, timeout: float = 30.0) -> bool:
        """Whole-service drain (SIGTERM shutdown path): every replica's
        SUPERVISOR drains (one-way — admissions refused) within the
        shared deadline."""
        end = time.perf_counter() + timeout
        ok = True
        for h in self.replicas:
            h.draining = True
            ok &= h.sup.drain(timeout=max(end - time.perf_counter(), 0.1))
        return ok

    def reset_breaker(self, replica: int | None = None) -> None:
        """Operator half-open for the ENGINE breaker (supervisor BROKEN)
        plus a router-circuit reset — per replica or all."""
        targets = (self.replicas if replica is None
                   else [self.replicas[replica]])
        with self._lock:
            for h in targets:
                h.fails = 0
                h.open_until = 0.0
                h.probing = False
        for h in targets:
            h.sup.reset_breaker()

    def close(self, timeout: float = 30.0) -> None:
        self._closed = True
        for h in self.replicas:
            h.close(timeout=timeout)

    def summary(self) -> dict:
        """The /stats payload: aggregated counters (cross-replica AND
        cross-generation — each supervisor already folds its dead
        generations in), merged latency percentiles over the live
        generations' request windows, the per-replica summaries, and the
        router block."""
        reps = [h.summary() for h in self.replicas]
        with self._lock:
            reap_carry = dict(self._reap_carry)
        out = {k: sum(r.get(k) or 0 for r in reps) + reap_carry[k]
               for k in _COUNTER_KEYS}
        ttfts, itls = [], []
        for h in self.replicas:
            for r in list(h.sup.stats.requests):
                if r.ttft_ms is not None:
                    ttfts.append(r.ttft_ms)
                if r.itl_ms is not None:
                    itls.append(r.itl_ms)
        rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
        out.update({
            "state": self.state,
            "scaling": self.scaling,
            "ttft_p50_ms": rnd(percentile(ttfts, 50)),
            "ttft_p99_ms": rnd(percentile(ttfts, 99)),
            "itl_p50_ms": rnd(percentile(itls, 50)),
            "itl_p99_ms": rnd(percentile(itls, 99)),
            "router": self.stats.summary(),
            "replicas": reps,
        })
        # the PARENT process's compile ledger (worker processes carry
        # their own in their per-replica summaries). No top-level hbm
        # block: thread replicas SHARE weight buffers — the per-replica
        # hbm blocks are each exact for their engine, and summing them
        # would multi-count the one weight allocation (docs/
        # observability.md "Device tier").
        from .profiler import COMPILES
        from .stats import KVTransferStats

        out["compiles"] = COMPILES.summary()
        # the transfer-plane aggregate: the router's own record (thread-
        # tier fills, disaggregation decisions, shadow fixes) + every
        # worker's wire record — present even with transfer off
        # (enabled=False: a tier must not lose the family to a flag)
        out["kv_transfer"] = KVTransferStats.merge(
            [self.kvx.summary()]
            + [r.get("kv_transfer") for r in reps
               if isinstance(r.get("kv_transfer"), dict)])
        return out

    def _retry_after(self) -> float:
        """Client hint while NO replica is routable: the soonest any
        replica's own hint says to come back."""
        return min((h.sup._retry_after() for h in self.replicas),
                   default=1.0)

    def profile(self, ms: float) -> dict | None:
        """Relay POST /admin/profile into REMOTE replica workers — all
        captures run CONCURRENTLY so every worker traces the same ms
        window. Returns {"rK": {dir, ms} | None} per remote replica, or
        None when this router has no remote replicas (thread replicas
        share the parent's jax runtime — the HTTP handler captures
        locally instead)."""
        remote = [h for h in self.replicas if hasattr(h, "client")]
        if not remote:
            return None
        out: dict = {}

        def run(h):
            out[f"r{h.id}"] = h.profile(ms)

        threads = [threading.Thread(target=run, args=(h,), daemon=True)
                   for h in remote]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=float(ms) / 1e3 + 60.0)
        return out

    # -- rolling restart ---------------------------------------------------

    def drain_replica(self, replica: int, timeout: float = 30.0) -> bool:
        """Take ONE replica out of rotation and finish its in-flight work
        (new traffic keeps flowing to its siblings). Follow with
        restart_replica (rebuild + re-admit) or undrain_replica."""
        with self._lock:
            self.stats.drains += 1
        return self.replicas[replica].drain(timeout=timeout)

    def restart_replica(self, replica: int, timeout: float = 30.0) -> None:
        h = self.replicas[replica]
        with self._lock:
            self.stats.restarts += 1
        h.restart(timeout=timeout)
        with self._lock:
            # reset the router circuit AFTER the rebuild, under the lock:
            # a concurrent _on_result for a request that died with the old
            # generation must not interleave with restart's field clears
            # and leave the circuit half-cleared against the fresh engine
            h.fails = 0
            h.open_until = 0.0
            h.probing = False

    def undrain_replica(self, replica: int) -> None:
        self.replicas[replica].undrain()

    def rolling_restart(self, timeout: float = 30.0) -> bool:
        """The runbook recipe (docs/operations.md): drain + restart each
        replica IN TURN — at most one replica is ever out of rotation, so
        the service stays ready and no request is failed. Returns False
        if any drain timed out (its stragglers got shutdown frames)."""
        ok = True
        for h in self.replicas:
            ok &= self.drain_replica(h.id, timeout=timeout)
            self.restart_replica(h.id, timeout=timeout)
        return ok

    # -- fleet autoscaling surface (runtime/fleet.py) ----------------------

    def add_replica(self, handle) -> None:
        """Enter an already-built (and therefore already-warm: every
        handle constructor blocks on its warmup/handshake) replica into
        rotation. The fleet controller builds the handle OFF the router
        lock — possibly minutes of spawn + compile — and this entry is
        one guarded list append, so placement never waits on a spawn."""
        with self._lock:
            assert all(h.id != handle.id for h in self.replicas), handle.id
            self.replicas.append(handle)
            self.stats.replicas = len(self.replicas)

    def reap_replica(self, replica: int, timeout: float = 30.0) -> None:
        """Remove ONE drained replica from rotation and close it (the
        controller's scale-down tail: mark ``reap`` → drain → here).
        Close-before-remove: the handle's close() retires its monitor
        thread (so a respawn can never resurrect a reaped worker), and
        only then does the list forget it."""
        with self._lock:
            matches = [h for h in self.replicas if h.id == replica]
        if not matches:
            return
        h = matches[0]
        h.reap = True
        h.close(timeout=timeout)
        final = h.summary()  # close() is final: no writer outlives it
        with self._lock:
            for k in _COUNTER_KEYS:
                self._reap_carry[k] += final.get(k) or 0
            self.replicas = [x for x in self.replicas if x.id != replica]
            self.stats.replicas = len(self.replicas)
            # drop stale stickiness onto the dead id: those sessions
            # re-place fresh (losing affinity costs one cold placement)
            for k in [k for k, v in self._affinity.items() if v == replica]:
                del self._affinity[k]

    # -- placement ---------------------------------------------------------

    def _routable(self, h: ReplicaHandle, now: float) -> bool:
        """May REQUEST traffic go to h right now? Supervisor-ready AND
        not draining AND the router circuit allows it (closed, or
        half-open with no probe already in flight). Prefill-TIER
        replicas are never request-routable: they exist to run prefill
        passes and donate blocks (runtime/kv_transfer.py) — a tier of
        only prefill workers is therefore correctly unready. Caller
        holds the lock."""
        if getattr(h, "tier", "mixed") == "prefill":
            return False
        if h.reap:
            # marked for fleet scale-down: out of rotation from the mark
            # (its drain may not have started yet) — a reaped replica
            # must never take the request that blocks its own reap
            return False
        if h.draining or h.sup is None or not h.sup.ready:
            return False
        if h.open_until <= 0.0:
            return True
        if now < h.open_until:
            return False          # circuit open: cooling down
        return not h.probing      # half-open: one probe at a time

    def _pick(self, prompt, session,
              exclude) -> tuple[ReplicaHandle, str, bool]:
        """Choose a replica (plus the reason, for stats, and whether this
        pick IS the replica's half-open probe). Raises EngineUnready when
        nothing is routable."""
        if self.policy == "cache_aware":
            # the radix walks are O(prompt) and lock-free-safe (match_len
            # is a read-only peek; transiently stale is fine for routing)
            # — do them BEFORE taking the placement lock so long prompts
            # can't serialize every concurrent submit and /readyz probe
            match = {h.id: h.match_len(prompt) for h in self.replicas
                     if h.id not in exclude}
        now = time.perf_counter()
        with self._lock:
            cands = [h for h in self.replicas
                     if h.id not in exclude and self._routable(h, now)]
            if not cands:
                self.stats.no_replica_rejections += 1
                raise EngineUnready("no_replica", self._retry_after())
            if session is not None:
                rid = self._affinity.get(session)
                hit = next((h for h in cands if h.id == rid), None)
                if hit is not None:
                    self._affinity.move_to_end(session)
                    return (hit, "affinity", self._mark_probe(hit, now))
            if self.policy == "round_robin":
                h = cands[self._rr % len(cands)]
                self._rr += 1
                return (h, "fallback", self._mark_probe(h, now))
            if self.policy == "cache_aware":
                best = max(match.get(h.id, 0) for h in cands)
                if best > 0:
                    warm = [h for h in cands if match.get(h.id, 0) == best]
                    h = min(warm, key=lambda h: (h.load(), h.id))
                    return (h, "cache_hit", self._mark_probe(h, now))
            # least-loaded fallback (and the least_loaded policy itself)
            h = min(cands, key=lambda h: (h.load(), h.id))
            return (h, "fallback", self._mark_probe(h, now))

    # -- KV block transfer: fills + disaggregation (kv_transfer.py) --------

    def _pick_donor(self, target, prompt: list[int]):
        """The fill decision: the sibling whose cache (real radix tree
        for thread replicas, shadow index for process replicas) leads
        the TARGET's by at least one whole block's worth of tokens.
        Returns (donor_handle, donor_match_tokens) or None. Lock-free
        peeks, same discipline as cache-aware _pick — a transiently
        stale answer costs one useless fetch (which degrades to a
        re-prefill), never correctness."""
        have = target.match_len(prompt)
        best, best_n = None, have + self._fill_min - 1
        for h in self.replicas:
            if h.id == target.id or h.draining or h.sup is None:
                continue
            if not h.sup.ready:
                continue  # a dead/respawning donor cannot serve a fetch
            n = h.match_len(prompt)
            if n > best_n:
                best, best_n = h, n
        return (best, best_n) if best is not None else None

    def _prefill_pass(self, req: "RouterRequest") -> None:
        """Run req's prompt through a prefill-tier replica with
        max_tokens=0: the full prompt prefills there (big chunks, no
        decode rows to interfere with) and its whole blocks publish at
        prefill-finish — the donor the decode placement's fill then
        draws from. Every failure shape (no routable prefill worker,
        door refusal, worker death) falls back to the unified mixed
        path; the pass must never fail the request."""
        if len(req._prompt) <= self._fill_min:
            return  # nothing a whole-block handoff could carry
        now = time.perf_counter()
        with self._lock:
            cands = [h for h in self.replicas
                     if getattr(h, "tier", "mixed") == "prefill"
                     and not h.draining and h.sup is not None
                     and (h.open_until <= 0.0 or now >= h.open_until)]
        cands = [h for h in cands if h.sup.ready]
        if not cands:
            if any(getattr(h, "tier", "mixed") == "prefill"
                   for h in self.replicas):
                with self._lock:
                    self.kvx.prefill_pass_fallbacks += 1
            return
        h = min(cands, key=lambda h: (h.load(), h.id))
        t0 = time.perf_counter()
        try:
            inner = h.sup.submit(req._prompt, 0, req._fresh_sampler(),
                                 eos_id=req._eos_id,
                                 deadline=req._deadline,
                                 trace_id=req.trace_id)
            for _ in inner.tokens(timeout=60.0):
                pass  # max_tokens=0: prefill only, nothing streams
            h.note_routed(req._prompt)
            with self._lock:
                self.kvx.prefill_passes += 1
            if TRACER.enabled:
                TRACER.event("route", req.trace_id, replica=h.id,
                             reason="prefill_pass",
                             ms=round((time.perf_counter() - t0) * 1e3,
                                      3))
        except Exception:  # noqa: BLE001 — degrade to the mixed path
            with self._lock:
                self.kvx.prefill_pass_fallbacks += 1

    def _arrange_fill(self, h, req: "RouterRequest", sampler_unused=None):
        """Pre-submit fill work for a placement on h. Returns the
        ``fill`` tuple to ride a REMOTE submit frame (the worker fetches
        donor->self over the wire), or None. Thread-tier fills run right
        here (donor and target share this process)."""
        donor = self._pick_donor(h, req._prompt)
        if donor is None:
            return None
        dh, dn = donor
        remote_t = hasattr(h, "client")
        remote_d = hasattr(dh, "client")
        if remote_t and remote_d:
            addr = dh.client.addr
            return (addr[0], addr[1], dn, dh)
        if not remote_t and not remote_d:
            from .kv_transfer import local_fill

            local_fill(dh.sup, h.sup, req._prompt, stats=self.kvx,
                       trace_id=req.trace_id, donor_id=dh.id)
            # thread replicas peek the REAL tree — no shadow to go stale
        return None

    def _note_fill_verdict(self, donor_handle, req: "RouterRequest",
                           inner, expected: int) -> None:
        """The shadow-staleness fix: the worker's ACCEPT echoed what the
        donor's RMSG_BLOCK_QUERY actually answered. An answer SHORT of
        what the shadow promised means donor-side eviction — drop the
        stale entries so they stop attracting placements and fetches of
        dead blocks (-1 = no verdict: donor unreachable, maybe
        mid-respawn — its monitor clears the shadow on its own)."""
        ans = getattr(inner, "fill_answer", -1)
        if ans < 0 or ans >= expected:
            return
        shadow = getattr(donor_handle, "shadow", None)
        if shadow is not None and shadow.truncate(req._prompt, ans):
            with self._lock:
                self.kvx.shadow_truncates += 1

    def _mark_probe(self, h: ReplicaHandle, now: float) -> bool:
        """Arm the half-open probe if this pick crossed the cooldown.
        Returns True iff THIS pick is the probe (the caller must release
        it on a door refusal or a no-verdict expiry — see _release_probe)."""
        if h.open_until > 0.0 and now >= h.open_until:
            h.probing = True
            self.stats.breaker_probes += 1
            return True
        return False

    def _release_probe(self, h: ReplicaHandle | None) -> None:
        """A probe attempt ended with NO health verdict (refused at the
        door, or expired on the client's own deadline): re-open the
        half-open window instead of leaking probing=True, which would
        unroute the replica until a manual breaker reset."""
        if h is None:
            return
        with self._lock:
            h.probing = False

    def _place(self, req: RouterRequest, exclude: tuple, sampler) -> None:
        """Pick + submit, walking past replicas that refuse at the door
        (went unready/closed between pick and submit, or queue-full) —
        a door refusal is a placement miss, not a breaker-worthy request
        failure. Re-raises the last refusal when every replica refused."""
        tried = list(exclude)
        last_exc: Exception | None = None
        while True:
            try:
                h, reason, probe = self._pick(req._prompt, req._session,
                                              tried)
            except EngineUnready:
                if isinstance(last_exc, (QueueFull, EngineUnready)):
                    raise last_exc from None
                raise
            # cache FILL on miss (runtime/kv_transfer.py): when a warmer
            # sibling exists, thread tiers import its blocks right here;
            # process tiers ship the donor's coordinates on the submit
            # frame and the worker pulls donor->self directly
            fill = (self._arrange_fill(h, req) if self._kv_transfer
                    else None)
            try:
                if fill is not None:
                    d_host, d_port, d_expected, d_handle = fill
                    inner = h.sup.submit(req._prompt, req._max_tokens,
                                         sampler, eos_id=req._eos_id,
                                         deadline=req._deadline,
                                         trace_id=req.trace_id,
                                         fill=(d_host, d_port,
                                               d_expected, d_handle.id),
                                         tenant=req._tenant,
                                         priority=req._priority)
                    self._note_fill_verdict(d_handle, req, inner,
                                            d_expected)
                else:
                    inner = h.sup.submit(req._prompt, req._max_tokens,
                                         sampler, eos_id=req._eos_id,
                                         deadline=req._deadline,
                                         trace_id=req.trace_id,
                                         tenant=req._tenant,
                                         priority=req._priority)
            except (EngineUnready, QueueFull, SchedulerClosed) as e:
                if probe:
                    self._release_probe(h)
                tried.append(h.id)
                last_exc = e
                continue
            except BaseException:
                # anything else submit raises (PromptTooLong, bad-args
                # ValueError) is the CALLER's error, not the replica's —
                # propagate it, but never leak an armed probe with it
                if probe:
                    self._release_probe(h)
                raise
            # feed the placement signal for FUTURE picks: in-process
            # replicas no-op (match_len peeks their real radix tree); a
            # process replica records the routed prompt in its shadow
            # index (cache-aware placement without an RPC)
            h.note_routed(req._prompt)
            if TRACER.enabled:
                TRACER.event("route", req.trace_id, replica=h.id,
                             reason=reason, attempt=req.retries,
                             probe=probe)
            with self._lock:
                req._inner, req._handle = inner, h
                req._probe = probe
                self.stats.routed += 1
                if reason == "cache_hit":
                    self.stats.routed_cache_hit += 1
                elif reason == "affinity":
                    self.stats.routed_affinity += 1
                else:
                    self.stats.routed_fallback += 1
                if req._session is not None:
                    self._affinity[req._session] = h.id
                    self._affinity.move_to_end(req._session)
                    while len(self._affinity) > _AFFINITY_CAP:
                        self._affinity.popitem(last=False)
            if req._cancelled:
                inner.cancel()
            return

    def _on_result(self, h: ReplicaHandle | None, ok: bool,
                   retried: bool = False) -> None:
        """Terminal accounting for one attempt on replica h: drives the
        router circuit (consecutive request failures open it; any success
        — including the half-open probe — closes it)."""
        if h is None:
            return
        with self._lock:
            if ok:
                was_open = h.open_until > 0.0
                h.fails = 0
                h.open_until = 0.0
                h.probing = False
                if retried:
                    self.stats.failovers_ok += 1
                if was_open and TRACER.enabled:
                    TRACER.event("circuit", 0, scope="router",
                                 replica=h.id, state="closed")
                return
            h.fails += 1
            now = time.perf_counter()
            reopening = h.probing and h.open_until > 0.0
            h.probing = False
            if h.fails >= self.circuit_threshold or reopening:
                if h.open_until <= 0.0 or reopening:
                    self.stats.breaker_trips += 1
                    if TRACER.enabled:
                        TRACER.event("circuit", 0, scope="router",
                                     replica=h.id, state="open",
                                     fails=h.fails)
                h.open_until = now + self.circuit_cooldown


def build_front_door(engine, *, serve_batch: int, serve_chunk: int = 0,
                     queue_depth: int = 0, request_deadline: float = 0.0,
                     stall_timeout: float = 0.0, prefix_cache: bool = False,
                     prefix_blocks: int = 0, prefix_block_len: int = 32,
                     replicas: int = 1, retry_budget: int = 1,
                     route_policy: str = "cache_aware",
                     replica_procs: int = 0, replica_hosts=None,
                     worker_config: dict | None = None,
                     workdir: str | None = None,
                     worker_io_timeout: float = 30.0,
                     spawn_timeout: float = 300.0,
                     slo_ttft_ms: float | None = None,
                     slo_itl_ms: float | None = None,
                     draft: str | None = None, draft_len: int = 0,
                     draft_vocab: int | None = None,
                     kv_transfer: bool = False, tiers=None,
                     tenant_ledger=None):
    """The ONE constructor of the serving front door, shared by every
    deployment shape (the engine-owner logic that used to live in
    apps/api_server.ApiState.scheduler):

      * replicas == 1 (default): an ``EngineSupervisor`` — the exact
        PR-3 object.
      * replicas > 1: a ``Router`` over N THREAD replicas, each its own
        supervisor over ``engine``'s SHARED weight buffers.
      * replica_procs > 0: a ``Router`` over N locally-SPAWNED worker
        PROCESSES (runtime/replica_worker.py), each loading its own
        weights from ``worker_config`` — the real fault boundary: a
        SIGKILL/OOM/segfault costs one process, and the handle respawns
        it under supervision.
      * replica_hosts: a ``Router`` over pre-started workers at
        ``[(host, port), ...]`` — the cross-host tier (no spawn
        supervision; each host's operator owns its worker's lifetime).

    The HTTP handlers serve all four through the identical duck-typed
    surface.

    ``tenant_ledger`` (runtime/fleet.TenantLedger) arms weighted-fair
    admission: every LOCAL scheduler generation gets a fresh WFQueue
    over this one ledger (budgets survive rebuilds), and process
    workers arm their own worker-side WFQ from the budget spec shipped
    in ``worker_config`` (fairness must hold in the queue where waiting
    actually happens). Router shapes also stash ``_spawn_factory`` so
    the fleet controller (runtime/fleet.py) can mint replicas exactly
    the way this constructor did."""
    from .engine import Engine

    if replica_procs or replica_hosts:
        import os
        import tempfile

        from .replica_worker import WorkerProc

        factories = []
        if replica_procs:
            assert worker_config is not None, \
                "replica_procs needs a worker_config dict"
            workdir = workdir or tempfile.mkdtemp(prefix="dllama-replicas-")
            os.makedirs(workdir, exist_ok=True)

            def spawn_factory(i, tier):
                # the fleet controller mints replica i EXACTLY the way
                # the loop below does (fresh cfg, fault_key=r{i}, same
                # workdir/timeouts) — scale-ups and boot replicas are
                # indistinguishable to chaos keys and respawn folds
                cfg = dict(worker_config)
                cfg["fault_key"] = f"r{i}"
                cfg["kv_transfer"] = bool(kv_transfer)
                cfg["tier"] = tier
                proc = WorkerProc(i, cfg, workdir=workdir,
                                  io_timeout=worker_io_timeout)
                return RemoteReplicaHandle(
                    i, proc=proc, block_len=prefix_block_len,
                    io_timeout=worker_io_timeout,
                    spawn_timeout=spawn_timeout,
                    respawn_timeout=spawn_timeout, tier=tier)

            for i in range(int(replica_procs)):
                # replica identity at the key-filtered fault sites rides
                # into the worker so DLLAMA_FAULTS key=rK follows replica
                # K across respawns, same as the thread tier; the
                # per-replica disaggregation role + transfer arming
                # (runtime/kv_transfer.py) are stamped the same way
                tier = tiers[i] if tiers else "mixed"
                factories.append(lambda i=i, tier=tier:
                                 spawn_factory(i, tier))
        else:
            spawn_factory = None
            for i, (host, port) in enumerate(replica_hosts):
                def make(i=i, host=host, port=port):
                    return RemoteReplicaHandle(
                        i, address=(host, port),
                        block_len=prefix_block_len,
                        io_timeout=worker_io_timeout)
                factories.append(make)
        router = Router(None, policy=route_policy,
                        retry_budget=retry_budget,
                        handle_factories=factories,
                        kv_transfer=kv_transfer,
                        fill_min_tokens=prefix_block_len,
                        request_deadline=request_deadline or None)
        router._spawn_factory = spawn_factory
        return router

    def engine_factory():
        # the launched engine's mesh carries over (tp serving — the
        # vocab-sharded path; the api door restricts WHICH meshes reach
        # here). Weights are the template's buffers either way; a mesh
        # template's spec already folded kv-head replication, so the
        # rebuild never re-replicates.
        return Engine(engine.spec, engine.params, engine.mesh,
                      batch=serve_batch,
                      max_seq_len=engine.seq_len,
                      compute_dtype=engine.compute_dtype,
                      cache_dtype=engine.cache_dtype,
                      use_pallas=engine.use_pallas,
                      pallas_interpret=engine.pallas_interpret,
                      activation_q80=engine.activation_q80,
                      q80_collectives=engine.q80_collectives,
                      shard_vocab=engine.shard_vocab,  # the template's
                      # RESOLVED decision (auto already applied): a
                      # rebuild must never flip the operator's choice
                      prefill_chunk=engine.prefill_chunk)

    n_blocks = 0
    if prefix_cache:
        n_blocks = prefix_blocks or max(
            2 * serve_batch * engine.seq_len // prefix_block_len, 1)
    fair_queue_factory = None
    if tenant_ledger is not None:
        from .fleet import WFQueue

        fair_queue_factory = lambda: WFQueue(tenant_ledger)  # noqa: E731
    sup_kwargs = dict(
        chunk=serve_chunk or None,
        max_queue=queue_depth or 4 * serve_batch,
        request_deadline=request_deadline or None,
        stall_timeout=stall_timeout or 10.0,
        prefix_blocks=n_blocks, prefix_block_len=prefix_block_len,
        slo_ttft_ms=slo_ttft_ms, slo_itl_ms=slo_itl_ms,
        draft=draft, draft_len=draft_len, draft_vocab=draft_vocab,
        fair_queue_factory=fair_queue_factory)
    if replicas <= 1:
        return EngineSupervisor(engine_factory, kv_transfer=kv_transfer,
                                **sup_kwargs)
    router = Router(engine_factory, replicas=replicas,
                    policy=route_policy, retry_budget=retry_budget,
                    kv_transfer=kv_transfer,
                    fill_min_tokens=prefix_block_len, tiers=tiers,
                    **sup_kwargs)
    # the fleet controller scales THREAD replicas too (tests drive the
    # loop without subprocesses): a scale-up builds a fresh supervised
    # replica over the same shared weight buffers
    router._spawn_factory = lambda rid, tier: ReplicaHandle(
        rid, engine_factory, dict(sup_kwargs, kv_transfer=kv_transfer),
        tier=tier)
    return router
