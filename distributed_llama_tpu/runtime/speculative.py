"""Prompt-lookup speculative decoding — draft from the context's own
n-grams, verify a whole draft in one forward.

Net-new vs the reference (strictly one token per forward,
ref: src/apps/dllama/dllama.cpp:43-81), and a TPU-shaped win: decode is
weight-READ-bound, so a verify forward over t = 1 + k tokens costs almost
the same HBM time as t = 1 — every accepted draft token is nearly free.
The draft source is the context itself (the "prompt lookup" scheme: find
the longest suffix n-gram that occurred earlier, propose its continuation)
— no draft model, no extra weights, and exact greedy equivalence: emitted
tokens are always the model's own argmaxes, drafts only decide how many
positions one forward can confirm.

Acceptance is content-dependent: repetitive text (code, extraction,
summaries quoting the source) accepts most drafts; high-entropy text
degrades gracefully to ~1 token/forward plus the (cheap) failed drafts.
"""

from __future__ import annotations

import numpy as np


def find_draft(
    history: np.ndarray,   # 1-D int32 token ids: prompt + emitted so far
    draft_len: int,
    max_ngram: int = 3,
    min_ngram: int = 1,
) -> list[int]:
    """Longest-suffix n-gram match: for n = max_ngram..min_ngram, find an
    earlier occurrence of the trailing n tokens and return up to draft_len
    tokens that followed it. [] when nothing matches.

    Among the occurrences of the winning n-gram, the MOST RECENT one that
    still has a full draft_len continuation wins (recency bias), falling
    back to the most recent occurrence outright. Looping text — exactly
    where lookup decoding pays — otherwise keeps matching a position a
    token or two from the end of history, yielding truncated length-1
    drafts and ~1 token/forward where a slightly older match drafts the
    whole cycle."""
    h = np.asarray(history)
    ln = h.shape[0]
    for n in range(max_ngram, min_ngram - 1, -1):
        if ln < n + 1:
            continue
        pat = h[ln - n:]
        win = np.lib.stride_tricks.sliding_window_view(h, n)
        hits = np.nonzero((win == pat).all(axis=1))[0]
        hits = hits[hits < ln - n]  # exclude the suffix itself
        if hits.size:
            full = hits[hits + n + draft_len <= ln]
            j = int(full[-1] if full.size else hits[-1]) + n
            return h[j: j + draft_len].tolist()
    return []


def target_dist(logits: np.ndarray, temperature: float, topp: float,
                vocab_size: int) -> np.ndarray:
    """The host Sampler's per-token sampling distribution, materialized:
    temperature softmax, then the reference's top-p nucleus (cutoff
    pre-filter, stable-descending sort, truncate at cumulative > topp
    INCLUDING the crossing element, renormalize — ref:
    src/tokenizer.cpp:265-306 and sampler.py:_sample_topp). Sampling from
    this vector is distribution-identical to Sampler.sample on the same
    logits, which is what makes rejection resampling exact."""
    from ..sampler import topp_nucleus

    logits = np.asarray(logits, np.float32).reshape(-1)[:vocab_size]
    x = logits / temperature
    x = np.exp(x - x.max())
    probs = x / x.sum()
    if topp <= 0 or topp >= 1:
        return probs.astype(np.float64)
    order, cum, last = topp_nucleus(probs, topp)
    out = np.zeros(probs.shape[0], np.float64)
    out[order[: last + 1]] = probs[order[: last + 1]] / cum[last]
    return out


def draw(p: np.ndarray, u: float) -> int:
    """Sample index ~ p given one uniform u in [0, 1)."""
    cdf = np.cumsum(p)
    idx = int(np.searchsorted(cdf, u * cdf[-1], side="right"))
    return min(idx, len(p) - 1)


def accept_or_resample(p: np.ndarray, d: int, u_accept: float,
                       u_res: float) -> tuple[bool, int]:
    """One rejection-resampling step against a DETERMINISTIC draft token d
    (prompt-lookup drafts are point masses, q = onehot(d), so the usual
    min(1, p/q) acceptance reduces to p(d)): accept d with probability
    p(d); on reject, sample from the residual (p with d zeroed,
    renormalized). Marginal over (u_accept, u_res) is exactly p — the
    distribution-exactness the sampled lookup mode rests on.
    Returns (accepted, token)."""
    pd = float(p[d])
    if u_accept < pd:
        return True, d
    r = p.copy()
    r[d] = 0.0
    s = r.sum()
    if s <= 0.0:  # p was a point mass at d — rejection is impossible
        return True, d
    return False, draw(r, u_res)


def accept_or_resample_q(p: np.ndarray, q: np.ndarray, d: int,
                         u_accept: float, u_res: float) -> tuple[bool, int]:
    """The GENERAL rejection-resampling step (Leviathan/Chen speculative
    sampling): the draft token d was SAMPLED from a non-point-mass
    proposal distribution q (a real draft model's own softmax — the
    self-draft's truncated-depth head, or a separate draft ``.m``), and
    the target distribution is p. Accept d with probability
    min(1, p(d)/q(d)); on reject, sample from the normalized residual
    max(p - q, 0). Marginalizing over (d ~ q, u_accept, u_res)
    reproduces p EXACTLY — the point-mass helper above is the q =
    onehot(d) special case. Returns (accepted, token)."""
    pd, qd = float(p[d]), float(q[d])
    # qd <= 0 means d cannot have been drawn from this q — certain
    # reject (min(1, p/q) is ill-defined; the residual stays exact)
    if qd > 0.0 and u_accept < min(1.0, pd / qd):
        return True, d
    r = np.maximum(p - q, 0.0)
    s = r.sum()
    if s <= 0.0:
        # p <= q pointwise means p == q (both sum to 1): the accept
        # probability was exactly p(d)/q(d) = 1 — rejection is impossible
        return True, d
    return False, draw(r / s, u_res)


def count_accepted(draft: list[int], greedy: np.ndarray) -> int:
    """How many leading draft tokens the verify forward confirmed: greedy[i]
    is the model's argmax AFTER segment position i, so draft token i (fed at
    segment position i+1) is correct iff it equals greedy[i]."""
    m = 0
    while m < len(draft) and int(greedy[m]) == draft[m]:
        m += 1
    return m
