"""Continuous batching: a slot-based KV scheduler over the batched Engine.

Iteration-level scheduling in the Orca style (Yu et al., OSDI '22) with the
slot-reuse KV management popularized by vLLM (Kwon et al., SOSP '23),
adapted to the fixed-shape compilation discipline of this engine: the KV
cache is ONE batch=B allocation whose rows ("slots") are leased to requests,
requests join and leave the running decode batch every step, and every
device program is one of exactly two executables —

  * ``slot_prefill_chunk_C`` — a (B, C) segment forward writing each
    prefilling row's chunk at its own offset (tail chunks pad to C, so C is
    the only prefill compilation key),
  * ``slot_decode_step``     — a (B, 1) decode step at per-row positions.

Rows not participating in a call are gated off by passing position ==
seq_len: their cache writes drop out of bounds (models/transformer's
drop-mode scatter) and their logits are never read. This replaces the
static batch endpoint's regime — all prompts in one request, serial
prefill, every slot held until the slowest row drains — with
iteration-level admission: a finished row's slot is handed to the next
queued request IMMEDIATELY (no cache zeroing or reallocation; the new
request overwrites each position before any of its queries can attend it,
the same invariant decode overruns rely on everywhere in the engine).

Chunked-prefill interleave: each scheduler iteration runs at most ONE
prefill-chunk forward and ONE decode step, so a newly admitted prompt adds
at most one chunk's latency to in-flight requests' inter-token gap while
its own time-to-first-token stays bounded by ceil(len/C) iterations.

Per-slot sampling state is the request's own host ``Sampler`` (its
xorshift stream IS the per-slot RNG state); greedy requests therefore
yield EXACTLY the tokens of a sequential ``Engine.generate`` run
(tests/test_scheduler.py pins token-identical parity, including mid-decode
joins and early-finish slot handoffs).

Cross-request KV reuse: with a ``runtime/prefix_cache.PrefixCache``
attached, ``_admit`` looks up the longest cached token prefix, seeds the
slot's cache rows from arena blocks (``Engine.slot_seed_prefix``) and
prefills only the uncached suffix; a slot publishes its PROMPT's K/V
back into the radix tree when the prompt finishes prefilling (prefill-
written blocks only — decode-step K/V is not guaranteed bitwise-equal
to a cold prefill's, and publishing it would void the exact-parity
guarantee). The matched path stays PINNED for the slot's lifetime so
eviction can never free a block an in-flight slot came from, and the
whole tree is invalidated whenever the engine generation dies
(``_abort_all`` — the arena dies with the engine).

Thread model: ``submit()`` is thread-safe; the step loop runs either on
the ``start()`` background thread or synchronously via ``step()`` (tests,
the bench). ``exclusive()`` drains all in-flight work and lends the
batched engine to a legacy whole-batch caller (apps/api_server's
/v1/batch/completions), so one process never holds two live batched
caches.
"""

from __future__ import annotations

import contextlib
import queue as _queue
import threading
import time
from collections import deque
from typing import Iterator

import numpy as np

from .faults import FAULTS
from .profiler import PROFILER
from .stats import RequestStats, ServeStats
from .trace import TRACER


class PromptTooLong(ValueError):
    """Prompt does not fit the engine's context window."""


class QueueFull(RuntimeError):
    """Admission refused: the request queue is at its configured bound.
    Overload must surface as a FAST structured rejection (HTTP 429 with
    Retry-After at the API layer), never as unbounded queue latency."""

    def __init__(self, depth: int, bound: int, retry_after: float = 1.0):
        super().__init__(f"queue full ({depth} waiting, bound {bound})")
        self.retry_after = retry_after


class SchedulerClosed(RuntimeError):
    """Submission after close(): the step loop is gone, so queueing the
    request would hang its waiter forever."""


class RequestError(RuntimeError):
    """Structured terminal failure of one request — the payload every
    error frame carries: a machine-readable ``code`` plus whether a
    client retry is expected to succeed (``retryable``). Raised out of
    ``ServeRequest.tokens()`` so stream consumers see one exception type
    with the frame attached."""

    def __init__(self, code: str, message: str, retryable: bool = True):
        super().__init__(message)
        self.code = code
        self.retryable = retryable

    def frame(self) -> dict:
        return {"code": self.code, "message": str(self),
                "retryable": self.retryable}


class ServeRequest:
    """One submitted generation request and its event stream.

    The scheduler pushes ``("token", id)`` events as the request's slot
    produces them, then exactly one terminal event: ``("done", reason)``
    with reason in {"stop", "length", "cancelled"} or ``("error", msg)``.
    ``tokens()`` iterates the stream; ``cancel()`` asks the scheduler to
    retire the request at its next iteration (the consumer-side stop for
    text-level stop sequences and client disconnects)."""

    def __init__(self, rid: int, prompt: list[int], max_tokens: int,
                 sampler, stop_ids: set[int],
                 deadline: float | None = None, trace_id: int = 0,
                 tenant: str | None = None, priority: str = "normal"):
        self.id = rid
        # multi-tenant fairness tags (runtime/fleet.py): which tenant's
        # WFQ share + token budget this request rides, and its priority
        # band — inert under the plain FIFO deque, read by WFQueue
        self.tenant = tenant
        self.priority = priority
        # flight-recorder span id (runtime/trace.py): minted ONCE per
        # client request at the front door and shared by every retry
        # attempt (and, across the process boundary, by the worker's
        # events) — 0 means untraced
        self.trace_id = trace_id
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.sampler = sampler
        self.stop_ids = stop_ids
        # absolute time.perf_counter() bound: past it the request is
        # failed with a structured "deadline" frame wherever it sits
        # (queued or mid-decode) — overload degrades to fast rejections
        self.deadline = deadline
        self.events: _queue.Queue = _queue.Queue()
        self.finished = threading.Event()
        self.finish_reason: str | None = None
        self.stats = RequestStats(n_prompt=len(prompt))
        self._cancelled = False
        self._terminal_lock = threading.Lock()
        self._terminal = False

    def _claim_terminal(self) -> bool:
        """Exactly-once guard for the terminal event: concurrent failure
        paths (a dying generation's _abort_all racing the supervisor's
        failed-during-submit fallback, close() racing a wedged step) may
        BOTH try to finish a request; only the first claim delivers the
        event and counts in the stats."""
        with self._terminal_lock:
            if self._terminal:
                return False
            self._terminal = True
            return True

    def cancel(self) -> None:
        self._cancelled = True

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def tokens(self, timeout: float = 600.0) -> Iterator[int]:
        """Yield generated token ids until the terminal event. `timeout`
        bounds the wait per event so a dead scheduler thread surfaces as
        an error instead of a hung consumer. Error frames raise
        ``RequestError`` with the structured payload attached."""
        while True:
            kind, val = self.events.get(timeout=timeout)
            if kind == "token":
                yield val
            elif kind == "done":
                return
            elif isinstance(val, dict):
                raise RequestError(val.get("code", "error"),
                                   val.get("message", "scheduler error"),
                                   val.get("retryable", True))
            else:  # legacy bare-string frame
                raise RequestError("error", f"scheduler error: {val}")


def chunk_ladder(chunk: int, rungs: int = 4) -> list[int]:
    """The adaptive admission policy's FIXED chunk-width menu: descending
    halvings of the configured width, at most `rungs` entries, floor 1.
    A ladder (not a continuum) keeps the prefill compile-key set bounded
    and knowable up front — ``Scheduler.warmup()`` compiles every rung,
    so an adaptive run mints ZERO post-warmup keys and ``--freeze-
    compiles`` stays green while the width moves."""
    ladder = [int(chunk)]
    while len(ladder) < rungs and ladder[-1] > 1:
        ladder.append(max(ladder[-1] // 2, 1))
    return ladder


class AdmissionPolicy:
    """SLO-aware self-tuning admission: trade per-iteration chunked-
    prefill width against decode occupancy (Orca's iteration-level knob)
    using the LIVE step timeline, entirely host-side.

    A scheduler iteration with both prefill and decode rows costs one
    (B, C) chunk forward plus one (B, 1) decode forward, and every
    decoding row's inter-token gap IS that iteration's wall time — so the
    chunk width C is the admission policy's one real lever: wide chunks
    finish prompts in few iterations (good TTFT) but stretch every
    running stream's gap (bad ITL); narrow chunks the reverse. The policy
    walks a fixed width ladder (``chunk_ladder``) one rung at a time:

      * SHRINK one rung when decoding rows saw prefill interference and
        the ITL EWMA is approaching ``slo_itl_ms`` (> shrink_frac of it);
      * WIDEN one rung when decode rows are idle (a pure-prefill
        iteration stretches nobody's gap), when the ITL EWMA sits
        comfortably under the SLO (< widen_frac), or when the TTFT EWMA
        is endangering ``slo_ttft_ms`` while ITL still has headroom.

    ``cooldown`` observed steps of hysteresis separate transitions so one
    noisy step cannot thrash the width. Pure bookkeeping — no device
    dispatch, no new jitted programs (the rung widths are all warmed) —
    so dlgrind fingerprints and the compile sentinel are untouched by
    construction. Exported as the ``admission`` /stats block and the
    ``dllama_admission_*`` /metrics family."""

    def __init__(self, chunk: int, *, slo_ttft_ms: float | None = None,
                 slo_itl_ms: float | None = None, rungs: int = 4,
                 alpha: float = 0.25, shrink_frac: float = 0.85,
                 widen_frac: float = 0.5, cooldown: int = 2):
        assert slo_ttft_ms or slo_itl_ms, "an SLO-less policy has no goal"
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_itl_ms = slo_itl_ms
        self.ladder = chunk_ladder(chunk, rungs)
        self._rung = 0              # index into ladder; 0 = widest
        self.alpha = float(alpha)   # EWMA weight of the newest sample
        self.shrink_frac = float(shrink_frac)
        self.widen_frac = float(widen_frac)
        self.cooldown = int(cooldown)
        self._since_change = self.cooldown  # first decision is eligible
        self.itl_ewma_ms: float | None = None
        self.ttft_ewma_ms: float | None = None
        self.shrinks = 0
        self.widens = 0
        # the "degrade — no speculation" actuator (ROADMAP item 2's
        # overload degrade, wired here where the live ITL signal is): a
        # speculative verify forward is WIDER than a plain decode step,
        # so when the ITL EWMA endangers the SLO the policy turns
        # drafting off before (independently of) shrinking the chunk
        # ladder, and re-arms it once ITL sits comfortably under the
        # target again. Same hysteresis bands as the width walk.
        self.spec_on = True
        self.spec_disables = 0
        self.spec_enables = 0

    @property
    def spec_allowed(self) -> bool:
        """Whether the scheduler may run speculative verify steps this
        iteration (runtime/draft.py per-slot drafting consults this
        before every draft dispatch)."""
        return self.spec_on

    @property
    def width(self) -> int:
        return self.ladder[self._rung]

    def _mix(self, prev: float | None, sample: float) -> float:
        return sample if prev is None else (
            self.alpha * sample + (1.0 - self.alpha) * prev)

    def observe_ttft(self, ttft_ms: float) -> None:
        self.ttft_ewma_ms = self._mix(self.ttft_ewma_ms, float(ttft_ms))

    def observe_step(self, wall_ms: float, decode_rows: int,
                     prefill_rows: int) -> None:
        """One WORKING iteration's composition + wall ms (called by
        ``_step_body`` after the forwards ran). A step with decode rows
        is their observed inter-token gap — that, not a per-request
        after-the-fact average, is the signal that can still save the
        requests currently running."""
        if decode_rows:
            self.itl_ewma_ms = self._mix(self.itl_ewma_ms, float(wall_ms))
        # speculation actuator first: it is independent of the width
        # cooldown (turning drafting off must not wait out a recent
        # chunk transition — the verify width is the bigger lever)
        if self.slo_itl_ms and self.itl_ewma_ms is not None:
            if (self.spec_on
                    and self.itl_ewma_ms > self.shrink_frac * self.slo_itl_ms):
                self.spec_on = False
                self.spec_disables += 1
            elif (not self.spec_on
                  and self.itl_ewma_ms < self.widen_frac * self.slo_itl_ms):
                self.spec_on = True
                self.spec_enables += 1
        self._since_change += 1
        if self._since_change < self.cooldown:
            return
        itl, slo_i = self.itl_ewma_ms, self.slo_itl_ms
        ttft, slo_t = self.ttft_ewma_ms, self.slo_ttft_ms
        if (slo_i and decode_rows and prefill_rows and itl is not None
                and itl > self.shrink_frac * slo_i):
            if self._rung + 1 < len(self.ladder):
                self._rung += 1
                self.shrinks += 1
                self._since_change = 0
            return
        comfortable = (slo_i is not None and itl is not None
                       and itl < self.widen_frac * slo_i)
        ttft_pressure = (slo_t is not None and ttft is not None
                         and ttft > self.shrink_frac * slo_t
                         and (slo_i is None or itl is None
                              or itl < self.shrink_frac * slo_i))
        if ((decode_rows == 0 or comfortable or ttft_pressure)
                and self._rung > 0):
            self._rung -= 1
            self.widens += 1
            self._since_change = 0

    def summary(self) -> dict:
        rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
        return {
            "slo_ttft_ms": self.slo_ttft_ms,
            "slo_itl_ms": self.slo_itl_ms,
            "chunk_width": self.width,
            "chunk_ladder": list(self.ladder),
            "itl_ewma_ms": rnd(self.itl_ewma_ms),
            "ttft_ewma_ms": rnd(self.ttft_ewma_ms),
            "shrinks": self.shrinks,
            "widens": self.widens,
            "spec_allowed": self.spec_on,
            "spec_disables": self.spec_disables,
            "spec_enables": self.spec_enables,
        }


class _Slot:
    """One row of the batched KV cache. state is derived: FREE when req is
    None, PREFILL while off < len(prompt), DECODE after. `pos` is the next
    cache write position, `last` the token to feed next step. `pins` is
    the prefix-cache path the slot was seeded from (held until the slot
    releases so eviction can't free its source blocks). With per-slot
    drafting armed (runtime/draft.py): `draft_pos` is the row's draft-KV
    frontier (positions < draft_pos of the draft cache hold the true
    stream — host bookkeeping only, reset on every lease like the main
    cache's; the next lease's prefill overwrites the predecessor's draft
    K/V before the draft can attend it) and `toks` the fed-token history
    draft catch-up chunks read from (prompt + emitted tokens)."""

    __slots__ = ("idx", "req", "pos", "off", "n_out", "last", "pins",
                 "draft_pos", "toks")

    def __init__(self, idx: int):
        self.idx = idx
        self.req: ServeRequest | None = None
        self.pos = 0
        self.off = 0
        self.n_out = 0
        self.last = 0
        self.pins: tuple = ()
        self.draft_pos = 0
        self.toks: list[int] = []


class Scheduler:
    def __init__(self, engine, *, chunk: int | None = None,
                 max_queue: int = 0, queue_timeout: float | None = None,
                 request_deadline: float | None = None,
                 prefix_cache=None, fault_key: str | None = None,
                 slo_ttft_ms: float | None = None,
                 slo_itl_ms: float | None = None,
                 draft_factory=None, draft_len: int = 0,
                 draft_vocab: int | None = None,
                 sample_vocab: int | None = None,
                 fair_queue=None):
        self.engine = engine
        # identifies THIS scheduler at the replica-level fault sites
        # (runtime/faults.py replica_raise/replica_stall): the router
        # names replica i's scheduler "r{i}" so chaos tests can kill one
        # replica deterministically while its siblings keep serving
        self.fault_key = fault_key
        self.chunk = int(chunk or min(engine.prefill_chunk, engine.seq_len))
        assert 1 <= self.chunk <= engine.seq_len, self.chunk
        # SLO-aware self-tuning admission (either SLO flag arms it): the
        # policy walks the chunk-width ladder per iteration off the live
        # step timeline; `chunk` stays the WIDEST rung (and the only
        # width when no SLO is set)
        self.admission = (AdmissionPolicy(self.chunk,
                                          slo_ttft_ms=slo_ttft_ms,
                                          slo_itl_ms=slo_itl_ms)
                          if (slo_ttft_ms or slo_itl_ms) else None)
        self.slots = [_Slot(i) for i in range(engine.batch)]
        # radix prefix cache (runtime/prefix_cache.PrefixCache) — must be
        # built over THIS engine's arena; a supervisor rebuild passes a
        # fresh one (the arena dies with the engine). None = reuse off.
        self.prefix_cache = prefix_cache
        assert prefix_cache is None or prefix_cache.engine is engine, (
            "prefix cache arena belongs to a different engine")
        # admission control: max_queue bounds the waiting line (0 = no
        # bound — the supervisor/API layer sets one); queue_timeout bounds
        # how long a request may WAIT before it must be failed rather than
        # started; request_deadline is the default per-request end-to-end
        # budget applied at submit when the caller gives none
        self.max_queue = int(max_queue)
        self.queue_timeout = queue_timeout
        self.request_deadline = request_deadline
        # per-slot REAL-draft speculation (runtime/draft.py): the factory
        # builds a DraftModel over THIS scheduler's engine (a supervisor
        # rebuild passes a fresh engine — the draft's params are views of
        # its buffers and must die with it). One batched draft KV cache
        # serves every slot; per-slot frontiers live on the slots.
        from .stats import SpecStats

        self.draft = draft_factory(engine) if draft_factory else None
        self.draft_len = int(draft_len) if self.draft is not None else 0
        assert self.draft is None or self.draft_len >= 1, \
            "a draft without a draft length proposes nothing"
        # device-argmax vocab for greedy verify: the TOKENIZER's vocab
        # (the host Sampler truncates there — sampler.py:69). Requests
        # whose sampler vocab differs simply never speculate.
        self.draft_vocab = int(draft_vocab or engine.spec.vocab_size)
        # sharded-sampling vocab (vocab-sharded engines,
        # ops/sharded_vocab.py): the TOKENIZER vocab the warmed
        # sample-prep executable truncates at — one compile key, warmed
        # below; requests whose sampler vocab differs take the warmed
        # per-row parity fallback instead of minting keys
        self.sample_vocab = int(sample_vocab or draft_vocab
                                or engine.spec.vocab_size)
        self.draft_cache = (self.draft.new_cache()
                            if self.draft is not None else None)
        self._spec_stats = SpecStats(
            mode=(self.draft.label if self.draft is not None else "off"),
            draft_len=self.draft_len)
        # deque.append/popleft are atomic under the GIL, so submit() never
        # touches the step mutex: a submitter must not wait out an
        # in-flight forward (measured: mutex-taking submits stalled a
        # 2.8 s arrival trace to 8.5 s behind back-to-back steps — lock
        # handoff is not FIFO)
        # fair_queue (runtime/fleet.WFQueue) duck-types this exact deque
        # slice — append/popleft/len/bool — swapping FIFO admission for
        # weighted-fair when tenant budgets are armed; its own internal
        # lock is tiny and never held across a forward, preserving the
        # cheap-submit constraint above
        self._queue = (fair_queue if fair_queue is not None
                       else deque())  # dlrace: guarded-by(self._mutex)
        # fleet overload ladder actuator (runtime/fleet.ShedLadder rung
        # "no_spec"): ORs with the admission policy's own spec gate —
        # either may turn drafting off, both must agree to turn it on.
        # Bool store/read is atomic under the GIL; written by the fleet
        # controller thread, read by the stepping thread.
        self.spec_degraded = False
        self._mutex = threading.RLock()  # step()/exclusive() mutual excl.
        self._wake = threading.Event()
        self.stats = ServeStats()
        if prefix_cache is not None:
            self.stats.prefix = prefix_cache.stats
        self.stats.admission = self.admission  # None when no SLO is set
        self.stats.spec = self._spec_stats  # always attached (mode "off"
        # when no draft: a tier must not lose the family to a launch flag)
        self._thread: threading.Thread | None = None
        self._stop = False
        self._closed = False  # dlrace: guarded-by(self._mutex)
        # watchdog heartbeat: perf_counter when the CURRENT step body
        # entered, None while idle/between steps. Written only by the
        # stepping thread; read lock-free by the supervisor's watchdog
        # (a float store is atomic under the GIL) — a mutex-holding
        # borrow (exclusive()) therefore never looks like a stall.
        self._step_t0: float | None = None  # dlrace: guarded-by(self._mutex)
        self._rid = 0  # dlrace: guarded-by(self._rid_lock)
        self._rid_lock = threading.Lock()

    # -- submission --------------------------------------------------------

    def submit(self, prompt: list[int], max_tokens: int, sampler,
               eos_id: int | set[int] | None = None,
               deadline: float | None = None,
               trace_id: int | None = None,
               tenant: str | None = None,
               priority: str = "normal") -> ServeRequest:
        """Enqueue a request; it joins the running batch as soon as a slot
        frees. `sampler` is PER REQUEST (its RNG stream is the slot's
        sampling state — concurrent requests never share coins).
        max_tokens <= 0 prefills and emits nothing (Engine.generate's
        hard-cap contract). Raises PromptTooLong before queueing when the
        prompt cannot fit the context, QueueFull when the waiting line is
        at max_queue, SchedulerClosed after close(). `deadline` is an
        absolute perf_counter bound (default: now + request_deadline when
        configured)."""
        if self._closed:
            raise SchedulerClosed("scheduler is closed")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) >= self.engine.seq_len:
            raise PromptTooLong(
                f"prompt is {len(prompt)} tokens; context is "
                f"{self.engine.seq_len}")
        if self.max_queue and len(self._queue) >= self.max_queue:
            with self._rid_lock:
                self.stats.requests_rejected += 1
            raise QueueFull(len(self._queue), self.max_queue)
        stop_ids = ({eos_id} if isinstance(eos_id, int)
                    else set(eos_id or ()))
        now = time.perf_counter()
        if deadline is None and self.request_deadline is not None:
            deadline = now + self.request_deadline
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        if trace_id is None:
            # single-supervisor tier: the scheduler door IS the front
            # door, so it mints the span id (the router mints earlier so
            # retries share one id and passes it through here)
            trace_id = TRACER.new_id() if TRACER.enabled else 0
        req = ServeRequest(rid, prompt, max_tokens, sampler, stop_ids,
                           deadline=deadline, trace_id=trace_id,
                           tenant=tenant, priority=priority)
        req.stats.t_submit = now
        if TRACER.enabled:
            TRACER.event("enqueue", trace_id, rid=rid,
                         n_prompt=len(prompt), max_tokens=max_tokens,
                         key=self.fault_key)
        with self._rid_lock:
            self.stats.requests_submitted += 1
        self.stats.requests.append(req.stats)  # deque.append: atomic
        self._queue.append(req)
        self._wake.set()
        if self._closed:
            # close() ran between the entry check and the append: its
            # _abort_all may already have drained the queue, so this
            # request would hang its waiter forever — fail it here
            # (idempotent: if the abort DID see it, the claim loses)
            self._fail_req(req, {"code": "shutdown",
                                 "message": "scheduler shutdown",
                                 "retryable": False})
        return req

    # -- the scheduling iteration -----------------------------------------

    def step(self) -> bool:
        """One scheduling iteration: admit queued requests into free slots,
        run one chunked-prefill forward for prefilling rows, one decode
        step for decoding rows. Returns False when there was no work.
        Synchronous entry point (tests/bench drive it directly; the
        background thread calls the same body)."""
        with self._mutex:
            return self._step_locked()

    def has_work(self) -> bool:
        with self._mutex:
            return bool(self._queue) or any(s.req is not None
                                            for s in self.slots)

    def _step_locked(self) -> bool:
        # sampled device-time attribution (runtime/profiler.py): every
        # --profile-sample-th WORKING step runs under a short
        # jax.profiler trace. Bracketed OUTSIDE the _step_t0 window:
        # start_trace/stop_trace overhead (seconds on a cold profiler)
        # must never read as step time, or the watchdog declares the
        # sampled step a stall and the supervisor kills a healthy
        # generation (observed live: first sample -> watchdog trip ->
        # spurious recovery). Guard-before-call like the tracer:
        # sampling off is one attribute read, no allocation; idle
        # iterations never consume a sample.
        prof = None
        if PROFILER.sample_every and (
                self._queue or any(s.req is not None for s in self.slots)):
            prof = PROFILER.step_begin()
        self._step_t0 = time.perf_counter()  # watchdog heartbeat: in-step
        try:
            return self._step_body()
        finally:
            # wall BEFORE clearing the heartbeat: the sampled step's host
            # wall rides the sync/compute record (dlwire) so dlprof can
            # show device collective ms against the step it lived in
            wall_ms = (time.perf_counter() - self._step_t0) * 1e3
            self._step_t0 = None
            if prof is not None:
                PROFILER.step_end(prof, wall_ms)

    def _step_body(self) -> bool:
        if not self._queue and all(s.req is None for s in self.slots):
            # idle iteration: nothing to do AND no fault site fires — an
            # armed fault must land on a WORKING step (a crash on an idle
            # loop is meaningless, and another scheduler's idle loop in
            # the same process must never consume a globally-armed fault
            # out from under the one being tested)
            return False
        # named fault sites (runtime/faults.py): no-ops unless armed; fired
        # BEFORE any device dispatch so injection never alters a jitted
        # program (the dlgrind fingerprints are injection-invariant)
        FAULTS.fire("step_raise")
        FAULTS.fire("step_stall")
        FAULTS.fire("slow_step")
        # replica-level sites: key-filtered, so an armed key=rK spec only
        # counts/fires on replica K's working steps (other schedulers —
        # including fault_key=None ones — pass through untouched)
        FAULTS.fire("replica_raise", key=self.fault_key)
        FAULTS.fire("replica_stall", key=self.fault_key)
        now = time.perf_counter()
        # reap cancellations and expired deadlines FIRST so a disconnected
        # client's request never burns another forward — in particular a
        # long prompt must not prefill its remaining chunks into a dead
        # slot — and an over-deadline request fails NOW, not after its
        # budget drains
        for s in self.slots:
            if s.req is None:
                continue
            if s.req._cancelled:
                self._finish_slot(s, "cancelled")
            elif s.req.expired(now):
                req, s.req = s.req, None
                self._release_slot_cache(s, req)
                self._expire_req(req)
        self._admit()
        pre = [s for s in self.slots
               if s.req is not None and s.off < len(s.req.prompt)]
        dec = [s for s in self.slots
               if s.req is not None and s.off >= len(s.req.prompt)]
        if not pre and not dec:
            return False
        self.stats.steps += 1
        self.stats.occupancy.append(len(pre) + len(dec))
        self.stats.queue_depth.append(len(self._queue))
        # per-iteration chunk width: the SLO-aware policy's current rung
        # (a warmed compile key — see AdmissionPolicy/chunk_ladder), or
        # the one configured width when no SLO is set
        cw = (self.admission.width if self.admission is not None
              else self.chunk) if pre else 0
        if pre:
            self._prefill_chunk(pre, cw)
        # per-slot drafting (runtime/draft.py): the admission policy's
        # "degrade — no speculation" actuator gates every draft dispatch
        # — when the live ITL EWMA endangers the SLO, the scheduler
        # falls back to plain (B, 1) decode steps until it recovers
        spec_ok = (self.draft is not None
                   and not self.spec_degraded
                   and (self.admission is None
                        or self.admission.spec_allowed))
        if self.draft is not None and dec and not spec_ok:
            self._spec_stats.degraded_steps += 1
        if spec_ok:
            # one draft catch-up chunk per iteration: rows whose draft
            # frontier trails the target (fresh admissions, prefix-cache
            # seeded prompts the draft must prefill itself, k == 0
            # rounds) advance up to one chunk — d/L of a target chunk
            self._draft_catchup_chunk()
        if dec:
            # rows that finished their prompt inside _prefill_chunk above
            # wait for the NEXT iteration: every live row gets at most one
            # decode forward per iteration (bounded ITL under admission)
            if spec_ok and any(self._spec_capable(s) for s in dec):
                self._decode_spec(dec)
            else:
                self._decode(dec)
        if TRACER.enabled:
            # step timeline: batch composition + wall ms, the raw
            # measurement behind /metrics' dllama_step_ms and the bench
            # step_timeline blocks (ROADMAP item 1's knee search). Wall
            # from the watchdog heartbeat t0 — one clock, no extra read
            # at step entry.
            TRACER.step(decode_rows=len(dec), prefill_rows=len(pre),
                        chunk=cw,
                        queue_depth=len(self._queue),
                        wall_ms=(time.perf_counter()
                                 - self._step_t0) * 1e3,
                        key=self.fault_key)
        if self.admission is not None:
            # the same wall the timeline records is the policy's signal;
            # it adapts the NEXT iteration's width (never this one's)
            self.admission.observe_step(
                (time.perf_counter() - self._step_t0) * 1e3,
                len(dec), len(pre))
        return True

    def _expire_req(self, req: ServeRequest, code: str = "deadline",
                    message: str = "request deadline exceeded") -> None:
        """Fail one request with a structured expiry frame."""
        if self._fail_req(req, {"code": code, "message": message,
                                "retryable": code != "deadline"}):
            self.stats.requests_expired += 1

    def _admit(self) -> None:  # dlrace: holds(self._mutex)
        now = time.perf_counter()
        free = [s for s in self.slots if s.req is None]
        while free and self._queue:
            req = self._queue.popleft()
            if req._cancelled:
                self._finish_req(req, "cancelled")
                continue
            if req.expired(now):
                self._expire_req(req)
                continue
            if (self.queue_timeout is not None
                    and now - req.stats.t_submit > self.queue_timeout):
                # queue-time budget: a request that waited too long is
                # failed at admission instead of started late — its waiter
                # gets a fast structured rejection it can retry elsewhere
                self._expire_req(req, code="queue_timeout",
                                 message="queue-time budget exceeded")
                continue
            s = free.pop(0)
            s.req = req
            s.off = 0
            s.pos = 0
            s.n_out = 0
            s.last = 0
            s.pins = ()
            # per-slot draft state resets with the lease (finish, cancel,
            # deadline, and abort all come back through here): the new
            # request's draft prefill overwrites the predecessor's draft
            # K/V before the draft can attend it — the same invariant as
            # the main cache's slot reuse
            s.draft_pos = 0
            s.toks = list(req.prompt)
            if TRACER.enabled:
                TRACER.event("admit", req.trace_id, slot=s.idx,
                             queue_ms=round(
                                 (now - req.stats.t_submit) * 1e3, 3),
                             key=self.fault_key)
            # slot "reset" is host-side bookkeeping ONLY — no cache zeroing
            # or reallocation. The new request's prefill/decode overwrites
            # every position before any of its queries can attend it, so
            # the predecessor's stale K/V is unreachable by construction.
            if self.prefix_cache is not None:
                # cross-request KV reuse: seed the longest cached prefix
                # (whole blocks, capped at len - 1 so the finishing chunk
                # still samples real logits) and prefill only the suffix.
                # The matched path stays pinned until the slot releases.
                n, ids, pins = self.prefix_cache.lookup_pin(req.prompt)
                if n > 0:
                    self.prefix_cache.seed_slot(s.idx, ids)
                    s.off = n
                    s.pins = pins
                if TRACER.enabled:
                    # recorded even on a miss (hit=0): a cold prefill is
                    # timeline information too
                    TRACER.event("seed", req.trace_id, hit=n,
                                 n_prompt=len(req.prompt))
                # (tokens_prefilled is counted per dispatched chunk in
                # _prefill_chunk — counting the whole suffix here would
                # overstate the denominator for requests cancelled or
                # expired mid-prefill)

    def _sample_view(self, logits, rows: list[_Slot]):
        """Wrap one forward's on-device logits for host sampling
        (Engine.sample_view): vocab-sharded engines serve the rows from
        the tiny argmax/candidate summary instead of a (B, vocab)
        fetch; replicated engines (and duck-typed test engines) get the
        classic full-logits view. temps carries each sampling row's
        temperature as a traced input (greedy rows pass 1.0)."""
        eng = self.engine
        sv = getattr(eng, "sample_view", None)
        if sv is None:
            from .sampling import FullLogitsView

            return FullLogitsView(eng.fetch_logits(logits))
        temps = np.ones((eng.batch,), np.float32)
        for s in rows:
            t = getattr(s.req.sampler, "temperature", 0.0)
            if t:
                temps[s.idx] = t
        return sv(logits, temps, self.sample_vocab)

    def _prefill_chunk(self, rows: list[_Slot],
                       width: int | None = None) -> None:
        eng = self.engine
        b, c = eng.batch, int(width or self.chunk)
        tok = np.zeros((b, c), np.int32)
        pos = np.full((b,), eng.seq_len, np.int32)  # gated rows: writes drop
        lidx = np.zeros((b,), np.int32)
        finishing = []
        for s in rows:
            n = min(c, len(s.req.prompt) - s.off)
            tok[s.idx, :n] = s.req.prompt[s.off:s.off + n]
            if self.prefix_cache is not None:
                # real (non-pad) tokens this forward actually prefills —
                # the honest denominator for prefill_saved_frac
                self.prefix_cache.stats.tokens_prefilled += n
            # tail padding (token 0) writes land beyond the prompt and are
            # overwritten by decode before any later query attends them
            pos[s.idx] = s.off
            lidx[s.idx] = n - 1
            if TRACER.enabled:
                TRACER.event("prefill", s.req.trace_id, off=s.off, n=n,
                             slot=s.idx)
            s.off += n
            if s.off == len(s.req.prompt):
                finishing.append(s)
        logits = eng.slot_prefill_chunk(tok, pos, lidx)
        if not finishing:
            return  # mid-prompt chunk: no D2H fetch at all
        view = self._sample_view(logits, finishing)
        for s in finishing:
            s.pos = len(s.req.prompt)
            if self.prefix_cache is not None:
                # publish the prompt's blocks the moment they are all
                # written — NOT at slot finish — so concurrent requests
                # sharing the prefix hit while this one still decodes
                # (blocks are immutable once published; a re-publish of
                # already-indexed blocks walks the tree and copies
                # nothing)
                self.prefix_cache.publish(s.idx, s.req.prompt)
            if s.req.max_tokens <= 0:
                # hard-cap contract, same as Engine.generate: the prefill
                # ran, nothing is emitted
                self._finish_slot(s, "length")
                continue
            self._emit(s, view.sample(s.req.sampler, s.idx))

    def _decode(self, rows: list[_Slot]) -> None:
        # cancellations were reaped at the top of the iteration; a cancel
        # landing mid-step costs at most this one extra forward
        live = rows
        eng = self.engine
        tok = np.zeros((eng.batch, 1), np.int32)
        pos = np.full((eng.batch,), eng.seq_len, np.int32)
        for s in live:
            tok[s.idx, 0] = s.last
            pos[s.idx] = s.pos
        logits = eng.slot_decode_step(tok, pos)
        view = self._sample_view(logits, live)
        for s in live:
            s.pos += 1
            self._emit(s, view.sample(s.req.sampler, s.idx))

    # -- per-slot real-draft speculation (runtime/draft.py) ----------------

    def _spec_capable(self, s: _Slot) -> bool:
        """Whether slot s can ride a speculative verify THIS iteration:
        greedy request (verification is the target's argmax — sampled
        rows would need per-row rejection chains, they ride the same
        verify forward's position-0 logits instead), sampler truncated
        at the scheduler's verify vocab, draft caught up to the target
        frontier, and at least 2 tokens of budget AND context headroom
        (drafting for a single remaining token buys nothing)."""
        req = s.req
        smp = req.sampler
        return (getattr(smp, "temperature", None) == 0.0
                and getattr(smp, "vocab_size", 0) == self.draft_vocab
                and s.draft_pos >= s.pos
                and req.max_tokens - s.n_out >= 2
                and self.engine.seq_len - s.pos >= 2)

    def _draft_catchup_chunk(self) -> None:
        """One batched (B, C) draft prefill chunk covering every slot
        whose draft-KV frontier trails what the target has written (the
        fed-token history is `s.toks`, capped at the written frontier —
        the final emitted token is never fed, there or here). Fixed
        width C = the configured chunk (ONE compile key however ragged
        the gaps); chunk-tail padding writes land beyond each row's
        frontier and are overwritten before the draft attends them."""
        eng, c = self.engine, self.chunk
        rows = []
        for s in self.slots:
            if s.req is None:
                continue
            smp = s.req.sampler
            if not (getattr(smp, "temperature", None) == 0.0
                    and getattr(smp, "vocab_size", 0) == self.draft_vocab):
                # a row that can never speculate (sampled request,
                # foreign vocab) gets no draft K/V — catch-up for it
                # would be a pure extra dispatch per iteration
                continue
            avail = min(len(s.toks), max(s.off, s.pos))
            if s.draft_pos < avail:
                rows.append((s, avail))
        if not rows:
            return
        tok = np.zeros((eng.batch, c), np.int32)
        pos = np.full((eng.batch,), eng.seq_len, np.int32)
        for s, avail in rows:
            n = min(c, avail - s.draft_pos)
            tok[s.idx, :n] = s.toks[s.draft_pos:s.draft_pos + n]
            pos[s.idx] = s.draft_pos
            s.draft_pos += n
        self.draft_cache = self.draft.prefill_chunk(self.draft_cache,
                                                    tok, pos)
        self._spec_stats.draft_forwards += 1

    def _decode_spec(self, rows: list[_Slot]) -> None:
        """The speculative decode iteration: ONE draft-scan dispatch
        proposes draft_len tokens per speculating row, ONE fixed-width
        verify forward confirms each row's accepted prefix + 1 — every
        row advances 1..draft_len+1 tokens per iteration at exact greedy
        parity (emission is always the TARGET's argmax; a wrong draft
        costs only its cheap forwards). Non-speculating rows (sampled,
        vocab-mismatched, draft catching up) ride the SAME verify
        forward: their segment pads with their own token and they sample
        one token from the position-0 logits — a (B, 1+K) forward costs
        ~one weight read like (B, 1), which is the whole bet."""
        from .speculative import count_accepted

        eng, k = self.engine, self.draft_len
        spec_rows = [s for s in rows if self._spec_capable(s)]
        dtok = np.zeros((eng.batch,), np.int32)
        dpos = np.full((eng.batch,), eng.seq_len, np.int32)  # gated rows
        for s in spec_rows:
            dtok[s.idx] = s.last
            dpos[s.idx] = s.pos
        drafts_np, self.draft_cache = self.draft.propose(
            self.draft_cache, dtok, dpos, k, n_vocab=self.draft_vocab)
        self._spec_stats.draft_forwards += 1
        tok = np.zeros((eng.batch, 1 + k), np.int32)
        pos = np.full((eng.batch,), eng.seq_len, np.int32)
        drafts: dict[int, list[int]] = {}
        for s in rows:
            tok[s.idx, :] = s.last  # pad = the row's own token (its
            # writes sit beyond the accepted prefix and are overwritten
            # before any later query attends them)
            pos[s.idx] = s.pos
        for s in spec_rows:
            # the scan always proposes k (one compile key); clamp to the
            # row's budget/headroom — surplus drafts become padding
            kk = min(k, eng.seq_len - s.pos - 1,
                     s.req.max_tokens - s.n_out - 1)
            d = [int(t) for t in drafts_np[s.idx][:kk]]
            drafts[s.idx] = d
            tok[s.idx, 1:1 + len(d)] = d
            s.draft_pos = s.pos + k  # the scan wrote pos..pos+k-1
        greedy, logits0 = eng.slot_verify_step(tok, pos, self.draft_vocab)
        self._spec_stats.verify_forwards += 1
        nonspec = [s for s in rows if s.idx not in drafts]
        # position-0 sampling rides the sharded view like any decode
        # step; built only when a non-speculating row exists (an
        # all-speculating iteration pays no extra dispatch)
        view0 = self._sample_view(logits0, nonspec) if nonspec else None
        for s in rows:
            d = drafts.get(s.idx)
            if d is None:
                s.pos += 1
                self._emit(s, view0.sample(s.req.sampler, s.idx))
                continue
            req = s.req
            m = count_accepted(d, greedy[s.idx])
            emitted = [int(g) for g in greedy[s.idx][: m + 1]]
            self._spec_stats.drafted += len(d)
            self._spec_stats.accepted += m
            self._spec_stats.emitted_spec += len(emitted)
            req.stats.spec_forwards += 1
            req.stats.spec_drafted += len(d)
            req.stats.spec_accepted += m
            pos0 = s.pos
            for t in emitted:
                s.pos += 1
                self._emit(s, t)
                if s.req is None:  # stop/budget retired the slot: the
                    break          # rest of the accepts are discarded
            if s.req is not None:
                # clamp the draft frontier to the TRUE verified stream:
                # positions past the first rejection hold rejected-token
                # K/V. The next speculative scan would overwrite them
                # contiguously before attending them — but intervening
                # PLAIN rounds (SLO degrade, budget tail) advance s.pos
                # without touching the draft cache, and a later catch-up
                # starting at an inflated draft_pos would leave the
                # stale entries below the frontier, silently decaying
                # the accept rate for the rest of the stream
                # (review-found)
                s.draft_pos = min(pos0 + k, s.pos)

    def _emit(self, s: _Slot, token: int) -> None:
        """Record one sampled token and retire the slot the moment the
        request is done — the freed slot is admissible next iteration.
        Exactly Engine.generate's continue condition, negated: a stop
        token is emitted then stops the row; budget and context-edge rows
        finish as "length". The final emitted token is never fed back
        (generate() parity — no overrun forward)."""
        req = s.req
        token = int(token)
        s.n_out += 1
        s.last = token
        s.toks.append(token)  # the draft catch-up's fed-token history
        now = time.perf_counter()
        if req.stats.t_first is None:
            req.stats.t_first = now
            if self.admission is not None:
                self.admission.observe_ttft(
                    (now - req.stats.t_submit) * 1e3)
            if TRACER.enabled:
                TRACER.event("first_token", req.trace_id,
                             ttft_ms=round((now - req.stats.t_submit)
                                           * 1e3, 3))
        elif TRACER.enabled and s.n_out % TRACER.decode_every == 0:
            # decode progress at a bounded cadence: a per-token event
            # would let one long stream flush the whole ring
            TRACER.event("decode", req.trace_id, n_out=s.n_out)
        req.stats.n_out = s.n_out
        self.stats.tokens_out += 1
        req.events.put(("token", token))
        if token in req.stop_ids:
            self._finish_slot(s, "stop")
        elif s.n_out >= req.max_tokens or s.pos >= self.engine.seq_len:
            self._finish_slot(s, "length")

    def _release_slot_cache(self, s: _Slot, req: ServeRequest) -> None:
        """Prefix-cache bookkeeping for a slot leaving any path: release
        the seed pins, and for a slot retiring MID-PREFILL (cancel,
        deadline) publish the prompt prefix it did write (s.off only
        advances after a chunk's forward ran, so [0, off) is always real
        data). Completed prompts published at prefill-finish already.

        Only PREFILL-written blocks are ever published — never the
        decode extension (req.prompt + fed tokens): decode-step K/V is
        not guaranteed bitwise-equal to what a cold prefill of the same
        tokens would write (different executables may reduce in a
        different order under bf16), and seeding it would silently void
        the cache-on == cache-off token-parity guarantee. Multi-turn
        reuse barely loses: turn N+1's prompt embeds turn N's reply,
        hits turn N's PROMPT blocks, re-prefills just the reply + new
        message — and its own prefill-finish publish then covers the
        full turn-N+1 prompt for turn N+2. This also bounds publish
        work to once per prompt, not per retirement."""
        if self.prefix_cache is None:
            return
        if 0 < s.off < len(req.prompt):
            self.prefix_cache.publish(s.idx, req.prompt[: s.off])
        self.prefix_cache.unpin(s.pins)
        s.pins = ()

    def _finish_slot(self, s: _Slot, reason: str) -> None:
        req, s.req = s.req, None  # slot is FREE from here on
        self._release_slot_cache(s, req)
        self._finish_req(req, reason)

    def _finish_req(self, req: ServeRequest, reason: str) -> None:
        if not req._claim_terminal():
            return
        req.finish_reason = reason
        req.stats.t_done = time.perf_counter()
        self.stats.requests_finished += 1
        if TRACER.enabled:
            if req.stats.spec_forwards:
                # the request's honest accept record, on its span — what
                # dlprof needs to attribute verify-forward cost per
                # request (one event per request, not per verify)
                TRACER.event("spec", req.trace_id,
                             forwards=req.stats.spec_forwards,
                             drafted=req.stats.spec_drafted,
                             accepted=req.stats.spec_accepted,
                             key=self.fault_key)
            TRACER.event("finish", req.trace_id, reason=reason,
                         n_out=req.stats.n_out)
        req.events.put(("done", reason))
        req.finished.set()

    def warmup(self) -> None:
        """Compile the serving executables (slot_prefill_chunk_C and
        slot_decode_step) by running each once with EVERY row gated off
        (pos == seq_len: cache writes drop out of bounds, logits unread) —
        state-neutral by the same invariant the scheduler always relies
        on. The supervisor runs this on a rebuilt engine BEFORE marking it
        ready, so first-step compile time is spent while the watchdog is
        not watching; without it a stall_timeout below the compile time
        would trip on every fresh engine's first real step (an infinite
        recovery loop on TPU, where compiles run tens of seconds)."""
        eng = self.engine
        with self._mutex:
            gate = np.full((eng.batch,), eng.seq_len, np.int32)
            # with the SLO-aware policy armed, EVERY ladder rung is a
            # planned prefill width: warm them all here so an adaptive
            # run mints zero post-warmup compile keys (the sentinel —
            # and --freeze-compiles — stay green while the width moves)
            widths = (self.admission.ladder if self.admission is not None
                      else [self.chunk])
            for w in widths:
                eng.slot_prefill_chunk(np.zeros((eng.batch, w), np.int32),
                                       gate, np.zeros((eng.batch,), np.int32))
            lg = eng.slot_decode_step(np.zeros((eng.batch, 1), np.int32),
                                      gate)
            # vocab-sharded engines: compile the sharded sample-prep +
            # per-row fallback executables against the warmed decode
            # step's logits — sampled traffic then mints ZERO
            # post-warmup keys (the prefill/verify paths share the same
            # batch-shaped keys)
            warm_sample = getattr(eng, "warm_sample_ops", None)
            if warm_sample is not None:
                warm_sample(lg, self.sample_vocab)
            if self.draft is not None:
                # the draft key set is planned and bounded: one prefill
                # width, one scan shape, one verify width — compile all
                # three here (all rows gated: state-neutral by the same
                # OOB invariant) so speculative traffic mints ZERO
                # post-warmup keys and --freeze-compiles stays green
                self.draft_cache = self.draft.prefill_chunk(
                    self.draft_cache,
                    np.zeros((eng.batch, self.chunk), np.int32), gate)
                _, self.draft_cache = self.draft.propose(
                    self.draft_cache, np.zeros((eng.batch,), np.int32),
                    gate, self.draft_len, n_vocab=self.draft_vocab)
                eng.slot_verify_step(
                    np.zeros((eng.batch, 1 + self.draft_len), np.int32),
                    gate, self.draft_vocab)
            if self.prefix_cache is not None:
                # the seed/publish executables compile here too — a
                # rebuilt engine's first prefix-cache admission must not
                # read as a stall either. Unlike the gated forwards
                # above, the seed warmup REALLY writes row 0, so the
                # prose precondition (idle scheduler) is enforced: a
                # warmup over a live slot 0 would replace its prefix K/V
                # with arena bytes and silently corrupt its output
                assert all(s.req is None for s in self.slots), (
                    "prefix-cache warmup requires an idle scheduler")
                self.prefix_cache.warmup()
            # the serving set is compiled: arm the recompile sentinel —
            # from here any NEW compile key on this engine is a
            # compile_after_warmup event (and a structured refusal under
            # --freeze-compiles; runtime/profiler.py). Engine-only:
            # duck-typed test engines without the ledger pass through.
            mark = getattr(eng, "mark_compile_warm", None)
            if mark is not None:
                mark()

    # -- background thread -------------------------------------------------

    def start(self) -> None:
        with self._mutex:
            if self._thread is not None:
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name="dllama-scheduler", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop:
            # clear-before-step ordering: a submit landing after the clear
            # is either seen by this step (queue appended before set) or
            # re-arms the event so the wait below returns immediately
            self._wake.clear()
            with self._mutex:
                try:
                    did = self._step_locked()
                except Exception as e:  # fail every request, keep serving
                    self._abort_all(f"{type(e).__name__}: {e}")
                    did = False
            if not did and not self._stop:
                self._wake.wait(timeout=0.05)

    def _fail_req(self, req: ServeRequest, frame: dict) -> bool:
        """Terminal structured-error delivery for one request
        (exactly-once: concurrent failure paths both calling this deliver
        one event and count one failure). Returns whether THIS call won
        the claim."""
        if not req._claim_terminal():
            return False
        req.finish_reason = "error"
        req.stats.t_done = time.perf_counter()
        self.stats.requests_finished += 1
        self.stats.requests_failed += 1
        if TRACER.enabled:
            TRACER.event("error", req.trace_id,
                         code=frame.get("code", "error"),
                         retryable=bool(frame.get("retryable", True)),
                         n_out=req.stats.n_out, key=self.fault_key)
        req.events.put(("error", dict(frame)))
        req.finished.set()
        return True

    def _abort_all(self, msg: str, code: str = "engine_error",
                   retryable: bool = True) -> None:
        """Fail every in-flight and queued request with one structured
        frame. Called WITHOUT the mutex from close()/the supervisor when
        the step thread may be wedged inside a forward holding it — slot
        hand-off here races only against that dead/stuck thread, whose
        scheduler generation is already discarded."""
        frame = {"code": code, "message": msg, "retryable": retryable}
        if self.prefix_cache is not None:
            # the engine generation behind the arena is being discarded
            # (crash recovery, close) — recovered engines must never
            # seed from a dead engine's blocks, so the WHOLE tree goes
            # (a mere step exception on the legacy unsupervised loop
            # also lands here: conservative cache loss, never staleness)
            self.prefix_cache.invalidate()
        for s in self.slots:
            s.pins = ()  # pinned nodes were detached by the invalidate
            if s.req is not None:
                req, s.req = s.req, None
                self._fail_req(req, frame)
        while self._queue:
            try:
                self._fail_req(self._queue.popleft(), frame)
            except IndexError:  # racing submit/abort: queue drained under us
                break

    def close(self, timeout: float = 30.0) -> None:
        """Stop the loop and FAIL whatever is still queued or in flight —
        a submitter blocked in ServeRequest.tokens() must get its terminal
        frame now, not a 600 s timeout (pre-fix, close() left queued
        requests un-failed and their waiters hanging)."""
        self._closed = True  # new submits raise SchedulerClosed
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # no mutex: a cleanly-joined thread is gone; a stuck one (hung
        # forward) holds the mutex forever and the waiters still need
        # their frames
        self._abort_all("scheduler shutdown", code="shutdown",
                        retryable=False)

    @contextlib.contextmanager
    def exclusive(self):
        """Lend the batched engine to a legacy whole-batch caller: blocks
        the step loop, drives every queued/in-flight request to completion
        on the caller's thread, then yields the engine. The borrower may
        reset()/step the engine freely — all slots are free while held.
        This is how the process keeps exactly ONE live batched KV cache
        (apps/api_server routes /v1/batch/completions through here)."""
        with self._mutex:
            while self._step_locked():
                pass
            yield self.engine

    # -- cross-replica KV block transfer (runtime/kv_transfer.py) ----------
    #
    # The admit-seeded-from-transfer path needs NO new admission code: a
    # fill publishes the fetched blocks into THIS scheduler's radix tree
    # before submit, and _admit's ordinary lookup_pin then seeds them —
    # so the PR-4 invariant (seeded K/V == a cold prefill's writes, greedy
    # bit-identical) carries over unchanged: the shipped bytes ARE a
    # prefill's writes, just a sibling replica's. These helpers exist so
    # the transfer engine never reaches into the step mutex directly.

    def kv_match_len(self, tokens: list[int]) -> int:
        """Lock-free peek at this scheduler's cached prefix (0 with the
        cache off) — the importer's n_have before deciding a fetch."""
        pc = self.prefix_cache
        return pc.match_len(tokens) if pc is not None else 0

    def kv_export_pin(self, tokens: list[int]):
        """Donor: pin + describe the exportable path (under the step
        mutex). Returns (n_tokens, block_ids, pins); (0, [], ()) with
        the cache off."""
        if self.prefix_cache is None:
            return 0, [], ()
        with self._mutex:
            return self.prefix_cache.export_pin(tokens)

    def kv_export_block(self, block_id: int):
        """Donor: one pinned block's host K/V pair (under the step mutex
        — see PrefixCache.export_block_host for why)."""
        with self._mutex:
            return self.prefix_cache.export_block_host(block_id)

    def kv_unpin(self, pins) -> None:
        with self._mutex:
            if self.prefix_cache is not None:
                self.prefix_cache.unpin(pins)

    def kv_import_prefix(self, tokens: list[int], start_block: int,
                         blocks: list) -> int:
        """Importer: publish fetched blocks into this scheduler's tree
        (under the step mutex). Returns tokens imported (0 = nothing
        attachable: the next admission simply re-prefills)."""
        if self.prefix_cache is None:
            return 0
        with self._mutex:
            return self.prefix_cache.import_path(tokens, start_block,
                                                 blocks)

    # -- observability -----------------------------------------------------

    def wire_estimate(self):
        """Per-emitted-token collective bytes under the measured mean
        occupancy (runtime/netstats.estimate_serve_wire): a gated slot
        still rides through every collective, so low occupancy inflates
        the per-token wire cost proportionally."""
        from .netstats import estimate_serve_wire

        occ = (sum(self.stats.occupancy) / len(self.stats.occupancy)
               if self.stats.occupancy else self.engine.batch)
        return estimate_serve_wire(
            self.engine.spec, self.engine.mesh, batch=self.engine.batch,
            occupancy=occ, q80=self.engine.q80_collectives)
