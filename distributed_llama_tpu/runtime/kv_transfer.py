"""Cross-replica KV block transfer: published arena blocks as a
distributed currency.

Until now every replica's radix prefix cache (runtime/prefix_cache.py)
was an island — the router's shadow index could only STEER requests
toward where KV already lives, so a cold replica re-prefilled prefixes a
sibling already holds, paying the full per-token forward (weight reads +
FLOPs + collectives) for bytes that exist one process away. This module
makes the blocks themselves move, the disaggregation/transfer idea of
the vLLM/SGLang serving lineage (PAPERS.md) folded into this repo's
machinery:

  * an RMSG frame family (``RMSG_BLOCK_QUERY``/``RMSG_BLOCK_FETCH``/
    ``RMSG_BLOCK_DATA``) rides the PR-5 framed codec
    (parallel/multihost._send_frame/_recv_frame — the socket fault
    sites fire inside it unchanged) between replica workers, shipping
    published arena blocks: already fixed-shape, refcounted, and
    token-addressed by PR 4, so a block is self-describing currency;
  * CACHE FILL ON MISS — when the router places a request on a replica
    whose cache trails a sibling's, the placed replica FETCHES the
    missing whole blocks (pin-on-donor for the transfer's lifetime),
    publishes them into its own radix tree, and the ordinary admission
    seeds them. The PR-4 invariant carries over byte-for-byte: the
    shipped K/V *is* a prefill's writes (the donor's — same executable,
    same params), so greedy outputs stay BIT-IDENTICAL with transfer on
    vs off. Any failure — donor death mid-``RMSG_BLOCK_DATA``, a torn
    frame, a stalled socket past the per-transfer deadline — degrades to
    a plain local re-prefill, never a request failure;
  * PREFILL/DECODE DISAGGREGATION — ``--tier prefill|decode|mixed``
    gives workers roles: a prefill-tier worker runs big chunks with no
    decode occupancy and its finished blocks stream to decode-tier
    workers through the same fill path, so decode ITL never eats a
    stranger's prefill chunk (runtime/router.py owns the role-aware
    placement and falls back to the unified mixed path when no prefill
    worker is routable).

Every block frame is accounted in a dlwire ledger (stats.WireStats, per
(peer, kind, dir)) from day one, so ``netstats.reconcile_wire`` closes
measured-vs-modeled over block traffic at the same 25% bar as the
cluster plane, and ``netstats.estimate_block_transfer`` models when a
transfer pays against the re-prefill it replaces. ``dlprof --wire``
renders the "KV transfer" section from these blocks.

Thread model: the donor's export loop holds the donor scheduler's step
mutex only per block copy (pin first, copy block-by-block, unpin in a
finally); the importer publishes under its own step mutex. Everything
here is host-side sockets + the two warmed arena executables
(``Engine.block_export``/``slot_import_block``) — no serving fingerprint
changes.

Chaos surface: ``kvx_stall``/``kvx_exit`` (runtime/faults.py) land a
wedge or a hard ``os._exit`` between two exact BLOCK_DATA frames of the
donor; the codec's ``frame_truncate``/``recv_stall`` sites fire at the
transfer sites unchanged (tests/test_kv_transfer.py).

Docs: docs/serving.md "KV block transfer", docs/operations.md runbook.
"""

from __future__ import annotations

import os
import socket
import time

import numpy as np

from ..parallel.multihost import ClusterProtocolError, _recv_frame, \
    _send_frame
from .faults import FAULTS
from .trace import TRACER

# the block-transfer verbs of the replica RMSG namespace
# (runtime/replica_worker.py owns 100..119; a version-checked HELLO
# precedes every connection, so a mixed build fails the handshake)
RMSG_BLOCK_QUERY = 120  # client -> worker: [requester, n_have, *tokens]
RMSG_BLOCK_ACK = 121    # worker -> client: [n_match, block_len, layers,
#                         kv_heads, head_size, dtype_code, payload_bytes]
RMSG_BLOCK_FETCH = 122  # client -> worker: [start_block, end_block]
RMSG_BLOCK_DATA = 123   # worker -> client: [block_index] + K||V payload
RMSG_BLOCK_END = 124    # worker -> client: [n_blocks_sent]

# ledger labels (the `kind` of dllama_kv_wire_bytes_total)
KVX_KIND_NAMES = {
    RMSG_BLOCK_QUERY: "BLOCK_QUERY", RMSG_BLOCK_ACK: "BLOCK_ACK",
    RMSG_BLOCK_FETCH: "BLOCK_FETCH", RMSG_BLOCK_DATA: "BLOCK_DATA",
    RMSG_BLOCK_END: "BLOCK_END",
    100: "HELLO", 101: "HELLO_ACK",  # the handshake frames share the conn
}

# arena dtypes a block may ship as (the ACK carries the code; an
# unknown/mismatched code is a refusal on the importer side — a fill
# must degrade, never write foreign-typed bytes into an arena)
DTYPE_CODES = {"float32": 1, "bfloat16": 2, "float8_e4m3fn": 3,
               "float16": 4}
CODE_DTYPES = {v: k for k, v in DTYPE_CODES.items()}

# the donor's kvx_exit hard-death code — EXIT_WORKER_FAULT's value
# (runtime/replica_worker.py), duplicated to keep this module import-
# cycle-free (replica_worker imports us at module level)
EXIT_KVX_FAULT = 86

TIERS = ("prefill", "decode", "mixed")


class KVTransferError(RuntimeError):
    """A transfer could not complete (protocol/shape/deadline). Always
    caught by the fill path: the request degrades to a local re-prefill
    — a transfer failure must never become a request failure.
    ``answered`` carries the donor's BLOCK_ACK match (tokens) when the
    failure happened AFTER the query was answered: the answer is a
    valid shadow-staleness verdict even when the data never arrived."""

    def __init__(self, msg: str, answered: int = -1):
        super().__init__(msg)
        self.answered = int(answered)


def _kind_name(kind) -> str:
    return KVX_KIND_NAMES.get(kind, str(kind))


def _mk_acct(wire, peer: int, direction: str):
    """Wire-ledger hook for the codec (same shape as the cluster
    plane's): None when no ledger is attached."""
    if wire is None:
        return None

    def acct(kind, nbytes):
        wire.account(peer, _kind_name(kind), direction, nbytes)
    return acct


def block_payload_bytes(n_layers: int, kv_heads: int, block_len: int,
                        head_size: int, dtype) -> int:
    """One block's on-the-wire K+V payload bytes — exact arithmetic the
    reconcile tests pin the measured ledger against."""
    one = n_layers * kv_heads * block_len * head_size
    return 2 * one * np.dtype(dtype).itemsize


# -- donor side -------------------------------------------------------------


class BlockDonor:
    """Serves one QUERY(/FETCH) connection against the CURRENT
    generation's prefix cache. Owned by the worker's ReplicaServer (and
    by in-process tests); ``sup_getter`` returns the live supervisor so
    a rolling rebuild mid-serve degrades instead of touching a dead
    generation."""

    def __init__(self, sup_getter, stats, *, fault_key: str | None = None,
                 io_timeout: float = 30.0):
        self._sup = sup_getter
        self.stats = stats
        self._fault_key = fault_key
        self._io = float(io_timeout)

    def serve(self, conn: socket.socket, frame) -> None:
        """Handle one RMSG_BLOCK_QUERY connection to completion. The
        matched path is pinned for exactly this connection's lifetime:
        a client that dies (or never fetches) unpins in the finally —
        a dead peer can never leak a pin."""
        ints = frame[1]
        if len(ints) < 2:
            raise ClusterProtocolError(f"short block query: {len(ints)}")
        requester, n_have = int(ints[0]), int(ints[1])
        tokens = [int(t) for t in ints[2:]]
        st = self.stats
        with st.lock:
            st.queries_served += 1
        acct_tx = _mk_acct(st.wire, requester, "tx")
        try:
            sched = self._sup()._sched
            pc = sched.prefix_cache
        except Exception:  # noqa: BLE001 — supervisor mid-swap
            sched = pc = None
        if pc is None:
            with st.lock:
                st.query_misses += 1
            _send_frame(conn, RMSG_BLOCK_ACK, [0, 0, 0, 0, 0, 0, 0],
                        timeout=self._io, acct=acct_tx)
            return
        bl = pc.block_len
        n_match, ids, pins = sched.kv_export_pin(tokens)
        try:
            eng = sched.engine
            dtype_code = DTYPE_CODES.get(
                np.dtype(eng.cache_dtype).name, 0)
            payload = block_payload_bytes(
                eng.spec.n_layers, eng.spec.n_kv_heads, bl,
                eng.spec.head_size, eng.cache_dtype)
            if n_match <= max(n_have, 0):
                # nothing the requester lacks — the MISS answer. The
                # router clears its stale shadow entry off this (the
                # donor evicted what the shadow still promised).
                with st.lock:
                    st.query_misses += 1
            _send_frame(conn, RMSG_BLOCK_ACK,
                        [n_match, bl, eng.spec.n_layers,
                         eng.spec.n_kv_heads, eng.spec.head_size,
                         dtype_code, payload],
                        timeout=self._io, acct=acct_tx)
            req = _recv_frame(conn, timeout=self._io,
                              acct=_mk_acct(st.wire, requester, "rx"))
            if req is None or req[0] != RMSG_BLOCK_FETCH:
                return  # client declined (miss) or died: unpin below
            start, end = int(req[1][0]), int(req[1][1])
            if not 0 <= start <= end <= n_match // bl:
                raise ClusterProtocolError(
                    f"block fetch range {start}..{end} outside "
                    f"0..{n_match // bl}")
            sent = 0
            for i in range(start, end):
                # chaos surface: a wedge or a hard exit lands exactly
                # between two BLOCK_DATA frames (key = the donor's
                # replica identity, like every replica-level site)
                FAULTS.fire("kvx_stall", key=self._fault_key)
                if FAULTS.triggered("kvx_exit", key=self._fault_key):
                    os._exit(EXIT_KVX_FAULT)
                k_np, v_np = sched.kv_export_block(ids[i])
                _send_frame(conn, RMSG_BLOCK_DATA, [i],
                            k_np.tobytes() + v_np.tobytes(),
                            timeout=self._io, acct=acct_tx)
                sent += 1
                with st.lock:
                    st.blocks_exported += 1
                    st.bytes_tx += payload
            _send_frame(conn, RMSG_BLOCK_END, [sent], timeout=self._io,
                        acct=acct_tx)
        except (OSError, ClusterProtocolError, socket.timeout):
            with st.lock:
                st.donor_aborts += 1
            raise
        finally:
            try:
                sched.kv_unpin(pins)
            except Exception:  # noqa: BLE001 — a dying generation's
                pass           # detached pins are already moot


# -- importer side ----------------------------------------------------------


def fetch_prefix(host: str, port: int, tokens: list[int], n_have: int, *,
                 block_len: int, block_shape: tuple, dtype,
                 protocol_version: int, requester: int = 0,
                 io_timeout: float = 10.0, deadline_s: float = 15.0,
                 wire=None, peer: int = 0):
    """Fetch the whole blocks of ``tokens`` beyond ``n_have`` from the
    donor at (host, port). Returns (n_match, start_block, blocks) —
    n_match is the donor's whole-block answer in tokens (the shadow
    verdict even when nothing is fetched), blocks a list of host
    (L, KVH, bl, hs) K/V pairs. Raises KVTransferError/OSError on any
    failure; ``deadline_s`` bounds the WHOLE transfer (each frame's recv
    runs under the remaining budget), so a stalled donor degrades within
    the bound instead of holding the request hostage."""
    t_end = time.monotonic() + float(deadline_s)

    def budget() -> float:
        left = t_end - time.monotonic()
        if left <= 0:
            raise KVTransferError("transfer deadline exceeded")
        return min(float(io_timeout), left)

    acct_tx = _mk_acct(wire, peer, "tx")
    acct_rx = _mk_acct(wire, peer, "rx")
    sock = socket.create_connection((host, int(port)), timeout=budget())
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_frame(sock, 100, [protocol_version], timeout=budget(),
                    acct=acct_tx)  # RMSG_HELLO
        ack = _recv_frame(sock, timeout=budget(), acct=acct_rx)
        if (ack is None or ack[0] != 101 or len(ack[1]) < 2
                or not ack[1][1]):  # RMSG_HELLO_ACK [version, ok, ...]
            raise KVTransferError(f"donor handshake rejected: {ack!r}")
        _send_frame(sock, RMSG_BLOCK_QUERY,
                    [int(requester), int(n_have), *tokens],
                    timeout=budget(), acct=acct_tx)
        ans = _recv_frame(sock, timeout=budget(), acct=acct_rx)
        if ans is None or ans[0] != RMSG_BLOCK_ACK or len(ans[1]) < 7:
            raise KVTransferError(f"bad block ack: {ans!r}")
        (n_match, bl, n_l, kvh, hs, dtype_code, payload) = [
            int(v) for v in ans[1][:7]]
        if n_match <= max(n_have, 0):
            return n_match, 0, []  # donor can't help: the MISS verdict
        try:
            want_shape = tuple(block_shape)
            if (bl != block_len or (n_l, kvh, bl, hs) != want_shape
                    or CODE_DTYPES.get(dtype_code)
                    != np.dtype(dtype).name):
                raise KVTransferError(
                    f"donor block geometry ({n_l},{kvh},{bl},{hs})/"
                    f"{CODE_DTYPES.get(dtype_code)} != local "
                    f"{want_shape}/{np.dtype(dtype).name}")
            one = n_l * kvh * bl * hs * np.dtype(dtype).itemsize
            if payload != 2 * one:
                raise KVTransferError(
                    f"donor payload {payload} != modeled {2 * one}")
            start = max(n_have, 0) // bl
            end = n_match // bl
            _send_frame(sock, RMSG_BLOCK_FETCH, [start, end],
                        timeout=budget(), acct=acct_tx)
            blocks: list = []
            expect = start
            while True:
                fr = _recv_frame(sock, timeout=budget(), acct=acct_rx)
                if fr is None:
                    raise KVTransferError(
                        f"donor closed mid-transfer after "
                        f"{len(blocks)}/{end - start} blocks")
                if fr[0] == RMSG_BLOCK_END:
                    break
                if fr[0] != RMSG_BLOCK_DATA or len(fr[2]) != payload:
                    raise KVTransferError(
                        f"bad block frame kind={fr[0]} "
                        f"payload={len(fr[2])}")
                if int(fr[1][0]) != expect:
                    raise KVTransferError(
                        f"out-of-order block {fr[1][0]} "
                        f"(expected {expect})")
                expect += 1
                buf = fr[2]
                k = np.frombuffer(buf[:one],
                                  dtype=np.dtype(dtype)).reshape(
                    n_l, kvh, bl, hs)
                v = np.frombuffer(buf[one:],
                                  dtype=np.dtype(dtype)).reshape(
                    n_l, kvh, bl, hs)
                blocks.append((k, v))
            if len(blocks) != end - start:
                raise KVTransferError(
                    f"short transfer: {len(blocks)}/{end - start} "
                    "blocks")
            return n_match, start, blocks
        except KVTransferError as e:
            e.answered = n_match  # the query WAS answered: a failure
            raise                 # past it still carries the verdict
        except (OSError, ClusterProtocolError, socket.timeout) as e:
            raise KVTransferError(f"transfer failed after the query "
                                  f"answered: {type(e).__name__}: {e}",
                                  answered=n_match) from e
    finally:
        try:
            sock.close()
        except OSError:
            pass


def fill_from_wire(sched, tokens: list[int], host: str, port: int,
                   expected: int, *, stats, protocol_version: int,
                   trace_id: int = 0, requester: int = 0,
                   donor_peer: int = 0, io_timeout: float = 10.0,
                   deadline_s: float = 15.0) -> int:
    """One cache FILL over the wire into ``sched``'s radix tree, before
    the request is admitted. Returns the donor's whole-block answer in
    tokens (the shadow-staleness verdict: < expected means the donor
    evicted what the router's shadow still promised), or -1 when there
    is NO verdict (donor unreachable/deadline/a torn transfer — the
    donor may be mid-respawn, so the shadow must not be cleared off it).
    NEVER raises: every failure degrades to a plain local re-prefill."""
    st = stats
    with st.lock:
        st.fills_requested += 1
    t0 = time.perf_counter()
    verdict, got, fell_back = -1, 0, False
    try:
        pc = sched.prefix_cache
        if pc is None:
            fell_back = True
            return -1
        n_have = sched.kv_match_len(tokens)
        if n_have >= expected:
            return -1  # already warm locally: nothing to fetch, no verdict
        eng = sched.engine
        n_match, start, blocks = fetch_prefix(
            host, port, tokens, n_have, block_len=pc.block_len,
            block_shape=(eng.spec.n_layers, eng.spec.n_kv_heads,
                         pc.block_len, eng.spec.head_size),
            dtype=eng.cache_dtype, protocol_version=protocol_version,
            requester=requester, io_timeout=io_timeout,
            deadline_s=deadline_s, wire=st.wire, peer=donor_peer)
        verdict = n_match
        if n_match < expected:
            with st.lock:
                st.fill_misses += 1
        if not blocks:
            return verdict
        payload = block_payload_bytes(
            eng.spec.n_layers, eng.spec.n_kv_heads, pc.block_len,
            eng.spec.head_size, eng.cache_dtype)
        with st.lock:
            st.bytes_rx += payload * len(blocks)
        got = sched.kv_import_prefix(tokens, start, blocks)
        if got > 0:
            with st.lock:
                st.fills_ok += 1
                st.tokens_filled += got
                st.blocks_filled += got // pc.block_len
        else:
            fell_back = True
        return verdict
    except Exception as e:  # noqa: BLE001 — degrade, NEVER fail the
        # request: besides the socket/protocol shapes, a supervisor
        # rebuild mid-import can raise out of jax (deleted donated
        # arena), and a frozen compile ledger a structured RequestError
        # — all of them must end in a plain local re-prefill
        fell_back = True
        # a failure AFTER the donor answered the query still carries
        # the answer — the shadow-staleness verdict survives the loss
        verdict = max(verdict, getattr(e, "answered", -1))
        return verdict
    finally:
        if fell_back:
            with st.lock:
                st.fill_fallbacks += 1
        ms = (time.perf_counter() - t0) * 1e3
        st.note_transfer_ms(ms)
        if TRACER.enabled and trace_id:
            TRACER.event("kv_fill", trace_id, donor=donor_peer,
                         transport="wire", expected=expected,
                         answered=verdict, filled=got,
                         ms=round(ms, 3), ok=got > 0)


def local_fill(donor_sup, target_sup, tokens: list[int], *, stats,
               trace_id: int = 0, donor_id: int = 0) -> int:
    """The thread-tier fill: donor and target schedulers share one
    process, so blocks hop arena -> host -> arena with no socket (the
    same export/import executables as the wire path — parity bars are
    transport-invariant). Same degrade-never-fail contract and return
    semantics as :func:`fill_from_wire`."""
    st = stats
    with st.lock:
        st.fills_requested += 1
    t0 = time.perf_counter()
    verdict, got, fell_back = -1, 0, False
    try:
        sched_d = donor_sup._sched
        sched_t = target_sup._sched
        pc_t = sched_t.prefix_cache
        pc_d = sched_d.prefix_cache
        if pc_t is None or pc_d is None \
                or pc_t.block_len != pc_d.block_len:
            fell_back = True
            return -1
        bl = pc_t.block_len
        n_have = sched_t.kv_match_len(tokens)
        n_match, ids, pins = sched_d.kv_export_pin(tokens)
        try:
            verdict = n_match
            if n_match <= n_have:
                with st.lock:
                    st.fill_misses += 1
                    st.queries_served += 1
                    st.query_misses += 1
                return verdict
            with st.lock:
                st.queries_served += 1
            start = n_have // bl
            payload = block_payload_bytes(
                sched_d.engine.spec.n_layers,
                sched_d.engine.spec.n_kv_heads, bl,
                sched_d.engine.spec.head_size,
                sched_d.engine.cache_dtype)
            blocks = []
            for i in range(start, n_match // bl):
                blocks.append(sched_d.kv_export_block(ids[i]))
                with st.lock:
                    st.blocks_exported += 1
                    st.bytes_tx += payload
        finally:
            sched_d.kv_unpin(pins)
        with st.lock:
            st.bytes_rx += payload * len(blocks)
        got = sched_t.kv_import_prefix(tokens, start, blocks)
        if got > 0:
            with st.lock:
                st.fills_ok += 1
                st.tokens_filled += got
                st.blocks_filled += got // bl
        else:
            fell_back = True
        return verdict
    except Exception:  # noqa: BLE001 — degrade, never fail the request
        fell_back = True
        return verdict
    finally:
        if fell_back:
            with st.lock:
                st.fill_fallbacks += 1
        ms = (time.perf_counter() - t0) * 1e3
        st.note_transfer_ms(ms)
        if TRACER.enabled and trace_id:
            TRACER.event("kv_fill", trace_id, donor=donor_id,
                         transport="local", answered=verdict,
                         filled=got, ms=round(ms, 3), ok=got > 0)
