"""Token sampler: greedy argmax, temperature multinomial, top-p nucleus.

Behavioral port of the reference Sampler (ref: src/tokenizer.cpp:231-364)
with the same xorshift coin-flip stream, so a fixed seed reproduces the
reference's sampling decisions given identical logits. Vectorized with numpy
(the reference loops per element); the sort is stable-descending which
matches the reference qsort comparator's ordering of distinct values
(ref: src/tokenizer.cpp:257-263).

An on-device (jnp) greedy path is provided separately in the engine for
latency; this host sampler is the full-featured reference-parity path.
A C++ twin (native/dllama_native.cpp, parity-tested in tests/test_native.py)
is used automatically when built — backend="python" forces this oracle.
"""

from __future__ import annotations

import numpy as np

from .utils.rng import xorshift_f32


def topp_nucleus(probs: np.ndarray, topp: float):
    """The reference's top-p nucleus (ref: src/tokenizer.cpp:265-306):
    cutoff pre-filter, stable-descending sort, truncation index at
    cumulative > topp INCLUDING the crossing element. Returns (order,
    cum, last) — token ids sorted by prob, float64 cumulative mass, and
    the inclusive truncation index. Shared by Sampler._sample_topp and
    the speculative target_dist so the rejection-resampling mode's
    distribution-exactness cannot drift from the sampler."""
    n = probs.shape[0]
    cutoff = (1.0 - topp) / (n - 1)
    cand = np.nonzero(probs >= cutoff)[0]
    if cand.size == 0:
        # near-uniform probs with topp < 1/n can leave no candidate
        # (the reference would read out of bounds here); keep the
        # (first) argmax so the nucleus is never empty — mirrored by
        # the native twin and the device sampler
        cand = np.array([int(np.argmax(probs))])
    order = cand[np.argsort(-probs[cand], kind="stable")]
    cum = np.cumsum(probs[order].astype(np.float64))
    over = np.nonzero(cum > topp)[0]
    last = int(over[0]) if over.size else len(order) - 1
    return order, cum, last


class Sampler:
    def __init__(self, vocab_size: int, temperature: float, topp: float,
                 seed: int, backend: str = "auto"):
        self.vocab_size = vocab_size
        self.temperature = float(temperature)
        self.topp = float(topp)
        self._native = None
        if backend in ("auto", "native"):
            from . import native

            if native.available():
                self._native = native.NativeSampler(
                    vocab_size, temperature, topp, seed)
            elif backend == "native":
                raise RuntimeError("native backend requested but "
                                   "libdllama_native.so is not built")
        self._rng_state = seed & ((1 << 64) - 1)

    @property
    def rng_state(self) -> int:
        if self._native is not None:
            return self._native.rng_state
        return self._rng_state

    @rng_state.setter
    def rng_state(self, v: int) -> None:
        if self._native is not None:
            self._native.rng_state = v
        else:
            self._rng_state = v & ((1 << 64) - 1)

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)
        if self._native is not None:
            self._native.set_temp(temperature)

    def set_seed(self, seed: int) -> None:
        self.rng_state = seed & ((1 << 64) - 1)

    def next_seed(self) -> int:
        """Advance the xorshift stream one step and return the new state as
        a 64-bit seed for derived per-request RNGs (sampled speculation,
        runtime/engine.generate_lookup_sampled_stream). Replicated
        processes holding identical sampler state derive identical seeds —
        the invariant the API server's multihost lock-step rests on — while
        consecutive calls yield fresh seeds (two identical back-to-back
        sampled-speculation requests must not produce identical text, just
        like two plain sampled requests don't)."""
        s, _ = xorshift_f32(self.rng_state)
        self.rng_state = s
        return s

    def _coin(self) -> float:
        self._rng_state, v = xorshift_f32(self._rng_state)
        return v

    def sample(self, logits: np.ndarray) -> int:
        if self._native is not None:
            return self._native.sample(logits)
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)[: self.vocab_size]
        if self.temperature == 0.0:
            return int(np.argmax(logits))
        x = logits / self.temperature
        # softmax with max-subtraction (ref: src/funcs.cpp:63-92)
        x = np.exp(x - x.max())
        probs = x / x.sum()
        coin = self._coin()
        if self.topp <= 0 or self.topp >= 1:
            return self._sample_mult(probs, coin)
        return self._sample_topp(probs, coin)

    def sample_batch(self, logits: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """Sample one token per SELECTED row of a (B, V) logits batch,
        consuming the shared xorshift stream in row order for the selected
        rows — token-for-token identical to calling sample() per selected
        row (parity-tested). The dp batch decode path's host sampler.

        DELIBERATELY a per-row loop. Batched numpy rewrites were built
        and MEASURED (V=32k, B=1/8/64, peaked and near-uniform logits)
        and every one lost to the loop: batched axis-1 argmax 0.3-0.5x
        (numpy's axis-wise reduction overhead exceeds B flat 1-D argmax
        calls), batched-CDF multinomial 0.3-0.8x (O(B*V) comparisons vs
        the loop's O(B log V) searchsorted), and three top-p variants —
        padded axis-wise stable argsort, flat two-key lexsort +
        segment-reduceat, argpartition top-K windows — all 0.3-0.9x (the
        padding/copies/flat-sort overhead exceeds the ~0.1 ms/row Python
        constant they remove; the nucleus sort is real per-row work).
        Host sampling at V=32k is numpy-bound, not Python-bound. The
        scaling answer for large-dp sampled serving is the on-device
        sampler (--device-sampling, per-row xorshift streams on the
        chip); this host path is the reference-parity mode.

        Returns (B,) int64 tokens; unselected rows hold -1."""
        out = np.full(np.asarray(logits).shape[0], -1, np.int64)
        for i in np.nonzero(np.asarray(mask, bool))[0]:
            out[i] = self.sample(logits[i])
        return out

    def _sample_mult(self, probs: np.ndarray, coin: float) -> int:
        # ref: src/tokenizer.cpp:244-255
        cdf = np.cumsum(probs.astype(np.float64))
        idx = int(np.searchsorted(cdf, coin, side="right"))
        return min(idx, self.vocab_size - 1)

    def _sample_topp(self, probs: np.ndarray, coin: float) -> int:
        # sample within the truncated nucleus mass (topp_nucleus holds the
        # construction, shared with speculative.target_dist)
        order, cum, last = topp_nucleus(probs, self.topp)
        r = coin * cum[last]
        idx = int(np.searchsorted(cum[: last + 1], r, side="right"))
        idx = min(idx, last)
        return int(order[idx])
