"""On-device token sampling — temperature / multinomial / top-p inside jit.

Net-new vs the reference, whose sampler is inherently CPU-side per token
(ref: src/tokenizer.cpp:231-364): here the whole sampling step (softmax,
CDF draw, nucleus truncation) runs on the TPU inside the decode program, so
sampled generation can use the same fully-on-device lax.scan as greedy
decode (Engine.generate_device) — no host round-trip per token.

The RNG is the reference's 64-bit xorshift* (ref: src/utils.cpp:53-64)
implemented bit-exactly on two uint32 limbs (JAX x64 is off), so the coin
stream matches utils/rng.py for any seed. Sampling semantics mirror
sampler.Sampler step for step; the one deviation is CDF accumulation in
f32 on device vs float64 on host, which can pick a neighboring token only
when the coin lands within f32 epsilon of a CDF boundary (~1e-6/step odds).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_U32 = jnp.uint32


def state_from_seed(seed: int) -> jnp.ndarray:
    """(2,) uint32 [hi, lo] device RNG state from a 64-bit seed."""
    seed &= (1 << 64) - 1
    import numpy as np

    return jnp.asarray(
        np.array([seed >> 32, seed & 0xFFFFFFFF], np.uint32))


def _mulhi_u32(a, b):
    """High 32 bits of a 32x32 multiply, via 16-bit limbs (no u64)."""
    a0, a1 = a & _U32(0xFFFF), a >> 16
    b0, b1 = b & _U32(0xFFFF), b >> 16
    p00, p01 = a0 * b0, a0 * b1
    p10, p11 = a1 * b0, a1 * b1
    mid = (p00 >> 16) + (p01 & _U32(0xFFFF)) + (p10 & _U32(0xFFFF))
    return p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)


def xorshift_step(state: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One xorshift* step on (2,) uint32 [hi, lo]; returns (state', u32
    sample) — bit-identical to utils/rng.xorshift_u32."""
    hi, lo = state[0], state[1]
    hi, lo = hi ^ (hi >> 12), lo ^ ((lo >> 12) | (hi << 20))
    hi, lo = hi ^ ((hi << 25) | (lo >> 7)), lo ^ (lo << 25)
    hi, lo = hi ^ (hi >> 27), lo ^ ((lo >> 27) | (hi << 5))
    # sample = bits 32..63 of state * 0x2545F4914F6CDD1D (mod 2^64)
    mh, ml = _U32(0x2545F491), _U32(0x4F6CDD1D)
    sample = _mulhi_u32(lo, ml) + lo * mh + hi * ml
    return jnp.stack([hi, lo]), sample


def coin_f32(state: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Random f32 in [0, 1) (ref: src/utils.cpp:61-64)."""
    state, u = xorshift_step(state)
    return state, (u >> 8).astype(jnp.float32) * jnp.float32(1.0 / 16777216.0)


def sample_token(logits: jnp.ndarray, state: jnp.ndarray,
                 temperature: float, topp: float,
                 _force_full: bool = False
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sample one token id from (vocab,) logits; returns (token i32, state').

    temperature/topp are STATIC (the engine compiles per sampler config),
    matching sampler.Sampler.sample's branch structure: temperature 0 ->
    argmax (no coin drawn); topp outside (0, 1) -> plain multinomial; else
    the reference's cutoff-prefilter + sort + truncate nucleus sampling
    (ref: src/tokenizer.cpp:231-306).
    """
    if temperature == 0.0:
        return jnp.argmax(logits).astype(jnp.int32), state

    x = logits.astype(jnp.float32) / jnp.float32(temperature)
    x = jnp.exp(x - x.max())
    probs = x / x.sum()
    state, coin = coin_f32(state)
    n = probs.shape[0]

    if topp <= 0 or topp >= 1:
        cdf = jnp.cumsum(probs)
        idx = jnp.searchsorted(cdf, coin, side="right")
        return jnp.minimum(idx, n - 1).astype(jnp.int32), state

    cutoff = jnp.float32((1.0 - topp) / (n - 1))
    keep = probs >= cutoff
    # near-uniform probs with topp < 1/n can leave no candidate, which
    # would wrap `last` negative below; keep the (first) argmax then —
    # the same fallback as the host Sampler and the native twin
    keep = jnp.where(keep.any(), keep, jnp.arange(n) == jnp.argmax(probs))
    # non-candidates carry key -1 < 0 <= any candidate prob, so they sink
    # to the tail of any descending order and contribute 0 to the cdf
    key = jnp.where(keep, probs, -1.0)
    n_cand = jnp.sum(keep) - 1  # last candidate position, if none exceed topp

    def _pick(order_p: jnp.ndarray, order_i: jnp.ndarray) -> jnp.ndarray:
        """Truncate a descending candidate order at cum > topp and draw —
        the shared tail of both the fast and the full path."""
        p_sorted = jnp.where(order_p >= 0, order_p, 0.0)
        cum = jnp.cumsum(p_sorted)
        over = cum > jnp.float32(topp)
        last = jnp.where(over.any(), jnp.argmax(over),
                         jnp.minimum(n_cand, order_p.shape[0] - 1))
        total = cum[last]
        r = coin * total
        idx = jnp.minimum(jnp.searchsorted(cum, r, side="right"), last)
        return order_i[idx].astype(jnp.int32)

    def _full(_) -> jnp.ndarray:
        order = jnp.argsort(-key, stable=True)
        return _pick(key[order], order)

    # FAST PATH: a full (vocab,) argsort per token is the sampled-decode
    # hot-path cost (measured ~1 ms/row/step at 32k vocab — ~8 ms of a
    # 31 ms batch-8 step). The nucleus almost always lives in the top few
    # hundred probs, so take an exact top-k window and use it whenever the
    # truncation provably lands inside (cum > topp within the window, or
    # fewer than k candidates exist); otherwise lax.cond runs the full
    # sort. Tie order matches: lax.top_k breaks value ties by lower index,
    # exactly like the stable descending argsort — token streams are
    # IDENTICAL to the full path either way.
    k = 512
    if _force_full or n <= 2 * k:
        return _full(None), state
    topv, topi = lax.top_k(key, k)
    in_window = (jnp.cumsum(jnp.maximum(topv, 0.0)) > jnp.float32(topp)
                 ).any() | (n_cand < k)
    tok = lax.cond(in_window, lambda _: _pick(topv, topi), _full, None)
    return tok, state
