"""RMS normalization.

Same math as the reference (ref: src/funcs.cpp:94-145): inv = 1/sqrt(mean(x^2)
+ 1e-5), o = w * (inv * x). The 1e-5 epsilon is added AFTER the mean, matching
the reference exactly. Computed in f32 regardless of the activation dtype —
the reference keeps the residual stream f32 too.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

RMS_EPS = 1e-5


def rms_inv(x: jnp.ndarray) -> jnp.ndarray:
    """1/rms over the last axis, keepdims. (ref: src/funcs.cpp:94-123)"""
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return lax.rsqrt(ms + RMS_EPS)


def rmsnorm(x: jnp.ndarray, weight: jnp.ndarray) -> jnp.ndarray:
    """o = weight * (x / rms(x)) in f32, cast back to x.dtype.

    (ref: src/funcs.cpp:125-145)
    """
    xf = x.astype(jnp.float32)
    out = weight.astype(jnp.float32) * (rms_inv(xf) * xf)
    return out.astype(x.dtype)
