"""Attention over a pre-filled KV cache.

TPU-native replacement for the reference's serial per-head loop
(ref: src/llama2-tasks.cpp:54-94): one masked `dot_general` pair that XLA
tiles onto the MXU, with GQA handled by reshaping query heads into
(kv_head, group) blocks instead of the reference's `h / kvMul` indexing.

Numerics match the reference: scores = q·k / sqrt(head_size), softmax with
max-subtraction over positions t <= pos, f32 accumulation.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30


def decode_attention(
    q: jnp.ndarray,        # (B, T, H, hs) — rotated queries
    k_cache: jnp.ndarray,  # (B, S, KVH, hs) — cache already updated at query positions
    v_cache: jnp.ndarray,  # (B, S, KVH, hs)
    q_pos: jnp.ndarray,    # (B, T) absolute position of each query token
) -> jnp.ndarray:
    """Causal attention of T query tokens against the full cache.

    Works for decode (T=1) and chunked prefill (T>1). Returns (B, T, H, hs).
    """
    b, t, h, hs = q.shape
    s = k_cache.shape[1]
    kvh = k_cache.shape[2]
    group = h // kvh  # ref kvMul: src/llama2-tasks.cpp:60

    qf = q.astype(jnp.float32).reshape(b, t, kvh, group, hs)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)

    # scores: (B, T, KVH, G, S)
    scores = jnp.einsum("btkgh,bskh->btkgs", qf, kf) / jnp.sqrt(jnp.float32(hs))
    # causal mask: cache position s visible iff s <= q_pos
    mask = jnp.arange(s)[None, None, :] <= q_pos[..., None]  # (B, T, S)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("btkgs,bskh->btkgh", probs, vf)
    return out.reshape(b, t, h, hs).astype(q.dtype)
