"""Attention over a pre-filled KV cache.

TPU-native replacement for the reference's serial per-head loop
(ref: src/llama2-tasks.cpp:54-94): one masked `dot_general` pair that XLA
tiles onto the MXU, with GQA handled by reshaping query heads into
(kv_head, group) blocks instead of the reference's `h / kvMul` indexing.

Numerics match the reference: scores = q·k / sqrt(head_size), softmax with
max-subtraction over positions t <= pos, f32 accumulation.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30


def is_narrow_cache(dtype) -> bool:
    """True for sub-bf16 KV-cache dtypes (the fp8 option). The contract:
    writes saturate then narrow (models/transformer._to_cache_dtype), reads
    upcast k/v at the dot operand so q and the softmax state never drop
    below the compute dtype (here and in ops/pallas_attention.py)."""
    return jnp.dtype(dtype).itemsize < 2


def decode_attention(
    q: jnp.ndarray,        # (B, T, H, hs) — rotated queries
    k_cache: jnp.ndarray,  # (B, KVH, S, hs) — cache already updated at query positions
    v_cache: jnp.ndarray,  # (B, KVH, S, hs)
    q_pos: jnp.ndarray,    # (B, T) absolute position of each query token
) -> jnp.ndarray:
    """Causal attention of T query tokens against the full cache.

    Works for decode (T=1) and chunked prefill (T>1). Returns (B, T, H, hs).
    """
    b, t, h, hs = q.shape
    kvh = k_cache.shape[1]
    s = k_cache.shape[2]
    group = h // kvh  # ref kvMul: src/llama2-tasks.cpp:60

    # keep k/v in their cache dtype: upcasting the whole cache to f32 would
    # materialize 2x f32 copies in HBM (measured 6.7 -> 1.6 ms/token for
    # 32 layers @ seq 2048 on v5e after this change); the MXU accumulates
    # bf16 contractions in f32 natively via preferred_element_type. The
    # cache is head-major (see models/transformer.KVCache) so each head's
    # (S, hs) panel reads sequentially. Sub-bf16 caches (the fp8 option —
    # half the cache bytes) upcast at the dot operand, where XLA fuses the
    # convert into the read; q/probs never narrow below the compute dtype.
    if is_narrow_cache(k_cache.dtype):
        k_cache = k_cache.astype(q.dtype)
        v_cache = v_cache.astype(q.dtype)
    qg = q.reshape(b, t, kvh, group, hs)

    # scores: (B, T, KVH, G, S)
    scores = jnp.einsum("btkgh,bksh->btkgs", qg, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hs))
    # causal mask: cache position s visible iff s <= q_pos
    mask = jnp.arange(s)[None, None, :] <= q_pos[..., None]  # (B, T, S)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("btkgs,bksh->btkgh", probs.astype(k_cache.dtype), v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, h, hs).astype(q.dtype)
