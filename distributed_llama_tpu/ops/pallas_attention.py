"""Pallas TPU kernel: flash attention over the KV cache (decode AND chunked
prefill).

TPU-native replacement for the reference's serial per-head attention loop
(ref: src/llama2-tasks.cpp:54-94). XLA's fused decode attention kept
assigning the KV cache a head-minor layout (32 kv heads in the 128-lane
dim -> 4x lane waste, ~75 GB/s effective on v5e); and for prefill chunks the
dense path materializes the full (B, T, KVH, G, S) score tensor in HBM
(ops/attention.py:56-63 — 67 MB per layer at T=256/S=2048). This kernel
fixes both by construction: each grid step streams one head's (SB, hs)
key/value panel — hs=128 exactly fills the lanes — against the head's
(T*G, hs) query panel, and keeps the running softmax state in VMEM scratch,
so scores never touch HBM.

Shapes: q (B, T, H, hs) with H = KVH * G (GQA group, ref kvMul:
src/llama2-tasks.cpp:60), reshaped here to (B*KVH, T*G, hs) row panels;
k/v cache (B, KVH, S, hs). Grid is (B*KVH, S/SB) with the sequence
dimension innermost: scratch acc/m/l carry the online-softmax state across
S blocks of the same head (flash decomposition), reset at block 0 and
finalized at the last block.

Causality: query row r (= token t*G + g) attends to cache positions
s <= pos0[b] + r//G — the cache is already updated at the chunk's
positions; positions beyond the last query — including cache slots not yet
written — are masked with -inf before the softmax.

HBM scaling with context: pos rides in as a scalar-prefetch operand and the
K/V index maps CLAMP the sequence-block index at the block containing the
chunk's LAST query position — Mosaic skips the DMA when consecutive grid
steps map to the same block, so the kernel reads ~pos bytes of cache, not
the full preallocated seq_len (at 7B/seq 2048 that dead read was
~1 GB/token early in a session); the repeated block's scores are fully
masked, and a pl.when skips its compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# TPUCompilerParams is the pre-rename spelling on jaxlib 0.4.x (the CPU CI
# pin); resolved once so a third rename fails loudly at import, not as a
# NoneType call deep in a trace
_COMPILER_PARAMS = (getattr(pltpu, "CompilerParams", None)
                    or getattr(pltpu, "TPUCompilerParams", None))
if _COMPILER_PARAMS is None:  # pragma: no cover
    raise ImportError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams — unsupported jax version for the flash kernels")

DEF_BLOCK_S = 512
NEG_INF = -1e30
F8_DTYPE = jnp.float8_e4m3fn


def _f8_bits_to(u8, out_dtype):
    """e4m3fn bits (uint8) -> out_dtype, vectorized f32-bit reassembly.

    Mosaic's own fp8 `astype` on v5e (no native fp8) lowers to a slow
    conversion that cost +0.74 ms/layer/token at 8k fill — the whole fp8
    KV-cache regression of BENCH_r04 (tools/exp_f8_flash.py: astype 4.447
    vs 3.686 ms/call for this decode, bit-exact). 16-bit vector shifts are
    also unsupported, so the reassembly stays in 32-bit lanes: a normal
    number's f32 bits are sign<<31 | (exp+120)<<23 | mant<<20; subnormals
    (mag < 8) take an int->float ladder (value = mant * 2^-9, exact in
    3 mantissa bits). Writes saturate (models/transformer._to_cache_dtype)
    and seeding boundaries sanitize (saturate_f8_nan_codes below), so
    NaN/inf bit patterns never occur in the cache."""
    i = u8.astype(jnp.int32)
    sign = (i & 0x80) << 24
    mag = i & 0x7F
    normal = (mag << 20) + (120 << 23)
    sub = mag.astype(jnp.float32) * jnp.float32(2.0 ** -9)
    bits = jnp.where(mag < 8, jax.lax.bitcast_convert_type(sub, jnp.int32),
                     normal) | sign
    f = jax.lax.bitcast_convert_type(bits, jnp.float32)
    return f if out_dtype == jnp.float32 else f.astype(out_dtype)


def saturate_f8_nan_codes(x):
    """Map e4m3fn NaN bit patterns (magnitude 0x7F) to the saturated max
    (+-448) so they can never reach ``_f8_bits_to``, which decodes the
    0x7F magnitude as a finite 480.0 (ADVICE r5).

    The kernel's correctness rests on the invariant that every cache
    producer saturates (models/transformer._to_cache_dtype) — true for
    all in-engine writes, but NOT enforceable for bytes that arrive from
    OUTSIDE a forward: a checkpoint-restored session file
    (Engine.load_session) or a prefix-cache arena seed
    (Engine.slot_seed_prefix) could carry 0x7F from a buggy or foreign
    producer, and one such byte at position p poisons every later
    attention read past p. This is the guard every cache-SEEDING
    boundary applies (Engine._seed_guard); non-f8 inputs pass through
    untouched. Saturating (rather than asserting) keeps the seeding
    paths jittable — a device-side assert would be a host callback in
    the serving hot path."""
    if x.dtype != F8_DTYPE:
        return x
    bits = jax.lax.bitcast_convert_type(x, jnp.uint8)
    mag = bits & jnp.uint8(0x7F)
    fixed = jnp.where(mag == jnp.uint8(0x7F),
                      (bits & jnp.uint8(0x80)) | jnp.uint8(0x7E), bits)
    return jax.lax.bitcast_convert_type(fixed.astype(jnp.uint8), F8_DTYPE)
# cap on T*G query rows per head panel: bounds the (rows, SB) f32 score tile
# in VMEM (1024x512x4 = 2 MB; acc another 512 KB). Prefill chunks above it
# fall back to the dense path — the engine's default chunk (256) stays under
# for G <= 4
MAX_Q_ROWS = 1024


def _kernel(pos_ref, q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref,
            *, sb, n_sb, kvh, t, g, scale, out_dtype):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    b = pl.program_id(0) // kvh
    pos = pos_ref[b]  # first query row's absolute position

    # blocks entirely past the last query position are fully masked: their
    # K/V DMA was clamped away (see index maps) and their compute is skipped
    @pl.when(j * sb <= pos + t - 1)
    def _accumulate():
        q = q_ref[0]                               # (T*G, hs)
        k = k_ref[0]                               # (SB, hs)
        v = v_ref[0]
        if k.dtype == F8_DTYPE:
            # e4m3 cache: HBM/VMEM/DMA stay narrow; reinterpret the block's
            # bits in-register (free) and do the exact upcast as cheap
            # 32-bit-lane VPU work before the dot (Mosaic's fp8 astype was
            # the BENCH_r04 2.3x f8 stall; an XLA-side whole-cache bitcast
            # materialized a copy per step and cost another ~50%)
            k = _f8_bits_to(jax.lax.bitcast_convert_type(k, jnp.uint8),
                            q.dtype)
            v = _f8_bits_to(jax.lax.bitcast_convert_type(v, jnp.uint8),
                            q.dtype)
        elif k.dtype != q.dtype:
            # other sub-bf16 cache dtypes: generic per-block upcast
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)

        dot = functools.partial(
            jax.lax.dot_general,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )
        scores = dot(q, k, dimension_numbers=(((1,), (1,)), ((), ()))) * scale  # (T*G, SB)

        # causal: row r is query token r//G at absolute position pos + r//G
        row_pos = pos + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0) // g
        s_pos = j * sb + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(s_pos <= row_pos, scores, NEG_INF)

        m_prev = m_ref[:]                          # (T*G, 1)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)                # (T*G, SB); masked cols underflow to 0
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = dot(p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())))
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == n_sb - 1)
    def _done():
        out_ref[0] = (acc_ref[:] / l_ref[:]).astype(out_dtype)


def _block_s(s: int) -> int:
    """SB=512 measured best across fills on v5e (a larger SB trades fewer
    grid steps for a bigger clamp over-read at low fill; A/B at seq 8192
    showed no net win)."""
    for sb in (DEF_BLOCK_S, 256, 128):
        if s % sb == 0:
            return sb
    return s


def flash_supported(t: int, h: int, kvh: int) -> bool:
    """Kernel precondition: the (T*G, SB) score tile must fit the VMEM
    budget. T == 1 (decode) always qualifies."""
    return t * (h // kvh) <= MAX_Q_ROWS


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_attention(
    q: jnp.ndarray,        # (B, T, H, hs) — rotated queries
    k_cache: jnp.ndarray,  # (B, KVH, S, hs)
    v_cache: jnp.ndarray,  # (B, KVH, S, hs)
    q_pos: jnp.ndarray,    # (B, T) absolute position of each query token
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal attention of T query tokens against the cache; returns
    (B, T, H, hs). Matches ops/attention.decode_attention semantics —
    q_pos rows must be contiguous (pos0[b] + arange(T), which is how every
    engine path builds them — models/transformer.forward)."""
    b, t, h, hs = q.shape
    kvh, s = k_cache.shape[1], k_cache.shape[2]
    g = h // kvh
    assert flash_supported(t, h, kvh), (t, g)
    sb = _block_s(s)
    n_sb = s // sb

    # kernel dots need matching operand dtypes (lax.dot_general does not
    # promote); compute dtype and cache dtype may differ. Wider caches
    # (f32) lift q; narrower caches (fp8) are lifted per-block in-kernel —
    # q and the softmax state never drop below the compute dtype
    from .attention import is_narrow_cache

    if not is_narrow_cache(k_cache.dtype):
        q = q.astype(k_cache.dtype)
    # (B, T, KVH, G, hs) -> (B*KVH, T*G, hs) row panels, one per kv head
    qh = (q.reshape(b, t, kvh, g, hs).transpose(0, 2, 1, 3, 4)
          .reshape(b * kvh, t * g, hs))
    kh = k_cache.reshape(b * kvh, s, hs)
    vh = v_cache.reshape(b * kvh, s, hs)
    pos = q_pos[:, 0].astype(jnp.int32)

    def kv_index(i, j, pos_ref):
        # clamp at the block containing the chunk's last query position:
        # steps past it re-map to the same block, so Mosaic elides their HBM
        # copy (the dead-read fix)
        return (i, jnp.minimum(j, (pos_ref[i // kvh] + t - 1) // sb), 0)

    out = pl.pallas_call(
        functools.partial(
            _kernel, sb=sb, n_sb=n_sb, kvh=kvh, t=t, g=g,
            scale=1.0 / (hs ** 0.5), out_dtype=q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * kvh, n_sb),
            in_specs=[
                pl.BlockSpec((1, t * g, hs), lambda i, j, p: (i, 0, 0)),
                pl.BlockSpec((1, sb, hs), kv_index),
                pl.BlockSpec((1, sb, hs), kv_index),
            ],
            out_specs=pl.BlockSpec((1, t * g, hs), lambda i, j, p: (i, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((t * g, hs), jnp.float32),
                pltpu.VMEM((t * g, 1), jnp.float32),
                pltpu.VMEM((t * g, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * kvh, t * g, hs), q.dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(pos, qh, kh, vh)

    return (out.reshape(b, kvh, t, g, hs).transpose(0, 2, 1, 3, 4)
            .reshape(b, t, h, hs))


def flash_decode_attention(
    q: jnp.ndarray,        # (B, T=1, H, hs)
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    q_pos: jnp.ndarray,    # (B, 1)
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-position decode attention — the T=1 case of flash_attention
    (kept as a named entry point: decode is the latency-critical path)."""
    return flash_attention(q, k_cache, v_cache, q_pos, interpret=interpret)
