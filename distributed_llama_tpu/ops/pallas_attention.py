"""Pallas TPU kernel: flash-decode attention over the KV cache.

TPU-native replacement for the reference's serial per-head attention loop
(ref: src/llama2-tasks.cpp:54-94). XLA's fused decode attention kept
assigning the KV cache a head-minor layout (32 kv heads in the 128-lane
dim -> 4x lane waste, ~75 GB/s effective on v5e); this kernel fixes the
read pattern by construction: each grid step streams one head's (SB, hs)
key/value panel — hs=128 exactly fills the lanes — and keeps the running
softmax state in VMEM scratch, so scores never touch HBM.

Shapes: q (B, KVH, G, hs) where G = n_heads/n_kv_heads (GQA group,
ref kvMul: src/llama2-tasks.cpp:60); k/v cache (B, KVH, S, hs). Grid is
(B*KVH, S/SB) with the sequence dimension innermost: scratch acc/m/l carry
the online-softmax state across S blocks of the same head (flash
decomposition), reset at block 0 and finalized at the last block.

Causality: decode attends to all cache positions s <= pos (the cache is
already updated at the query's position); positions beyond pos — including
cache slots not yet written — are masked with -inf before the softmax.

HBM scaling with context: pos rides in as a scalar-prefetch operand and the
K/V index maps CLAMP the sequence-block index at the block containing pos —
Mosaic skips the DMA when consecutive grid steps map to the same block, so
the kernel reads ~pos bytes of cache, not the full preallocated seq_len
(at 7B/seq 2048 that dead read was ~1 GB/token early in a session); the
repeated block's scores are fully masked, and a pl.when skips its compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEF_BLOCK_S = 512
NEG_INF = -1e30


def _kernel(pos_ref, q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref,
            *, sb, n_sb, kvh, scale, out_dtype):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    b = pl.program_id(0) // kvh
    pos = pos_ref[b]

    # blocks entirely past pos are fully masked: their K/V DMA was clamped
    # away (see index maps) and their compute is skipped
    @pl.when(j * sb <= pos)
    def _accumulate():
        q = q_ref[0]                               # (G, hs)
        k = k_ref[0]                               # (SB, hs)
        v = v_ref[0]
        if k.dtype != q.dtype:
            # sub-bf16 cache (fp8 option): HBM/VMEM stay narrow, the upcast
            # is per-block VPU work right before the dot
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)

        dot = functools.partial(
            jax.lax.dot_general,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )
        scores = dot(q, k, dimension_numbers=(((1,), (1,)), ((), ()))) * scale  # (G, SB)

        s_pos = j * sb + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(s_pos <= pos, scores, NEG_INF)

        m_prev = m_ref[:]                          # (G, 1)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)                # (G, SB); masked cols underflow to 0
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = dot(p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())))
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == n_sb - 1)
    def _done():
        out_ref[0] = (acc_ref[:] / l_ref[:]).astype(out_dtype)


def _block_s(s: int) -> int:
    """SB=512 measured best across fills on v5e (a larger SB trades fewer
    grid steps for a bigger clamp over-read at low fill; A/B at seq 8192
    showed no net win)."""
    for sb in (DEF_BLOCK_S, 256, 128):
        if s % sb == 0:
            return sb
    return s


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_decode_attention(
    q: jnp.ndarray,        # (B, T=1, H, hs)
    k_cache: jnp.ndarray,  # (B, KVH, S, hs)
    v_cache: jnp.ndarray,  # (B, KVH, S, hs)
    q_pos: jnp.ndarray,    # (B, T=1) absolute position of the query token
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-position decode attention; returns (B, 1, H, hs).

    Matches ops/attention.decode_attention semantics for T == 1.
    """
    b, t, h, hs = q.shape
    assert t == 1, "flash decode is T=1; prefill uses decode_attention/ring"
    kvh, s = k_cache.shape[1], k_cache.shape[2]
    g = h // kvh
    sb = _block_s(s)
    n_sb = s // sb

    # kernel dots need matching operand dtypes (lax.dot_general does not
    # promote); compute dtype and cache dtype may differ. Wider caches
    # (f32) lift q; narrower caches (fp8) are lifted per-block in-kernel —
    # q and the softmax state never drop below the compute dtype
    from .attention import is_narrow_cache

    if not is_narrow_cache(k_cache.dtype):
        q = q.astype(k_cache.dtype)
    qh = q.reshape(b, kvh, g, hs).reshape(b * kvh, g, hs)
    kh = k_cache.reshape(b * kvh, s, hs)
    vh = v_cache.reshape(b * kvh, s, hs)
    pos = q_pos[:, 0].astype(jnp.int32)

    def kv_index(i, j, pos_ref):
        # clamp at the block containing pos[b]: steps past it re-map to the
        # same block, so Mosaic elides their HBM copy (the dead-read fix)
        return (i, jnp.minimum(j, pos_ref[i // kvh] // sb), 0)

    out = pl.pallas_call(
        functools.partial(
            _kernel, sb=sb, n_sb=n_sb, kvh=kvh,
            scale=1.0 / (hs ** 0.5), out_dtype=q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * kvh, n_sb),
            in_specs=[
                pl.BlockSpec((1, g, hs), lambda i, j, p: (i, 0, 0)),
                pl.BlockSpec((1, sb, hs), kv_index),
                pl.BlockSpec((1, sb, hs), kv_index),
            ],
            out_specs=pl.BlockSpec((1, g, hs), lambda i, j, p: (i, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((g, hs), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * kvh, g, hs), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(pos, qh, kh, vh)

    return out.reshape(b, h, hs)[:, None]
