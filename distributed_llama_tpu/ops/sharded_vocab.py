"""Vocab-dim sharding: tp-split embedding gather + sharded sampling.

The reference kept the embedding and classifier head root-only
(ref: src/transformer.cpp:639,663-673) and early revisions of this repo
replicated them per device — 533 MB/chip at 70B widths, blowing the
README's own 2.42 GB/chip budget (VERDICT weak #3), plus a serialized
~0.36 ms/token full-logit head read. Megatron-LM's parallel vocab
embedding + sharded cross-entropy (PAPERS.md) is the standard fix; this
module is its inference-side analogue:

  * **Embedding** (:func:`embed_tokens_sharded`) — ``tok_emb`` lives as a
    local ``(vocab/S, dim)`` shard per device (S = the product of the
    vocab mesh axes, normally tp; under pp the table additionally splits
    over pp since the gather runs outside the manual region). The lookup
    is a masked LOCAL gather — out-of-shard token rows contribute exact
    zeros — followed by one all-reduce of the (B, T, dim) activations.
    Zeros + one real contribution add exactly in any float dtype, so the
    result is BIT-IDENTICAL to the replicated ``emb[tokens]`` gather.
  * **Head / sampling** (:func:`sharded_sample_prep`) — the logits stay
    vocab-sharded on device (wcls is row-split already); what crosses to
    the host is a tiny per-shard summary instead of the (B, vocab)
    logits:

      - greedy: local argmax + local max per shard, a (S, B) pair
        gather, and a global pick with the SAME deterministic
        lowest-index tie-break ``np.argmax`` implies (within a shard the
        local argmax picks the lowest local index; across shards the
        lowest global id among max-attaining shards wins — and any
        equal value in a lower shard has the lower global id).
      - sampled: local top-k probabilities (exact — the softmax
        denominator is a psum over shards of the per-shard masses) with
        global ids, plus each shard's k-th-largest prob as the
        EXACTNESS GUARD. The merged k·S candidates provably contain the
        global top-k: the global i-th largest value (i <= k) is within
        the top-i <= top-k of whatever shard holds it. Host-side
        (runtime/sampling.sample_candidates) the oracle's nucleus walk runs on
        the merged candidates and is EXACT whenever the truncation
        point lands strictly above the guard (every token above the
        guard is a candidate, in oracle order); otherwise the caller
        falls back to a single replicated row fetch (the parity
        oracle), so the distribution is exact in every case.

Everything traced here is a module-level body so analysis/entrypoints.py
fingerprints the SAME programs the engine jits (the
seed_rows_from_blocks discipline). Docs: docs/parallelism.md
("Vocab sharding").
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.compat import shard_map
from ..parallel.mesh import DP_AXIS


def vocab_shard_axes(mesh, vocab_size: int) -> tuple[str, ...]:
    """The mesh axes the vocab dim can row-split over: tp always (when it
    divides), pp too when present (the embedding gather and head matmul
    run OUTSIDE the manual pp region, so the table may split over both —
    each pp stage would otherwise hold a full copy it never reads for
    the other stages' tokens). Returns () when the vocab cannot split
    evenly — the caller keeps the replicated path."""
    if mesh is None:
        return ()
    tp = mesh.shape.get("tp", 1)
    pp = mesh.shape.get("pp", 1)
    if tp <= 1:
        return ()
    if pp > 1 and vocab_size % (pp * tp) == 0:
        return ("pp", "tp")
    if vocab_size % tp != 0:
        return ()
    return ("tp",)


def _axes_size(mesh, axes: tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def _shard_index(axes: tuple[str, ...], sizes: tuple[int, ...]):
    """Linear shard index along `axes` inside a manual region, matching
    PartitionSpec((axes,)) layout order (major-to-minor as listed)."""
    idx = jnp.int32(0)
    for a, s in zip(axes, sizes):
        idx = idx * s + lax.axis_index(a).astype(jnp.int32)
    return idx


def embed_tokens_local(emb_local, tokens, base, compute_dtype, axes):
    """The per-shard embedding body: masked local gather + all-reduce.
    Token ids outside [base, base + vocab/S) contribute exact zeros; the
    psum then adds zeros to the one shard's real rows — exact in any
    float dtype, so sharded == replicated bit-for-bit. Module-level so
    the audit fingerprints the program the engine runs."""
    vloc = emb_local.shape[0]
    loc = tokens.astype(jnp.int32) - base
    ok = (loc >= 0) & (loc < vloc)
    safe = jnp.clip(loc, 0, vloc - 1)
    x = emb_local[safe].astype(compute_dtype)
    x = jnp.where(ok[..., None], x, jnp.zeros((), compute_dtype))
    return lax.psum(x, axes)


def embed_tokens_sharded(emb, tokens, mesh, axes: tuple[str, ...],
                         compute_dtype):
    """(B, T) int32 tokens -> (B, T, dim) activations from a vocab-
    sharded embedding table (emb placed P(axes, None)). The output is
    replicated over the vocab axes (each shard contributed its rows);
    GSPMD reshards downstream as the consumer needs."""
    sizes = tuple(mesh.shape[a] for a in axes)
    vloc = emb.shape[0] // _axes_size(mesh, axes)

    def body(emb_local, tok):
        base = _shard_index(axes, sizes) * vloc
        return embed_tokens_local(emb_local, tok, base, compute_dtype,
                                  axes)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(axes, None), P(DP_AXIS, None)),
        out_specs=P(DP_AXIS, None, None),
        check_vma=False,
    )(emb, tokens)


# -- sharded sampling prep ---------------------------------------------------


def sample_prep_local(l_local, temps, base, n_vocab, k, axes):
    """Per-shard sampling summary over a (B, vocab/S) logits shard:

      * greedy half: (local max, local argmax as a GLOBAL id), both over
        the tokenizer vocab only (ids >= n_vocab mask to -inf — the host
        Sampler's truncation, sampler.py:69);
      * sampled half: the local top-k EXACT probabilities (softmax over
        the FULL vocab: global max by pmax, denominator by psum) with
        global ids, plus the shard's k-th-largest prob — the host-side
        exactness guard.

    temps is a traced (B,) float32 (per-row temperature — requests in a
    batch sample at different temperatures without new compile keys);
    rows with temperature 0 pass 1.0 and ignore the sampled half."""
    vloc = l_local.shape[-1]
    gid = base + jnp.arange(vloc, dtype=jnp.int32)
    valid = gid < n_vocab
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    lm = jnp.where(valid[None, :], l_local.astype(jnp.float32), neg)

    loc_max = jnp.max(lm, axis=-1)                        # (B,)
    loc_arg = base + jnp.argmax(lm, axis=-1).astype(jnp.int32)

    t = jnp.maximum(temps.astype(jnp.float32), 1e-6)[:, None]
    x = lm / t
    gmax = lax.pmax(jnp.max(x, axis=-1), axes)            # (B,)
    e = jnp.where(valid[None, :], jnp.exp(x - gmax[:, None]), 0.0)
    z = lax.psum(jnp.sum(e, axis=-1), axes)               # (B,)
    p = e / z[:, None]
    top_p, top_i = lax.top_k(p, k)                        # (B, k) desc
    top_id = base + top_i.astype(jnp.int32)
    guard = top_p[:, k - 1]                               # k-th largest
    return (loc_max[:, None], loc_arg[:, None], top_p, top_id,
            guard[:, None])


def sharded_sample_prep(logits, temps, mesh, axes: tuple[str, ...],
                        n_vocab: int, k: int):
    """(B, V) vocab-sharded logits -> the host-fetchable sampling
    summary, with the full logits NEVER gathered:

      argmax  (B,)        — the global greedy token (tie-break pinned)
      cand_p  (B, S*k)    — exact candidate probs, per-shard top-k
      cand_id (B, S*k)    — their global token ids
      guard   (B, S)      — each shard's k-th-largest prob

    The cross-shard greedy pick happens on the tiny (B, S) gathered
    pair: lowest global id among the max-attaining shards — exactly
    np.argmax's first-max rule, since ids increase with shard index."""
    sizes = tuple(mesh.shape[a] for a in axes)
    n_shards = _axes_size(mesh, axes)
    vloc = logits.shape[-1] // n_shards

    def body(l_local, t):
        base = _shard_index(axes, sizes) * vloc
        return sample_prep_local(l_local, t, base, n_vocab, k, axes)

    spec_b = P(DP_AXIS, axes)
    lmax, larg, cand_p, cand_id, guard = shard_map(
        body, mesh=mesh,
        in_specs=(P(DP_AXIS, axes), P(DP_AXIS)),
        out_specs=(spec_b, spec_b, spec_b, spec_b, spec_b),
        check_vma=False,
    )(logits, temps)
    # global greedy pick over the (B, S) summaries — GSPMD land, the
    # gather here is S values per row, not the vocab
    best = jnp.max(lmax, axis=1, keepdims=True)
    amax = jnp.min(jnp.where(lmax == best, larg, jnp.int32(2**31 - 1)),
                   axis=1).astype(jnp.int32)
    return amax, cand_p, cand_id, guard
