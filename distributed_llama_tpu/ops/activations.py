"""Hidden activations, matching the reference's exact formulas
(ref: src/funcs.cpp:490-506)."""

from __future__ import annotations

import jax.numpy as jnp

from ..models.spec import HiddenAct

_SQRT_2_OVER_PI = 0.79788456080286535587989211986876
_GELU_COEF_A = 0.044715


def silu(x: jnp.ndarray) -> jnp.ndarray:
    # x / (1 + exp(-x)) (ref: src/funcs.cpp:498-506); literals pinned to
    # f32 so the kernel dtype is explicit (dlgrind DLG104)
    xf = x.astype(jnp.float32)
    one = jnp.float32(1.0)
    return (xf / (one + jnp.exp(-xf))).astype(x.dtype)


def gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    # tanh approximation (ref: src/funcs.cpp:487-496)
    xf = x.astype(jnp.float32)
    half, one = jnp.float32(0.5), jnp.float32(1.0)
    out = half * xf * (one + jnp.tanh(
        _SQRT_2_OVER_PI * xf * (one + _GELU_COEF_A * xf * xf)))
    return out.astype(x.dtype)


def apply_hidden_act(x: jnp.ndarray, act: HiddenAct) -> jnp.ndarray:
    if act == HiddenAct.SILU:
        return silu(x)
    if act == HiddenAct.GELU:
        return gelu_tanh(x)
    raise ValueError(act)
