"""Rotary position embeddings, both reference styles.

* `rope_llama` — interleaved adjacent-pair rotation over the flat q/k vector
  with frequency exponent (i % head_size)/head_size (ref:
  src/transformer.cpp:98-135 LlamaRopeSlice). Used by LLAMA-arch models;
  the HF converter permutes q/k weights into this layout
  (ref: converter/convert-hf.py:12-15).

* `rope_falcon` — half-rotation within each head: element j pairs with
  j + head_size/2 (ref: src/transformer.cpp:137-159 FalconRopeSlice).
  Used by GROK1/MIXTRAL-arch models.

Angles are computed on the fly (a table is a trace-time constant under jit;
XLA hoists it), in f32. Functions take x shaped (..., n_heads, head_size)
and positions shaped (...-batch,) broadcastable.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..models.spec import ArchType


def _angles(pos: jnp.ndarray, head_size: int, theta: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin of pos * theta^(-2j/head_size) for j in [0, head_size/2).

    pos: (...,) -> returns (..., head_size/2) each.
    """
    j = jnp.arange(head_size // 2, dtype=jnp.float32)
    freq = 1.0 / jnp.power(jnp.float32(theta), 2.0 * j / head_size)
    val = pos.astype(jnp.float32)[..., None] * freq
    return jnp.cos(val), jnp.sin(val)


def rope_llama(x: jnp.ndarray, pos: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Interleaved rotation: pairs (2j, 2j+1) within each head.

    x: (..., H, hs); pos broadcastable to x.shape[:-2].
    """
    *lead, h, hs = x.shape
    fcr, fci = _angles(pos, hs, theta)  # (..., hs/2)
    fcr = fcr[..., None, :]
    fci = fci[..., None, :]
    xf = x.astype(jnp.float32).reshape(*lead, h, hs // 2, 2)
    x0 = xf[..., 0]
    x1 = xf[..., 1]
    r0 = x0 * fcr - x1 * fci
    r1 = x0 * fci + x1 * fcr
    return jnp.stack([r0, r1], axis=-1).reshape(*lead, h, hs).astype(x.dtype)


def rope_falcon(x: jnp.ndarray, pos: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Half-rotation: element j pairs with j + hs/2 within each head."""
    *lead, h, hs = x.shape
    fcr, fci = _angles(pos, hs, theta)
    fcr = fcr[..., None, :]
    fci = fci[..., None, :]
    xf = x.astype(jnp.float32)
    x0 = xf[..., : hs // 2]
    x1 = xf[..., hs // 2:]
    r0 = x0 * fcr - x1 * fci
    r1 = x0 * fci + x1 * fcr
    return jnp.concatenate([r0, r1], axis=-1).astype(x.dtype)


def apply_rope(x: jnp.ndarray, pos: jnp.ndarray, theta: float, arch: ArchType) -> jnp.ndarray:
    """Arch dispatch (ref: src/transformer.cpp:391-395)."""
    if arch == ArchType.LLAMA:
        return rope_llama(x, pos, theta)
    return rope_falcon(x, pos, theta)
