from .norms import rms_inv, rmsnorm
from .activations import silu, gelu_tanh, apply_hidden_act
from .rope import rope_llama, rope_falcon, apply_rope
from .attention import decode_attention
from .matmul import matmul, WeightFormat

__all__ = [
    "rms_inv",
    "rmsnorm",
    "silu",
    "gelu_tanh",
    "apply_hidden_act",
    "rope_llama",
    "rope_falcon",
    "apply_rope",
    "decode_attention",
    "matmul",
    "WeightFormat",
]
