"""Weight-format-dispatching matmul.

TPU-native equivalent of the reference's matmul dispatcher over (weight dtype
x input dtype) pairs (ref: src/funcs.cpp:413-454). Weights are stored either
dense (f32/bf16) or as Q40 `QuantizedTensor`s kept packed in HBM; the Q40
path dequantizes inline — XLA fuses the nibble-unpack + scale multiply into
the matmul's operand read, which is the bring-up analogue of the reference's
fused Q40xQ80 NEON/AVX2 kernel (ref: src/funcs.cpp:286-385). The Pallas
int4-dot kernel (ops/pallas_q40.py) replaces this on TPU for the hot path.

Convention matches the reference: weight W has logical shape (d, n) (d output
rows), activations are (..., n), output is (..., d) = x @ W^T.
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp

from ..quants.jax_codec import QuantizedTensor, dequantize_q40_jax, quantize_q80_jax, dequantize_q80_jax

WeightFormat = Union[jnp.ndarray, QuantizedTensor]


def local_matmul(
    x: jnp.ndarray,
    w: WeightFormat,
    *,
    compute_dtype,
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-device matmul core: Pallas fused Q40 kernel when the operands
    qualify, XLA dequant einsum otherwise. Shared by matmul() and the
    shard_map per-shard bodies (parallel/tp_q80.py) so the kernel
    preconditions and fallback live in exactly one place."""
    x = x.astype(compute_dtype)
    if isinstance(w, QuantizedTensor):
        if use_pallas:
            from .pallas_q40 import q40_matmul, supports_pallas

            t = 1
            for s in x.shape[:-1]:
                t *= s
            if supports_pallas(w, t):
                return q40_matmul(x, w, out_dtype=compute_dtype,
                                  interpret=interpret)
        wd = dequantize_q40_jax(w, dtype=compute_dtype)
    else:
        wd = w.astype(compute_dtype)
    return jnp.einsum("...n,dn->...d", x, wd,
                      preferred_element_type=compute_dtype)


def matmul(
    x: jnp.ndarray,
    w: WeightFormat,
    *,
    activation_q80: bool = False,
    compute_dtype=jnp.float32,
    use_pallas: bool = False,
    tp_mesh=None,
    tp_reduce: str = "exact",
    pallas_interpret: bool = False,
    manual_tp: int = 0,
    manual_ep: int = 0,  # carried in the pp region's cfg for the MoE
    # block (ep_moe._ep_body); dense matmuls ignore it — ep shards only
    # the expert axis, every other weight is replicated across ep
    manual_sp: int = 0,  # likewise: sp shards only the KV cache's
    # sequence dim (transformer._attention_block), never a matmul operand
) -> jnp.ndarray:
    """y[..., d] = sum_n x[..., n] * W[d, n].

    activation_q80=True round-trips the activation through Q80 blocks first,
    reproducing the reference's quantized activation buffers
    (ref: src/tasks.cpp:124-148) for bit-accuracy experiments.

    use_pallas=True routes Q40 weights through the fused dequant-matmul TPU
    kernel (ops/pallas_q40.py) when its layout preconditions hold.

    tp_mesh: mesh for the explicit shard_map execution paths — weights
    arrive as TpRowWeight (row-split, communication-free) or TpColWeight
    (col-split partial sums, reduced per tp_reduce: "exact" psum or the
    reference's "q80" compressed exchange) — parallel/tp_q80.py.

    manual_tp: > 0 when the caller is ALREADY inside a fully-manual region
    (the pipeline-parallel layer loop, parallel/pp.py) with tp manual and
    this many shards: Tp-marked weights are shard-local there, so row splits
    run the local kernel directly and col splits reduce with an explicit
    psum — no shard_map entry (which cannot nest).
    """
    if activation_q80:
        q, scales = quantize_q80_jax(x)
        x = dequantize_q80_jax(q, scales, dtype=compute_dtype)
    else:
        x = x.astype(compute_dtype)

    from ..parallel.tp_q80 import (
        TpColWeight, TpRowWeight, manual_psum, tp_col_matmul, tp_row_matmul)

    if manual_tp:
        from ..parallel.mesh import TP_AXIS

        if isinstance(w, TpColWeight):
            partial = local_matmul(x, w.w, compute_dtype=compute_dtype,
                                   use_pallas=use_pallas,
                                   interpret=pallas_interpret)
            return (manual_psum(partial, TP_AXIS) if manual_tp > 1
                    else partial)
        if isinstance(w, TpRowWeight):
            w = w.w
        return local_matmul(x, w, compute_dtype=compute_dtype,
                            use_pallas=use_pallas, interpret=pallas_interpret)

    if isinstance(w, TpColWeight):
        assert tp_mesh is not None, "TpColWeight requires the mesh in cfg"
        return tp_col_matmul(x, w, tp_mesh, compute_dtype=compute_dtype,
                             reduce=tp_reduce, use_pallas=use_pallas,
                             interpret=pallas_interpret)
    if isinstance(w, TpRowWeight):
        assert tp_mesh is not None, "TpRowWeight requires the mesh in cfg"
        return tp_row_matmul(x, w, tp_mesh, compute_dtype=compute_dtype,
                             use_pallas=use_pallas,
                             interpret=pallas_interpret)

    return local_matmul(x, w, compute_dtype=compute_dtype,
                        use_pallas=use_pallas, interpret=pallas_interpret)


def fused_expert_matmul(
    x: jnp.ndarray,
    w,                      # stacked (E, d, n) weight leaf
    e: jnp.ndarray,         # traced i32 expert index
    *,
    activation_q80: bool = False,
    compute_dtype=jnp.float32,
    use_pallas: bool = False,
    tp_mesh=None,
    tp_reduce: str = "exact",
    pallas_interpret: bool = False,
    manual_tp: int = 0,
    manual_ep: int = 0,  # ignored — see matmul()
    manual_sp: int = 0,  # ignored — see matmul()
):
    """Expert-indexed matmul against a stacked (E, d, n) Q40 weight without
    materializing the expert's slice (ops/pallas_q40.q40_expert_matmul).

    Returns None when ineligible — plain-QuantizedTensor single-shard Q40
    stacks only (which includes manual-region pp layers at tp == 1, where
    the local stack is the whole weight); the caller falls back to
    gather-then-matmul (which is also what the mesh paths' Tp/Ep wrappers
    take)."""
    del tp_reduce, manual_tp
    if not (use_pallas and tp_mesh is None
            and isinstance(w, QuantizedTensor) and w.packed.ndim == 3):
        return None
    from .pallas_q40 import MAX_T, q40_expert_matmul

    t = 1
    for s in x.shape[:-1]:
        t *= s
    if t > MAX_T:
        return None
    if activation_q80:  # same round-trip matmul() applies
        q, scales = quantize_q80_jax(x)
        x = dequantize_q80_jax(q, scales, dtype=compute_dtype)
    return q40_expert_matmul(x.astype(compute_dtype), w, e,
                             out_dtype=compute_dtype,
                             interpret=pallas_interpret)
