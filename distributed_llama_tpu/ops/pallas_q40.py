"""Pallas TPU kernel: fused Q40-dequant matmul.

TPU-native replacement for the reference's hot Q40xQ80 NEON/AVX2 kernel
(ref: src/funcs.cpp:286-385). The reference streams 4.5-bit weights through
SIMD integer dot products; here the same HBM-traffic win comes from reading
the packed nibbles (0.5625 B/weight + 1/16 scale byte) and dequantizing in
VMEM right before the MXU contraction — the dense weight matrix never
touches HBM. At decode batch=1 the op is bandwidth-bound, so this beats
dequantize-to-dense + dot (which moves ~4.5 B/weight through HBM).

Layout: QuantizedTensor packed is nibble-position-major (d, 16, nb) uint8
(see quants/jax_codec.py) so the flattened lane order is m = j*nb + b.
Consequences inside the kernel:
  * the per-block scale expansion s16[d, m] = s[d, m % nb] is a lane tile —
    exactly `pltpu.repeat(s, 16)` (an element-wise repeat of the block-major
    order would need a shape cast Mosaic cannot lower);
  * no weight shuffle is needed; instead the small activation is pre-split
    outside the kernel into matching lo/hi orders:
      x_lo[t, j*nb + b] = x[t, b*32 + j]       (low-nibble elements)
      x_hi[t, j*nb + b] = x[t, b*32 + 16 + j]  (high-nibble elements)
Then  y = x_lo @ dequant(lo).T + x_hi @ dequant(hi).T  with the reference's
decoder semantics value = (nibble - 8) * scale (ref: src/quants.cpp:166-179).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants.jax_codec import QuantizedTensor

LANES = 128
DEF_TILE_D = 256


def _kernel(x_lo_ref, x_hi_ref, packed_ref, scales_ref, out_ref, *, nb, out_dtype):
    # ref decoder: (q & 0xF) - 8. Mosaic legalizes neither i8 arithmetic nor
    # u8 shifts, so widen to i32 first and keep the -8 and scale on the f32 VPU
    pk = packed_ref[:].astype(jnp.int32)                 # (TD, M=16*nb)
    lo = (pk & 0xF).astype(jnp.float32) - 8.0
    hi = (pk >> 4).astype(jnp.float32) - 8.0
    s = scales_ref[:]                                    # (TD, NB) f32 — Mosaic has no f16
    s16 = pltpu.repeat(s, 16, axis=1)                    # lane-tile -> (TD, M)
    wlo = lo * s16
    whi = hi * s16

    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = dot(x_lo_ref[:], wlo) + dot(x_hi_ref[:], whi)  # (T, TD)
    out_ref[:] = acc.astype(out_dtype)


def _tile_d(d: int, tile_d: int = DEF_TILE_D) -> int:
    """Output-dim tile: Mosaic wants the last block dim to be a multiple of
    128 lanes OR the whole array dim — so tile by 256/128 when divisible,
    else take d whole (grid of 1)."""
    for t in (tile_d, LANES):
        if d % t == 0:
            return t
    return d


def supports_pallas(w: QuantizedTensor) -> bool:
    """Kernel precondition: 2D weight (d, 16, nb) — callers slice leading
    (layer/expert) dims first. m/nb ride as full-size blocks, so no lane
    alignment is required of them."""
    return w.packed.ndim == 3


def _split_activation(x: jnp.ndarray, nb: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(T, n) -> lo/hi halves in kernel lane order m = j*nb + b."""
    t = x.shape[0]
    x4 = x.reshape(t, nb, 2, 16)                         # [t, b, half, j]
    x_lo = x4[:, :, 0, :].transpose(0, 2, 1).reshape(t, nb * 16)
    x_hi = x4[:, :, 1, :].transpose(0, 2, 1).reshape(t, nb * 16)
    return x_lo, x_hi


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def q40_matmul(
    x: jnp.ndarray,
    w: QuantizedTensor,
    out_dtype=jnp.float32,
    interpret: bool = False,
) -> jnp.ndarray:
    """y[..., d] = sum_n x[..., n] * W[d, n] with W in packed Q40 form.

    Matches matmul()'s convention (ref: src/funcs.cpp:413-454); x may have any
    leading dims. Weight stays packed through HBM; dequant happens per-tile in
    VMEM fused into the MXU contraction.
    """
    d, _, nb = w.packed.shape
    n = nb * 32
    m = nb * 16

    lead = x.shape[:-1]
    t = 1
    for s in lead:
        t *= s
    x_lo, x_hi = _split_activation(x.reshape(t, n).astype(jnp.float32), nb)

    packed2d = w.packed.reshape(d, m)
    td = _tile_d(d)
    grid = (d // td,)

    out = pl.pallas_call(
        functools.partial(_kernel, nb=nb, out_dtype=out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * t * d * n,
            bytes_accessed=d * m + d * nb * 2 + 2 * t * m * 4 + t * d * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x_lo, x_hi, packed2d, w.scales.astype(jnp.float32))

    return out.reshape(*lead, d)
