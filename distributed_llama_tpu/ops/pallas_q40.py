"""Pallas TPU kernel: fused Q40-dequant matmul.

TPU-native replacement for the reference's hot Q40xQ80 NEON/AVX2 kernel
(ref: src/funcs.cpp:286-385). The reference streams 4.5-bit weights through
SIMD integer dot products; here the same HBM-traffic win comes from reading
the packed nibbles (0.5625 B/weight + 1/16 scale byte) and dequantizing in
VMEM right before the MXU contraction — the dense weight matrix never
touches HBM.

Decode at batch=1 makes this op VPU-bound on the unpack arithmetic (the
packed read itself is far under the HBM roofline), so the kernel minimizes
per-byte VPU work with an algebraic restructure. With the reference decoder
value = (nibble - 8) * scale (ref: src/quants.cpp:166-179):

    y = x_lo·(lo-8)s + x_hi·(hi-8)s
      = x_lo·(lo s) + x_hi·(hi s) - 8 Σ_b s[d,b]·xsum[b]

so the per-element subtractions vanish: the hot loop touches each packed
byte with only widen, and, shift, two converts, and two scale-muls. The
correction term is a tiny (t, nb)x(td, nb) dot of per-block activation sums
against the scales already resident in VMEM. (A further restructure that
feeds the raw byte pk = lo + 16*hi to the MXU saves the `and` but amplifies
f32 rounding ~36x through cancellation — rejected. bf16 VPU arithmetic
measures *slower* than f32 — the VPU is f32-native.)

Decode roofline (measured v5e): the ~7 VPU ops per packed byte above cap
the kernel at ~475 GB/s of packed-byte throughput (v5e VPU ~3.8 Tops/s),
and whole-model decode measures 409-472 GB/s effective — the kernel runs
at its VPU design ceiling, not the 819 GB/s HBM ceiling. For PREFILL
chunks (t=256, bf16 MXU feeds) the kernel also wins decisively: 7B
2048-token prefill measures 6317 tok/s fused vs 2299 tok/s on the XLA
dequant-einsum path (2.7x) — the round-3 kernel measured 5771 tok/s with
the nibble unpack (VPU) fully serialized against the MXU contraction;
sub-tiling the td=256 tile (see _n_sub) overlaps the two for +9.5%
whole-model (+41% on the w1/w3 matmul alone). Cutting ops/byte
further means int8 MXU dots — measured and REJECTED: an int4-unpack ->
int8 dot_general variant runs 4x slower at t=1 (82 vs 331 GB/s packed,
tools/exp_int8_dot.py) because Mosaic has no efficient int8 gemv path;
Q80 weights would unpack cheaper (~2.5 ops/byte) but carry 1.9x the
bytes, a net loss. 7B Q40 decode lands at ~9.5 ms/token accordingly.

Layout: QuantizedTensor packed is nibble-position-major, stored flattened
(d, m) uint8 with lane order m = j*nb + b (see quants/jax_codec.py) — the
kernel consumes the HBM buffer in place, no reshape/re-tile.
Consequences inside the kernel:
  * the per-block scale expansion s16[d, m] = s[d, m % nb] is a lane tile —
    exactly `pltpu.repeat(s, 16)` (an element-wise repeat of the block-major
    order would need a shape cast Mosaic cannot lower);
  * no weight shuffle is needed; instead the small activation is pre-split
    outside the kernel into matching lo/hi orders:
      x_lo[t, j*nb + b] = x[t, b*32 + j]       (low-nibble elements)
      x_hi[t, j*nb + b] = x[t, b*32 + 16 + j]  (high-nibble elements)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants.jax_codec import QuantizedTensor

LANES = 128
# output-dim tile candidates, largest first (larger tiles amortize grid
# overhead; measured td=1024 ~7% faster than td=256 on v5e)
TILE_D_CANDIDATES = (1024, 512, 256, LANES)
# above this token count the op is FLOPs-amortized and the XLA dequant path
# is used instead; also bounds the kernel's (t, m) VMEM blocks (ADVICE r1)
MAX_T = 256


def _f16_bits_to_f32(u: jnp.ndarray) -> jnp.ndarray:
    """Decode f16 bit patterns (int32-widened uint16) to f32 exactly with
    integer ops + bitcast — Mosaic has no f16 arithmetic, and keeping the
    scales 2 bytes wide in HBM saves ~10% of the kernel's traffic (measured
    1.19x, tools/exp_scale_f16.py). Handles normals and subnormals; inf/nan
    cannot occur in Q40 scales."""
    sign = (u & 0x8000) << 16
    e = (u >> 10) & 0x1F
    m = u & 0x3FF
    normal = jax.lax.bitcast_convert_type(
        sign | ((e + 112) << 23) | (m << 13), jnp.float32)
    sub = jnp.where(sign != 0, -1.0, 1.0) * (
        m.astype(jnp.float32) * (2.0 ** -24))
    return jnp.where(e == 0, sub, normal)


def _dequant_dot(x_lo, x_hi, xsum, pk_u8, s_raw,
                 *, out_dtype, scales_u16, mxu_bf16):
    """The kernel math on loaded blocks: dequantize a (TD, M) packed tile in
    registers and contract with the pre-split activations. Activations must
    already be in the contraction dtype (bf16 when mxu_bf16).

    (A round-5 re-try of the pk-substitution — fold lo = pk - 16*hi into
    the contraction to drop the `& 0xF` — was REJECTED twice over: timing
    FLAT at 1.000x (the and-op co-issues off the critical path) and 6.4%
    relative error (DEFAULT-precision dots pass f32 operands through the
    MXU as bf16; pk's 8 value bits fill the mantissa and the 16x
    cancellation amplifies the truncation). Full record:
    tools/exp_pk_decode.py.)"""
    pk = pk_u8.astype(jnp.int32)                         # (TD, M=16*nb)
    lo = (pk & 0xF).astype(jnp.float32)
    hi = (pk >> 4).astype(jnp.float32)
    if scales_u16:
        s = _f16_bits_to_f32(s_raw.astype(jnp.int32))    # (TD, NB)
    else:
        s = s_raw                                        # f32 (hand-built)
    s16 = pltpu.repeat(s, 16, axis=1)                    # lane-tile -> (TD, M)

    # DEFAULT precision: single-pass MXU feed (HIGHEST = multi-pass f32
    # decomposition, measured ~5x slower for the whole kernel); operands are
    # engine-bf16 activations and 4-bit weights, so nothing real is lost
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT,
    )
    wl, wh = lo * s16, hi * s16
    if mxu_bf16:
        # multi-token (prefill) chunks are MXU-bound: f32 feeds cap the MXU
        # at 1/4 of its bf16 rate (v5e 49 vs 197 TFLOP/s), so cast the
        # dequantized tiles down. 4-bit weight levels and bf16 engine
        # activations fit bf16 exactly; only requested when the caller's
        # out_dtype is bf16 (decode t=1 stays f32/VPU-bound)
        wl, wh = wl.astype(jnp.bfloat16), wh.astype(jnp.bfloat16)
    acc = dot(x_lo, wl)                                  # (T, TD)
    acc += dot(x_hi, wh)
    acc += dot(xsum, s) * jnp.float32(-8.0)              # fold every (nib-8) offset
    return acc.astype(out_dtype)


def _n_sub(td: int, m: int, mxu_bf16: bool) -> int:
    """Sub-tile count for the unpack/MXU interleave (prefill mode only).

    Splitting the (td, m) packed tile into n_sub row sub-tiles and issuing
    each sub-tile's dot right after its unpack lets the MXU chew on sub-tile
    i while the VPU unpacks i+1. Measured on v5e at t=256
    (tools/exp_unpack_overlap.py + the w2-shape probe):
      * w1/w3 shape (d=11008, m=2048, td=256): n_sub=8 wins 1.41x
        (n_sub=2: 1.37x, n_sub=4: 1.38x)
      * w2 shape (d=4096, m=5504, td=256): n_sub=2 wins 2.26x
        (36.6 vs 82.9 ms/call); n_sub=4 measured SLOWER than whole-tile
        and n_sub=8 OOMs scoped VMEM (16.77M > 16M limit)
      * attention-projection shape (d=4096, m=2048, td=1024): every
        sub-tile variant flat or worse (0.89-0.98x) — whole-tile stays
    so: sub-tile only the td=256 tile, 8-way when the packed tile is at
    most 512 KB (m <= 2048, the measured-safe regime), else 2-way. Decode
    (t=1) is VPU-bound with nothing to overlap, so f32 mode stays
    whole-tile. 32-row sub-tiles satisfy the uint8 sublane tile."""
    if not (mxu_bf16 and td == 256):
        return 1
    return 8 if td * m <= (1 << 19) else 2


def _subtiled_write(x_lo, x_hi, xsum, load_packed, load_scales, out_ref,
                    *, out_dtype, scales_u16, mxu_bf16):
    """Run _dequant_dot per 1/n_sub row slice of the packed tile, writing
    each output column slice as soon as its dot is issued. load_packed /
    load_scales map a row slice -> loaded sub-block (ref slicing stays at
    the call site because the expert kernel's refs carry a leading dim)."""
    td = out_ref.shape[-1]
    n_sub = _n_sub(td, x_lo.shape[-1], mxu_bf16)
    if mxu_bf16:
        x_lo, x_hi = x_lo.astype(jnp.bfloat16), x_hi.astype(jnp.bfloat16)
    h = td // n_sub
    for i in range(n_sub):
        sl = slice(i * h, (i + 1) * h)
        out_ref[:, sl] = _dequant_dot(
            x_lo, x_hi, xsum, load_packed(sl), load_scales(sl),
            out_dtype=out_dtype, scales_u16=scales_u16, mxu_bf16=mxu_bf16)


def _kernel(x_lo_ref, x_hi_ref, xsum_ref, packed_ref, scales_ref, out_ref,
            *, nb, out_dtype, scales_u16, mxu_bf16):
    _subtiled_write(
        x_lo_ref[:], x_hi_ref[:], xsum_ref[:],
        lambda sl: packed_ref[sl, :], lambda sl: scales_ref[sl, :], out_ref,
        out_dtype=out_dtype, scales_u16=scales_u16, mxu_bf16=mxu_bf16)


def _expert_kernel(e_ref, x_lo_ref, x_hi_ref, xsum_ref, packed_ref,
                   scales_ref, out_ref, *, nb, out_dtype, scales_u16,
                   mxu_bf16):
    del e_ref  # consumed by the index maps (expert selection)
    _subtiled_write(
        x_lo_ref[:], x_hi_ref[:], xsum_ref[:],
        lambda sl: packed_ref[0, sl, :], lambda sl: scales_ref[0, sl, :],
        out_ref,
        out_dtype=out_dtype, scales_u16=scales_u16, mxu_bf16=mxu_bf16)


def _tile_d(d: int, m: int) -> int:
    """Output-dim tile: Mosaic wants the last block dim to be a multiple of
    128 lanes OR the whole array dim — so tile by the largest divisor from
    the candidate list whose f32 unpack intermediates (the dominant VMEM
    consumers, ~4 bytes per packed byte each) stay within the ~16 MB
    scoped-VMEM budget, else take d whole (grid of 1)."""
    for t in TILE_D_CANDIDATES:
        if d % t == 0 and t * m <= 2_300_000:
            return t
    return d


def supports_pallas(w: QuantizedTensor, t: int = 1) -> bool:
    """Kernel preconditions: 2D weight (d, m) — callers slice leading
    (layer/expert) dims first — and a token count small enough that decode/
    short-prefill VMEM blocks fit (longer segments are FLOPs-amortized and
    take the XLA dequant path)."""
    return w.packed.ndim == 2 and t <= MAX_T


def _split_activation(x: jnp.ndarray, nb: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(T, n) -> lo/hi halves in kernel lane order m = j*nb + b."""
    t = x.shape[0]
    x4 = x.reshape(t, nb, 2, 16)                         # [t, b, half, j]
    x_lo = x4[:, :, 0, :].transpose(0, 2, 1).reshape(t, nb * 16)
    x_hi = x4[:, :, 1, :].transpose(0, 2, 1).reshape(t, nb * 16)
    return x_lo, x_hi


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def q40_matmul(
    x: jnp.ndarray,
    w: QuantizedTensor,
    out_dtype=jnp.float32,
    interpret: bool = False,
) -> jnp.ndarray:
    """y[..., d] = sum_n x[..., n] * W[d, n] with W in packed Q40 form.

    Matches matmul()'s convention (ref: src/funcs.cpp:413-454); x may have any
    leading dims. Weight stays packed through HBM; dequant happens per-tile in
    VMEM fused into the MXU contraction.
    """
    d, m = w.packed.shape
    nb = m // 16
    n = nb * 32

    lead = x.shape[:-1]
    t = 1
    for s in lead:
        t *= s
    x_lo, x_hi = _split_activation(x.reshape(t, n).astype(jnp.float32), nb)
    xsum = (x_lo + x_hi).reshape(t, 16, nb).sum(axis=1)  # (t, nb) per-block sums

    packed2d = w.packed  # already stored flattened (d, m) — consumed in place
    td = _tile_d(d, m)
    grid = (d // td,)
    scales_u16 = w.scales.dtype == jnp.uint16
    scales = w.scales if scales_u16 else w.scales.astype(jnp.float32)
    # multi-token chunks with a bf16 consumer take the bf16 MXU feed (see
    # _kernel); single-token decode and f32 consumers keep exact f32
    mxu_bf16 = jnp.dtype(out_dtype) == jnp.bfloat16 and t >= 16

    out = pl.pallas_call(
        functools.partial(_kernel, nb=nb, out_dtype=out_dtype,
                          scales_u16=scales_u16, mxu_bf16=mxu_bf16),
        grid=grid,
        in_specs=[
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, td), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * t * d * n,
            bytes_accessed=d * m + d * nb * 2 + 2 * t * m * 4 + t * d * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x_lo, x_hi, xsum, packed2d, scales)

    return out.reshape(*lead, d)


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def q40_expert_matmul(
    x: jnp.ndarray,
    w: QuantizedTensor,    # stacked (E, d, m) packed / (E, d, nb) scales
    e: jnp.ndarray,        # traced i32 expert index
    out_dtype=jnp.float32,
    interpret: bool = False,
) -> jnp.ndarray:
    """y[..., d] = sum_n x[..., n] * W[e, d, n] with the expert chosen by a
    TRACED index — the MoE decode gather (models/transformer._moe_ffn; the
    reference computes just the active experts the same way, ref:
    src/grok1-tasks.cpp:128-143).

    The expert index rides in as a scalar-prefetch operand and the block
    index maps offset straight into the (E, d, m) HBM stack, so the kernel
    reads the active expert's packed bytes IN PLACE. The alternative —
    lax.dynamic_index_in_dim then q40_matmul — materializes a full HBM copy
    of the expert's weight before the kernel can read it (read + write +
    re-read = 3x the bytes of the decode-critical path).
    """
    n_e, d, m = w.packed.shape
    nb = m // 16
    n = nb * 32

    lead = x.shape[:-1]
    t = 1
    for s in lead:
        t *= s
    x_lo, x_hi = _split_activation(x.reshape(t, n).astype(jnp.float32), nb)
    xsum = (x_lo + x_hi).reshape(t, 16, nb).sum(axis=1)

    td = _tile_d(d, m)
    scales_u16 = w.scales.dtype == jnp.uint16
    scales = w.scales if scales_u16 else w.scales.astype(jnp.float32)
    mxu_bf16 = jnp.dtype(out_dtype) == jnp.bfloat16 and t >= 16
    e_arr = jnp.atleast_1d(e).astype(jnp.int32)

    out = pl.pallas_call(
        functools.partial(_expert_kernel, nb=nb, out_dtype=out_dtype,
                          scales_u16=scales_u16, mxu_bf16=mxu_bf16),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(d // td,),
            in_specs=[
                pl.BlockSpec((t, m), lambda i, e_ref: (0, 0)),
                pl.BlockSpec((t, m), lambda i, e_ref: (0, 0)),
                pl.BlockSpec((t, nb), lambda i, e_ref: (0, 0)),
                pl.BlockSpec((1, td, m), lambda i, e_ref: (e_ref[0], i, 0)),
                pl.BlockSpec((1, td, nb), lambda i, e_ref: (e_ref[0], i, 0)),
            ],
            out_specs=pl.BlockSpec((t, td), lambda i, e_ref: (0, i)),
        ),
        out_shape=jax.ShapeDtypeStruct((t, d), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * t * d * n,
            bytes_accessed=d * m + d * nb * 2 + 2 * t * m * 4 + t * d * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(e_arr, x_lo, x_hi, xsum, w.packed, scales)

    return out.reshape(*lead, d)
