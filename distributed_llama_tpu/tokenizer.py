"""llama2.c-style BPE tokenizer.

Behavioral port of the reference tokenizer (ref: src/tokenizer.cpp:109-229):
UTF-8 codepoint scan, byte-fallback at +3 offset, then greedy highest-score
pair merging. Decode strips a leading space after BOS and expands `<0xXX>`
raw-byte pieces (ref: src/tokenizer.cpp:89-100).

A C++ implementation with the same behavior lives in native/
(dllama_native.cpp, built with `make -C native`) and is used automatically
when the shared library is present (backend="auto"); this pure-Python
version is the fallback and the correctness oracle the native code is
parity-tested against (tests/test_native.py).
"""

from __future__ import annotations

from .io.tokenizer_file import TokenizerData, read_tokenizer_file


class Tokenizer:
    def __init__(self, data: TokenizerData, backend: str = "auto"):
        self.data = data
        self.vocab = data.vocab
        self.scores = data.scores
        self.bos_id = data.bos_id
        self.eos_id = data.eos_id
        self._index: dict[bytes, int] = {}
        for i, tok in enumerate(self.vocab):
            # first occurrence wins, like bsearch over a stable-sorted vocab
            if tok not in self._index:
                self._index[tok] = i
        self._native = None
        if backend in ("auto", "native"):
            from . import native

            if native.available():
                self._native = native.NativeTokenizer(
                    self.vocab, self.scores, self.bos_id, self.eos_id)
            elif backend == "native":
                raise RuntimeError("native backend requested but "
                                   "libdllama_native.so is not built")

    @classmethod
    def from_file(cls, path: str) -> "Tokenizer":
        return cls(read_tokenizer_file(path))

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # end-of-turn pieces emitted by instruct-tuned models whose header eos_id
    # is the base-model eos (e.g. llama-3: eos=<|end_of_text|> while chat
    # turns end with <|eot_id|>/<|eom_id|>)
    CHAT_STOP_PIECES = (b"<|eot_id|>", b"<|eom_id|>")

    def stop_token_ids(self) -> set[int]:
        """eos_id plus any end-of-turn marker tokens present in the vocab —
        the id set generation should stop on."""
        ids = {self.eos_id}
        for piece in self.CHAT_STOP_PIECES:
            tid = self._index.get(piece)
            if tid is not None:
                ids.add(tid)
        return ids

    def encode(self, text: str, add_bos: bool = True, add_eos: bool = False) -> list[int]:
        if self._native is not None:
            return self._native.encode(text, add_bos, add_eos)
        tokens: list[int] = []
        if add_bos:
            tokens.append(self.bos_id)

        raw = text.encode("utf-8")
        if raw:
            # dummy space prefix (ref: src/tokenizer.cpp:140-144)
            space = self._index.get(b" ")
            if space is not None:
                tokens.append(space)

        # codepoint scan with byte fallback (ref: src/tokenizer.cpp:155-192)
        i = 0
        while i < len(raw):
            j = i + 1
            # gather continuation bytes, capped at 4 total like the reference
            while j < len(raw) and (raw[j] & 0xC0) == 0x80 and (j - i) < 4:
                j += 1
            piece = raw[i:j]
            tid = self._index.get(piece)
            if tid is not None:
                tokens.append(tid)
            else:
                # byte fallback, +3 offset; clamp to <unk> (0) if the vocab
                # has no byte tokens (the reference indexes unchecked)
                tokens.extend(b + 3 if b + 3 < len(self.vocab) else 0
                              for b in piece)
            i = j

        # greedy merge of the best-scoring adjacent pair (ref: src/tokenizer.cpp:195-223)
        while True:
            best_score = -1e10
            best_id = -1
            best_idx = -1
            for k in range(len(tokens) - 1):
                merged = self.vocab[tokens[k]] + self.vocab[tokens[k + 1]]
                mid = self._index.get(merged)
                if mid is not None and self.scores[mid] > best_score:
                    best_score = self.scores[mid]
                    best_id = mid
                    best_idx = k
            if best_idx == -1:
                break
            tokens[best_idx:best_idx + 2] = [best_id]

        if add_eos:
            tokens.append(self.eos_id)
        return tokens

    def decode_piece(self, prev_token: int, token: int) -> bytes:
        if self._native is not None:
            return self._native.decode_piece(prev_token, token)
        piece = self.vocab[token]
        if prev_token == self.bos_id and piece.startswith(b" "):
            piece = piece[1:]
        # raw-byte pieces look like b'<0xAB>' (ref: src/tokenizer.cpp:93-98)
        if len(piece) == 6 and piece.startswith(b"<0x") and piece.endswith(b">"):
            try:
                return bytes([int(piece[3:5], 16)])
            except ValueError:
                pass
        return piece

    def decode(self, tokens: list[int]) -> str:
        out = bytearray()
        prev = self.bos_id if tokens and tokens[0] == self.bos_id else -1
        for t in tokens:
            if t == self.bos_id:
                prev = t
                continue
            out += self.decode_piece(prev, t)
            prev = t
        return out.decode("utf-8", errors="replace")
