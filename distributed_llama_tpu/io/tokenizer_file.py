"""`.t` tokenizer-file format.

Byte-compatible with the reference (ref: src/tokenizer.hpp:16-23,
tokenizer.cpp:38-80): a 24-byte header {magic 0x567123, vocabSize,
maxTokenLength, bosId, eosId, padId} followed by, per token, an f32 score,
an i32 byte-length and the raw token bytes.
"""

from __future__ import annotations

import dataclasses
import struct

TOKENIZER_MAGIC = 0x567123


@dataclasses.dataclass
class TokenizerData:
    vocab: list[bytes]
    scores: list[float]
    bos_id: int
    eos_id: int
    pad_id: int = -1

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def max_token_length(self) -> int:
        return max((len(t) for t in self.vocab), default=0)


def read_tokenizer_file(path: str) -> TokenizerData:
    with open(path, "rb") as f:
        magic, vocab_size, _max_len, bos_id, eos_id, pad_id = struct.unpack("<IIIiii", f.read(24))
        if magic != TOKENIZER_MAGIC:
            raise ValueError(f"invalid tokenizer file magic {magic:#x}")
        vocab: list[bytes] = []
        scores: list[float] = []
        for _ in range(vocab_size):
            score, length = struct.unpack("<fi", f.read(8))
            vocab.append(f.read(length))
            scores.append(score)
    return TokenizerData(vocab=vocab, scores=scores, bos_id=bos_id, eos_id=eos_id, pad_id=pad_id)


def write_tokenizer_file(path: str, data: TokenizerData) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(
            "<IIIiii", TOKENIZER_MAGIC, data.vocab_size, data.max_token_length,
            data.bos_id, data.eos_id, data.pad_id,
        ))
        for tok, score in zip(data.vocab, data.scores):
            f.write(struct.pack("<fi", score, len(tok)))
            f.write(tok)
