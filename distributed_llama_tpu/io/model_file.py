"""`.m` model-file format: reader and writer.

Byte-compatible with the reference's custom model format so models converted
for the reference engine load here unchanged, and fixtures written here load
in the reference:

  * header: legacy fixed struct (magic 0xABCD00/01, ref:
    src/transformer.hpp:59-69, transformer.cpp:198-213) or KV-pair format
    (magic 0xA00ABCD, ref: src/transformer.cpp:214-243, converter/writer.py:110-139)
  * tensor walk order: embedding; per layer q,k,v,wo, then dense w1,w2,w3 or
    MoE router + per-expert up,gate,down; rms weights; final rms; wcls
    (ref: src/transformer.cpp:623-683)

Unlike the reference — which mmaps and pushes byte-slices over sockets — we
return tensors as numpy arrays (dense f32/f16) or host Q40/Q80 struct-of-array
pairs ready for device upload; sharding happens later via jax.device_put with
NamedSharding, not by byte-slicing rows here.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Iterator

import numpy as np

from ..models.spec import ArchType, HiddenAct, ModelSpec
from ..quants.types import BLOCK_SIZE, FloatType, batch_bytes
from ..quants.numpy_codec import (
    dequantize_q40,
    dequantize_q80,
    q40_bytes_to_arrays,
    q40_arrays_to_bytes,
    q80_bytes_to_arrays,
    q80_arrays_to_bytes,
    quantize_q40,
    quantize_q80,
)

MAGIC_KV = 0xA00ABCD  # ref: src/transformer.cpp:214
LEGACY_MAGICS = (0xABCD00, 0xABCD01)  # ref: src/transformer.cpp:198

# header KV keys, ref: src/transformer.hpp:42-57
_KEYS = {
    "version": 0,
    "arch_type": 1,
    "dim": 2,
    "hidden_dim": 3,
    "n_layers": 4,
    "n_heads": 5,
    "n_kv_heads": 6,
    "n_experts": 7,
    "n_active_experts": 8,
    "vocab_size": 9,
    "max_seq_len": 10,
    "hidden_act": 11,
    "rope_theta": 12,
    "weights_float_type": 13,
}


@dataclasses.dataclass
class HostTensor:
    """A tensor as stored on file: dense numpy or quantized struct-of-arrays.

    Logical shape is (d, n): d output rows of n values, matching the
    reference's matmul convention (W @ x, ref: src/funcs.cpp:413-454).
    """

    name: str
    ftype: FloatType
    shape: tuple[int, ...]
    data: np.ndarray | None = None       # dense f32 (or f16) payload
    scales: np.ndarray | None = None     # (d, nb) f16 for Q40/Q80
    packed: np.ndarray | None = None     # (d, nb, 16) u8 for Q40 / (d, nb, 32) i8 for Q80

    def to_f32(self) -> np.ndarray:
        if self.ftype == FloatType.F32:
            return self.data
        if self.ftype == FloatType.F16:
            return self.data.astype(np.float32)
        if self.ftype == FloatType.Q40:
            return dequantize_q40(self.scales, self.packed).reshape(self.shape)
        if self.ftype == FloatType.Q80:
            return dequantize_q80(self.scales, self.packed).reshape(self.shape)
        raise ValueError(self.ftype)


def model_tensor_plan(spec: ModelSpec) -> Iterator[tuple[str, tuple[int, ...], FloatType]]:
    """Yield (name, shape, ftype) in exact file order (ref: src/transformer.cpp:623-683).

    Shapes are (d, n) = (out_dim, in_dim) for matmul weights.
    """
    wt = spec.weights_float_type
    yield "tok_emb", (spec.vocab_size, spec.dim), FloatType.F32
    for l in range(spec.n_layers):
        p = f"layers.{l}."
        yield p + "wq", (spec.dim, spec.dim), wt
        yield p + "wk", (spec.kv_dim, spec.dim), wt
        yield p + "wv", (spec.kv_dim, spec.dim), wt
        yield p + "wo", (spec.dim, spec.dim), wt
        if spec.is_moe:
            yield p + "moe_router", (spec.n_experts, spec.dim), wt
            for e in range(spec.n_experts):
                yield p + f"experts.{e}.up", (spec.hidden_dim, spec.dim), wt
                yield p + f"experts.{e}.gate", (spec.hidden_dim, spec.dim), wt
                yield p + f"experts.{e}.down", (spec.dim, spec.hidden_dim), wt
        else:
            yield p + "w1", (spec.hidden_dim, spec.dim), wt
            yield p + "w2", (spec.dim, spec.hidden_dim), wt
            yield p + "w3", (spec.hidden_dim, spec.dim), wt
        yield p + "rms_att", (spec.dim,), FloatType.F32
        yield p + "rms_ffn", (spec.dim,), FloatType.F32
        if spec.arch == ArchType.GROK1:
            yield p + "rms_moe", (spec.dim,), FloatType.F32
            yield p + "rms_ffn2", (spec.dim,), FloatType.F32
    yield "rms_final", (spec.dim,), FloatType.F32
    yield "wcls", (spec.vocab_size, spec.dim), wt


def _tensor_bytes(shape: tuple[int, ...], ftype: FloatType) -> int:
    n = shape[-1]
    d = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    return batch_bytes(ftype, n, d)


def read_spec(path: str, weights_float_type: FloatType | None = None) -> ModelSpec:
    """Parse the `.m` header (ref: src/transformer.cpp:183-291)."""
    with open(path, "rb") as f:
        magic = struct.unpack("<i", f.read(4))[0]
        fields: dict[str, int] = {}
        if magic in LEGACY_MAGICS:
            names = ["dim", "hidden_dim", "n_layers", "n_heads", "n_kv_heads",
                     "n_experts", "n_active_experts", "vocab_size", "max_seq_len"]
            vals = struct.unpack("<9i", f.read(36))
            fields = dict(zip(names, vals))
            fields["arch_type"] = magic
            header_size = 4 + 36
            rope_theta = 10000.0
            hidden_act = HiddenAct.SILU
            version = 0
            file_wt = None
        elif magic == MAGIC_KV:
            header_size = struct.unpack("<i", f.read(4))[0]
            data = f.read(header_size - 8)
            n_kv = len(data) // 8
            inv = {v: k for k, v in _KEYS.items()}
            for i in range(n_kv):
                k, v = struct.unpack_from("<ii", data, i * 8)
                fields[inv[k]] = v
            rope_theta = float(fields.pop("rope_theta", 10000))
            hidden_act = HiddenAct(fields.pop("hidden_act", int(HiddenAct.SILU)))
            version = fields.pop("version", 0)
            file_wt = fields.pop("weights_float_type", None)
        else:
            raise ValueError(f"unsupported model file magic {magic:#x}")

    wt = weights_float_type
    if wt is None:
        wt = FloatType(file_wt) if file_wt is not None else FloatType.F32
    elif file_wt is not None and int(wt) != file_wt:
        # the reference requires the flag to match the file (ref: app.cpp:47-48)
        # but fails mid-load; fail fast with a clear message instead
        raise ValueError(
            f"--weights-float-type {wt.name} does not match the model file "
            f"header ({FloatType(file_wt).name})")
    spec = ModelSpec(
        arch=ArchType(fields["arch_type"]),
        dim=fields["dim"],
        hidden_dim=fields["hidden_dim"],
        n_layers=fields["n_layers"],
        n_heads=fields["n_heads"],
        n_kv_heads=fields["n_kv_heads"],
        n_experts=fields.get("n_experts", 0),
        n_active_experts=fields.get("n_active_experts", 0),
        vocab_size=fields["vocab_size"],
        seq_len=fields["max_seq_len"],
        hidden_act=hidden_act,
        rope_theta=rope_theta,
        weights_float_type=wt,
        version=version,
    )
    spec.validate()
    object.__setattr__(spec, "_header_size", header_size)
    return spec


def tensor_from_bytes(name: str, shape: tuple[int, ...], ftype: FloatType,
                      buf: bytes) -> HostTensor:
    """Decode one tensor's raw FILE bytes into a HostTensor — the shared
    tail of the file reader and the multihost root-push receiver
    (parallel/multihost.bcast_model_tensors), which ships exactly these
    bytes over the wire like the reference's per-worker weight push
    (ref: src/transformer.cpp:562-621)."""
    if ftype == FloatType.F32:
        return HostTensor(name, ftype, shape, data=np.frombuffer(buf, np.float32).reshape(shape).copy())
    if ftype == FloatType.F16:
        return HostTensor(name, ftype, shape, data=np.frombuffer(buf, np.float16).reshape(shape).copy())
    n = shape[-1]
    d = int(np.prod(shape[:-1]))
    nb = n // BLOCK_SIZE
    if ftype == FloatType.Q40:
        scales, packed = q40_bytes_to_arrays(buf, d * n)
        return HostTensor(name, ftype, shape,
                          scales=scales.reshape(d, nb), packed=packed.reshape(d, nb, 16))
    if ftype == FloatType.Q80:
        scales, q = q80_bytes_to_arrays(buf, d * n)
        return HostTensor(name, ftype, shape,
                          scales=scales.reshape(d, nb), packed=q.reshape(d, nb, 32))
    raise ValueError(ftype)


def _read_tensor(f, name: str, shape: tuple[int, ...], ftype: FloatType) -> HostTensor:
    nbytes = _tensor_bytes(shape, ftype)
    buf = f.read(nbytes)
    if len(buf) != nbytes:
        raise EOFError(f"model file truncated at tensor {name}")
    return tensor_from_bytes(name, shape, ftype, buf)


def iter_model_tensors(path: str, spec: ModelSpec) -> Iterator[HostTensor]:
    """Yield tensors one at a time in file order — the streaming read the
    70B-scale loader consumes (models/loader.py): only one tensor's host
    buffer is live per step (the reference streams from mmap the same way,
    ref: src/transformer.cpp:607-621)."""
    header_size = getattr(spec, "_header_size", None)
    if header_size is None:  # spec built independently of this file
        header_size = getattr(read_spec(path, spec.weights_float_type),
                              "_header_size")
    with open(path, "rb") as f:
        f.seek(header_size)
        for name, shape, ftype in model_tensor_plan(spec):
            yield _read_tensor(f, name, shape, ftype)
        if f.read(1):
            raise ValueError("model file has trailing bytes — spec/file mismatch")


def read_model(path: str, weights_float_type: FloatType | None = None,
               spec: ModelSpec | None = None) -> tuple[ModelSpec, dict[str, HostTensor]]:
    """Read header + all tensors into one dict (small/medium models and
    tests; the sharded streaming path is models/loader.py)."""
    if spec is None:
        spec = read_spec(path, weights_float_type)
    tensors = {t.name: t for t in iter_model_tensors(path, spec)}
    return spec, tensors


def write_header(f, spec: ModelSpec) -> None:
    """KV header, byte-identical to converter/writer.py:110-139."""
    params = {
        "version": spec.version,
        "arch_type": int(spec.arch),
        "hidden_act": int(spec.hidden_act),
        "dim": spec.dim,
        "hidden_dim": spec.hidden_dim,
        "n_layers": spec.n_layers,
        "n_heads": spec.n_heads,
        "n_kv_heads": spec.n_kv_heads,
        "weights_float_type": int(spec.weights_float_type),
        "max_seq_len": spec.seq_len,
        "vocab_size": spec.vocab_size,
        "n_experts": spec.n_experts,
        "n_active_experts": spec.n_active_experts,
        "rope_theta": int(spec.rope_theta),
    }
    data = b""
    for key, value in params.items():
        data += struct.pack("<ii", _KEYS[key], value)
    f.write(struct.pack("<i", MAGIC_KV))
    f.write(struct.pack("<i", 8 + len(data)))
    f.write(data)


def write_tensor(f, x: np.ndarray, ftype: FloatType) -> None:
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if ftype == FloatType.F32:
        f.write(flat.tobytes())
    elif ftype == FloatType.F16:
        f.write(flat.astype(np.float16).tobytes())
    elif ftype == FloatType.Q40:
        scales, packed = quantize_q40(flat)
        f.write(q40_arrays_to_bytes(scales, packed))
    elif ftype == FloatType.Q80:
        scales, q = quantize_q80(flat)
        f.write(q80_arrays_to_bytes(scales, q))
    else:
        raise ValueError(ftype)


def content_fingerprint(path: str) -> int:
    """Cheap content hash of a model file: CRC of the size plus 64 KiB
    sampled at the start, middle and end — catches same-architecture
    different-weight builds (fine-tunes, requants) without reading a
    40 GB file. Used by the multihost cluster config check and the
    KV-session fingerprint (both would otherwise pair a cache/cluster
    with weights that never produced it)."""
    import os
    import zlib

    size = os.path.getsize(path)
    fp = zlib.crc32(str(size).encode())
    with open(path, "rb") as f:
        for off in (0, size // 2, max(size - 65536, 0)):
            f.seek(off)
            fp = zlib.crc32(f.read(65536), fp)
    return fp


def write_model(path: str, spec: ModelSpec, tensors: dict[str, np.ndarray]) -> None:
    """Write a complete `.m` file from dense f32 tensors (quantizing to the
    spec's weights_float_type where the plan demands)."""
    spec.validate()  # reject unusable specs at write, not first read
    with open(path, "wb") as f:
        write_header(f, spec)
        for name, shape, ftype in model_tensor_plan(spec):
            x = tensors[name]
            assert tuple(x.shape) == tuple(shape), (name, x.shape, shape)
            write_tensor(f, x, ftype)
