from .model_file import read_spec, read_model, write_model, model_tensor_plan, HostTensor
from .tokenizer_file import read_tokenizer_file, write_tokenizer_file, TokenizerData

__all__ = [
    "read_spec",
    "read_model",
    "write_model",
    "model_tensor_plan",
    "HostTensor",
    "read_tokenizer_file",
    "write_tokenizer_file",
    "TokenizerData",
]
