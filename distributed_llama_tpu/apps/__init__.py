"""User-facing apps: dllama CLI and the OpenAI-compatible API server
(TPU-native equivalents of ref: src/apps/dllama, src/apps/dllama-api)."""
