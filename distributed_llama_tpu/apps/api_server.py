"""OpenAI-compatible HTTP API server.

TPU-native equivalent of the reference's dllama-api
(ref: src/apps/dllama-api/dllama-api.cpp):

  * POST /v1/chat/completions — completion + SSE streaming
    (ref: dllama-api.cpp:202-314)
  * GET /v1/models (ref: dllama-api.cpp:316-322)
  * Llama-3 header chat template (ref: dllama-api.cpp:173-181)
  * per-request temperature / seed / max_tokens / stop
    (ref: dllama-api.cpp:211-232), applied via Sampler setters
    (ref: src/tokenizer.cpp:358-364)
  * stop-sequence scan over the trailing pieces (ref: dllama-api.cpp:272-286)
  * prefix/session reuse (net-new — the reference resets the KV cache per
    request, ref: dllama-api.cpp:236-249): the longest common token prefix
    of the previous session stays cached and only the suffix re-prefills,
    which on TPU removes the dominant cost of a chat follow-up turn.
    Single-process only — multi-host clusters reset per request so a
    worker-side resync can never desync the processes' prefill shapes

Front-end: a THREADED accept loop (ThreadingHTTPServer — net-new vs the
reference's single-threaded accept, ref: dllama-api.cpp:341-352; stdlib
only, no external deps). With --serve-batch B the process runs the
continuous-batching scheduler (runtime/scheduler.py): /v1/completions and
/v1/chat/completions enqueue onto the shared slot scheduler and stream
tokens per-request as their slot produces them, so concurrent clients
share one batched decode instead of queueing whole requests. Without it,
requests serialize on the single engine behind state.engine_lock (the
reference's behavior, minus dropped connections).
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading
import time
from http.server import (BaseHTTPRequestHandler, HTTPServer,
                         ThreadingHTTPServer)

import jax
import numpy as np

from ..runtime.fleet import ShedReject
from ..runtime.resilience import EngineUnready
from ..runtime.scheduler import PromptTooLong, QueueFull, RequestError

CHAT_EOS_MARKERS = ("<|eot_id|>", "<|end_of_text|>")

# SSE keepalive cadence for collected (non-streaming-engine) paths: the
# batch endpoint's greedy+lookup path buffers all rows before the first
# data event, so comment frames (": keepalive") flow while it collects —
# a long generation must not trip client/proxy idle timeouts (ADVICE r5
# low). Comments are protocol-invisible to SSE clients. Tests shrink this.
KEEPALIVE_SECS = 1.0


class BadRequest(ValueError):
    """Deterministic client-input error (malformed temperature/seed/stop/
    prompt types): must map to HTTP 400, never to a retryable 503 — a
    well-behaved client would otherwise retry the permanently-invalid
    request forever."""


def _is_loopback(addr: str) -> bool:
    """Default guard for the /admin/* operator endpoints: auth-free but
    loopback-only — an operator SSHed onto the box (or a sidecar) can
    reset breakers and roll replicas, while nothing routable from the
    service port's clients can. Covers IPv4 loopback (the whole
    127.0.0.0/8), IPv6 ::1, and the IPv6-mapped IPv4 form."""
    if addr.startswith("::ffff:"):
        addr = addr[len("::ffff:"):]
    return addr == "::1" or addr.startswith("127.")


def _admin_authorized(state: "ApiState", client_addr: str,
                      auth_header: str | None) -> bool:
    """May this caller use /admin/*? Loopback always can (the SSHed
    operator). Off-loopback needs ``--admin-token``: remote-replica
    deployments put the operator on another machine, where loopback-only
    was an outage (the breaker could not be reset over the network).
    The compare is constant-time (hmac.compare_digest) so the token
    cannot be recovered byte-at-a-time from response timing."""
    if _is_loopback(client_addr):
        return True
    if not state.admin_token or not auth_header:
        return False
    import hmac

    expected = "Bearer " + state.admin_token
    return hmac.compare_digest(auth_header.encode(), expected.encode())


def build_chat_prompt(messages: list[dict]) -> str:
    """Llama-3 header template (ref: dllama-api.cpp:173-181)."""
    out = []
    for m in messages:
        out.append(f"<|start_header_id|>{m.get('role', 'user')}<|end_header_id|>\n\n"
                   f"{m.get('content', '')}<|eot_id|>")
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


class ApiState:
    def __init__(self, engine, tokenizer, sampler, model_name: str = "dllama",
                 lookup_decode: int = 0, serve_batch: int = 0,
                 serve_chunk: int = 0, queue_depth: int = 0,
                 request_deadline: float = 0.0, stall_timeout: float = 0.0,
                 prefix_cache: bool = False, prefix_blocks: int = 0,
                 prefix_block_len: int = 32, replicas: int = 1,
                 retry_budget: int = 1, route_policy: str = "cache_aware",
                 replica_procs: int = 0, replica_hosts=None,
                 worker_config: dict | None = None,
                 admin_token: str | None = None,
                 profile_dir: str | None = None,
                 slo_ttft_ms: float | None = None,
                 slo_itl_ms: float | None = None,
                 autosize: dict | None = None,
                 draft: str | None = None, draft_len: int = 0,
                 kv_transfer: bool = False, tiers=None,
                 min_replicas: int = 0, max_replicas: int = 0,
                 tenant_budgets: str | None = None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.sampler = sampler
        self.model_name = model_name
        # resilience config (docs/operations.md): bounded admission queue
        # (0 = 4x serve_batch), per-request end-to-end deadline seconds
        # (0 = off), watchdog stall bound seconds (0 = default 10)
        self.queue_depth = queue_depth
        self.request_deadline = request_deadline
        self.stall_timeout = stall_timeout
        # graceful drain (SIGTERM): admissions stop, /readyz goes 503,
        # in-flight work finishes up to --drain-timeout
        self.draining = False
        # token history whose K/V writes are live in the engine cache
        # (prefix/session reuse — see _completion_chunks)
        self.cached_tokens: list[int] = []
        # greedy requests draft+verify up to this many tokens per forward
        # (prompt-lookup speculation, runtime/speculative.py); 0 = off
        self.lookup_decode = lookup_decode
        # REAL-draft speculation (runtime/draft.py): the --draft spec
        # string ("self:D" / "model:PATH") and per-forward budget. On
        # the scheduler path the draft rides build_front_door into
        # every replica's scheduler; on the legacy path a DraftModel is
        # built lazily over this process's engine. spec_stats is the
        # LEGACY tier's aggregate accept record (the scheduler tiers
        # carry theirs on ServeStats.spec) — attached to /stats and
        # /metrics in every tier, launch flags notwithstanding.
        from ..runtime.stats import SpecStats

        self.draft = draft
        self.draft_len = int(draft_len or 0)
        self._draft_model = None
        self.spec_stats = SpecStats(
            mode=(draft or ("lookup" if lookup_decode else "off")),
            draft_len=self.draft_len or lookup_decode)
        # serve_batch > 0 runs the continuous-batching scheduler with this
        # many KV slots: /v1/completions and /v1/chat/completions enqueue
        # onto it, and POST /v1/batch/completions borrows its engine.
        # Decode is weight-read-bound, so b live slots amortize one weight
        # read per step (bench.py's continuous-batching row).
        self.serve_batch = serve_batch
        self.serve_chunk = serve_chunk  # prefill chunk; 0 = engine default
        # radix prefix cache (runtime/prefix_cache.py): cross-request KV
        # reuse on the scheduler path. blocks = 0 auto-sizes the arena to
        # 2x the live cache footprint (2 * B * seq_len worth of blocks) —
        # enough to keep several distinct system prompts + recent
        # conversations resident without doubling engine memory twice
        self.prefix_cache = prefix_cache
        self.prefix_block_len = prefix_block_len
        self.prefix_blocks = prefix_blocks
        # multi-replica serving tier (runtime/router.py): replicas > 1
        # puts a cache-aware failover router in front of N supervised
        # engine replicas over SHARED weights; retry_budget bounds the
        # automatic resubmits of not-yet-streamed requests after a
        # replica failure, route_policy picks the placement rule
        self.replicas = replicas
        self.retry_budget = retry_budget
        self.route_policy = route_policy
        # KV block transfer + prefill/decode disaggregation (runtime/
        # kv_transfer.py): the enable flag and the per-replica roles
        # build_front_door stamps into handles/worker configs
        self.kv_transfer = bool(kv_transfer)
        self.tiers = tiers
        # PROCESS-isolated replica tier (runtime/replica_worker.py):
        # replica_procs spawns N supervised worker processes locally
        # (each its own interpreter — a segfault/SIGKILL/OOM costs one
        # process, not the service); replica_hosts connects to
        # pre-started workers at [(host, port), ...] instead
        self.replica_procs = replica_procs
        self.replica_hosts = replica_hosts
        self.worker_config = worker_config
        # optional bearer token for /admin/*: remote-replica operators
        # are not on loopback, so --admin-token is the non-local
        # alternative to _is_loopback (constant-time compare)
        self.admin_token = admin_token
        # serializes legacy single-engine requests under the threaded
        # accept loop (the scheduler path needs no lock — it queues)
        self.engine_lock = threading.RLock()
        self._scheduler = None
        # router mode = any multi-handle tier (thread, process, or
        # remote): gates session affinity and the per-replica /readyz
        # payload independent of WHICH tier is configured
        self.router_mode = bool(replicas > 1 or replica_procs
                                or replica_hosts)
        # multihost root: set to the ClusterPeerLost when the control
        # plane detects a dead/wedged worker — /readyz answers 503
        # cluster_lost during the brief window before the diagnostic exit
        self.cluster_lost = None
        # POST /admin/profile capture home (--profile-dir; a tempdir per
        # capture otherwise) and the cached build-identity block every
        # /healthz + /metrics answer carries
        self.profile_dir = profile_dir
        self._build_info: dict | None = None
        # SLO-aware admission (runtime/scheduler.AdmissionPolicy): either
        # target arms the adaptive chunk-width policy in every replica's
        # scheduler; the auto-sizing decision record (resolve_auto_shape)
        # rides /stats + /metrics so the chosen shape is always visible
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_itl_ms = slo_itl_ms
        self.autosize = autosize
        # the fleet brain (runtime/fleet.py): --min/--max-replicas arm
        # load-adaptive autoscaling of the replica set, --tenant-budgets
        # arms weighted-fair queueing + per-tenant token buckets, and
        # either SLO target arms the overload shed ladder. The
        # controller is built WITH the front door (scheduler()) so the
        # fleet /stats + /metrics block exists in every scheduler tier.
        self.min_replicas = int(min_replicas or 0)
        self.max_replicas = int(max_replicas or 0)
        self.tenant_budgets = tenant_budgets
        self._fleet = None
        self.tenant_ledger = None

    def build_info(self) -> dict:
        """{version, jax, backend, mesh} — computed once (the backend
        and mesh never change within a process), served on /healthz
        (`build` block) and /metrics (`dllama_build_info`)."""
        if self._build_info is None:
            from ..runtime.profiler import build_info

            self._build_info = build_info(self.engine)
        return self._build_info

    def scheduler(self):
        """The serving front door, built and started on first use: an
        ``EngineSupervisor`` (replicas == 1) or a failover ``Router``
        over N supervised replicas — both constructed by
        runtime/router.build_front_door, the engine-owner logic that
        used to live here (the HTTP layer no longer builds engines). The
        handlers speak one duck-typed surface (``submit``/``engine``/
        ``exclusive``/``ready``/``summary``), so 1 and N replicas serve
        through identical code. Every replica's engine SHARES this
        engine's param device buffers — replication costs KV caches and
        prefix arenas, never weight copies. Single-process only; a tp
        mesh composes on the single-supervisor tier (the vocab-sharded
        serving path) — serve() refuses every other mesh axis, cluster,
        and tp×replicas combination at startup."""
        with self.engine_lock:  # two first requests must not double-build
            if self._scheduler is None:
                from ..runtime.fleet import (FleetConfig, FleetController,
                                             ShedLadder, TenantLedger,
                                             parse_tenant_budgets)
                from ..runtime.router import build_front_door

                if self.tenant_budgets and self.tenant_ledger is None:
                    self.tenant_ledger = TenantLedger(
                        parse_tenant_budgets(self.tenant_budgets))
                self._scheduler = build_front_door(
                    self.engine, serve_batch=self.serve_batch,
                    serve_chunk=self.serve_chunk,
                    queue_depth=self.queue_depth,
                    request_deadline=self.request_deadline,
                    stall_timeout=self.stall_timeout,
                    prefix_cache=self.prefix_cache,
                    prefix_blocks=self.prefix_blocks,
                    prefix_block_len=self.prefix_block_len,
                    replicas=self.replicas,
                    retry_budget=self.retry_budget,
                    route_policy=self.route_policy,
                    replica_procs=self.replica_procs,
                    replica_hosts=self.replica_hosts,
                    worker_config=self.worker_config,
                    slo_ttft_ms=self.slo_ttft_ms,
                    slo_itl_ms=self.slo_itl_ms,
                    draft=self.draft, draft_len=self.draft_len,
                    draft_vocab=self.tokenizer.vocab_size,
                    kv_transfer=self.kv_transfer, tiers=self.tiers,
                    tenant_ledger=self.tenant_ledger)
                # the fleet brain rides every scheduler tier: the shed
                # ladder arms off the SLO targets (no SLO = no ladder,
                # admit() passes through), autoscaling arms off the
                # --min/--max-replicas window (FleetController scales
                # only when the door exposes a spawn factory)
                boot = max(self.replicas, self.replica_procs,
                           len(self.replica_hosts or ()), 1)
                cfg = FleetConfig(
                    min_replicas=self.min_replicas or boot,
                    max_replicas=self.max_replicas or boot)
                ladder = (ShedLadder()
                          if (self.slo_ttft_ms or self.slo_itl_ms)
                          else None)
                self._fleet = FleetController(
                    self._scheduler, config=cfg, ladder=ladder,
                    ledger=self.tenant_ledger)
                self._fleet.start()
            return self._scheduler

    def fleet(self):
        """The fleet controller, built WITH the front door (None until
        the first scheduler-path request forces the build)."""
        self.scheduler()
        return self._fleet

    def batch_engine(self):
        """The batched engine — the SCHEDULER's engine (one live batched
        KV cache per process; callers stepping it directly must hold
        Scheduler.exclusive())."""
        return self.scheduler().engine

    def draft_model(self):
        """The LEGACY path's DraftModel over this process's engine,
        built once on first use (the scheduler tiers build their own
        per generation through build_front_door — never this one)."""
        if self._draft_model is None and self.draft:
            from ..runtime.draft import build_draft

            with self.engine_lock:
                if self._draft_model is None:
                    self._draft_model = build_draft(self.engine,
                                                    self.draft)
        return self._draft_model


def _raw_prompt_body(body: dict) -> bool:
    """A /v1/completions-shaped body (raw `prompt`, no chat template or
    chat EOS markers). Inferred from the body, not the route, so the
    multi-host worker replay (apps/dllama.cmd_worker re-runs the raw body
    through _completion_chunks) handles both endpoints with no protocol
    change."""
    return "messages" not in body and "prompt" in body


def _piece_scanner(tokenizer, first_prev: int, markers, stops):
    """Per-token text scan shared by the single-request streams (the
    legacy and scheduler paths): eos / chat-marker / stop-sequence
    semantics live exactly once — the batch endpoint's per-row scan_token
    mirrors the same rules with per-row state. Returns scan(tok) -> the
    decoded piece to emit, or None when the request just STOPPED (the
    token is consumed, never emitted)."""
    scan_state = {"prev": first_prev, "tail": ""}
    tail_len = max([len(m) for m in markers]
                   + [len(s) for s in stops] + [1]) + 16
    eos = tokenizer.eos_id

    def scan(tok: int) -> str | None:
        if tok == eos:
            return None
        piece = tokenizer.decode_piece(scan_state["prev"], tok).decode(
            "utf-8", errors="replace")
        scan_state["prev"] = tok
        # bounded trailing window (ref: dllama-api.cpp:272-286)
        scan_state["tail"] = (scan_state["tail"] + piece)[-tail_len:]
        if (any(m in scan_state["tail"] for m in markers)
                or (stops and any(s in scan_state["tail"] for s in stops))):
            return None
        return piece

    return scan


def _completion_chunks(state: ApiState, body: dict):
    """Generator of generated text pieces for one request (the legacy
    single-engine path: prefix reuse, lookup decode, shared sampler)."""
    engine, tokenizer, sampler = state.engine, state.tokenizer, state.sampler

    if _raw_prompt_body(body):
        prompt = body.get("prompt") or ""
        markers: tuple = ()
    else:
        prompt = build_chat_prompt(body.get("messages", []))
        markers = CHAT_EOS_MARKERS
    max_tokens = int(body.get("max_tokens", 0) or 0)
    stops = body.get("stop") or []
    if isinstance(stops, str):
        stops = [stops]

    tokens = tokenizer.encode(prompt)
    if len(tokens) >= engine.seq_len:
        raise PromptTooLong(
            f"prompt is {len(tokens)} tokens; context is {engine.seq_len}")

    # prefix/session reuse (net-new vs the reference's full per-request
    # reset, ref: dllama-api.cpp:236-249): chat turns share the system
    # prompt + history, and on TPU the re-prefill is the expensive part of
    # a turn. Keep the longest common token prefix of the previous
    # session's cache and prefill only the suffix — positions >= the kept
    # prefix hold stale K/V that this request overwrites position-by-
    # position before any of its queries can attend them (the same
    # invariant decode overruns rely on, runtime/engine.py).
    lcp = 0
    if jax.process_count() == 1:
        # multi-host clusters skip reuse: it is only collective-safe while
        # every process's cached_tokens agree, and a worker-local failure
        # resync (apps/dllama.cmd_worker) legitimately clears one side —
        # the next request must then prefill identically everywhere
        while (lcp < len(state.cached_tokens) and lcp < len(tokens) - 1
               and state.cached_tokens[lcp] == tokens[lcp]):
            lcp += 1
    if lcp > 0:
        engine.pos = lcp
    else:
        engine.reset()
    suffix = tokens[lcp:]
    state.cached_tokens = []  # repopulated on success below

    # per-request sampler params must not leak into later requests that omit
    # them — temperature AND the RNG stream position are restored in the
    # finally below (a request's "seed" must not permanently reseed the
    # shared sampler)
    saved_temp = sampler.temperature
    saved_rng_state = None
    if body.get("temperature") is not None:
        sampler.set_temp(float(body["temperature"]))
    if body.get("seed") is not None:
        saved_rng_state = sampler.rng_state
        sampler.set_seed(int(body["seed"]))

    limit = engine.seq_len - len(tokens) - 1
    n_gen = min(max_tokens, limit) if max_tokens > 0 else limit

    n_prompt = len(tokens)
    scan = _piece_scanner(tokenizer, tokens[-1], markers, stops)
    emitted = 0
    finish = "length"
    def plain_tokens():
        """Reference-parity sampled loop as a token iterator: yield, then
        step the token only if the consumer pulls again (so the last
        emitted token is never stepped — same as the host generate())."""
        logits = engine.prefill(suffix)
        for _ in range(n_gen):
            tok = sampler.sample(engine.fetch_logits(logits)[0])
            yield tok
            if engine.pos >= engine.seq_len:
                return
            logits = engine.step(np.asarray([[tok]], np.int32), engine.pos)
            history.append(tok)  # stepping tok wrote its K/V

    # requests can speculate: prompt-lookup drafts verified in one forward.
    # Greedy requests stream the EXACT greedy tokens (argmax verify); at
    # temperature > 0 the rejection-resampling mode keeps every emitted
    # token distributed exactly as a host-sampler draw, but on a DERIVED
    # numpy RNG — the token stream is not the plain path's xorshift stream
    # (acceptance consumes a data-dependent number of uniforms, so coin
    # parity is impossible by construction — runtime/speculative.py). Safe
    # on multi-host clusters: prefix reuse is off there, so every process
    # replays the identical request from token 0, mines identical drafts,
    # and (sampled mode) derives the identical seed from the replicated
    # sampler stream (Sampler.next_seed) — same verify widths, collectives
    # in lock-step (the --lookup-decode flag itself is in the cluster
    # config fingerprint)
    use_lookup = state.lookup_decode > 0
    use_draft = state.draft is not None
    history = list(tokens)  # every prompt position is written by prefill
    # history bookkeeping ownership: the lookup streams do NOT append their
    # emitted tokens (their K/V is already written by the verify forward, so
    # the consumer loop appends), while plain_tokens() appends as it steps.
    # `speculating` — not `use_lookup` — gates the consumer-side append, so a
    # request that falls through to the plain loop (e.g. a client-supplied
    # NEGATIVE temperature) keeps exactly one owner and the prefix cache
    # stays aligned with real K/V positions.
    speculating = False
    try:
        if use_draft and sampler.temperature == 0.0:
            # real-draft speculation (runtime/draft.py): bit-identical
            # greedy stream, drafts from the model's own truncated-depth
            # prefix (or a separate draft .m) — pays on arbitrary text
            speculating = True
            token_iter = engine.generate_draft_stream(
                suffix, n_gen, history=tokens,
                draft=state.draft_model(), draft_len=state.draft_len or 7,
                vocab_size=tokenizer.vocab_size)
        elif use_draft and sampler.temperature > 0.0:
            speculating = True
            token_iter = engine.generate_draft_sampled_stream(
                suffix, n_gen, history=tokens,
                draft=state.draft_model(),
                temperature=sampler.temperature, topp=sampler.topp,
                seed=sampler.next_seed(),
                draft_len=state.draft_len or 7,
                vocab_size=tokenizer.vocab_size)
        elif use_lookup and sampler.temperature == 0.0:
            speculating = True
            token_iter = engine.generate_lookup_stream(
                suffix, n_gen, history=tokens,
                draft_len=state.lookup_decode,
                vocab_size=tokenizer.vocab_size)
        elif use_lookup and sampler.temperature > 0.0:
            speculating = True
            token_iter = engine.generate_lookup_sampled_stream(
                suffix, n_gen, history=tokens,
                temperature=sampler.temperature, topp=sampler.topp,
                seed=sampler.next_seed(),
                draft_len=state.lookup_decode,
                vocab_size=tokenizer.vocab_size)
        else:
            token_iter = plain_tokens()
        for tok in token_iter:
            piece = scan(tok)
            if piece is None:  # eos / chat marker / stop sequence
                finish = "stop"
                break
            emitted += 1
            if speculating:
                history.append(tok)  # its K/V position is already written
            yield ("piece", piece)
        state.cached_tokens = history[: engine.pos]
    finally:
        sampler.set_temp(saved_temp)
        if saved_rng_state is not None:
            sampler.rng_state = saved_rng_state
        if speculating:
            # fold the request's accept record into the LEGACY tier's
            # aggregate `spec` block (the scheduler tiers count inline)
            ls = getattr(engine, "last_spec", None)
            if ls:
                ss = state.spec_stats
                ss.verify_forwards += ls["forwards"]
                ss.drafted += ls["drafted"]
                ss.accepted += ls["accepted"]
                ss.emitted_spec += ls["emitted"]
    yield ("done", {"finish_reason": finish,
                    "prompt_tokens": n_prompt,
                    "completion_tokens": emitted})


def _prefix_would_hit(door, tokens: list[int]) -> bool:
    """Would this prompt seed from a radix prefix cache anywhere in the
    tier? The ladder's prefix_only rung admits only work that reuses
    cached KV (cheap prefill). Read-only peeks (match_len /
    kv_match_len), never a pin; any failure reads as a miss — under
    overload the conservative answer is to shed."""
    try:
        handles = getattr(door, "replicas", None)
        if handles:
            return any(h.match_len(tokens) > 0 for h in handles
                       if not getattr(h, "reap", False))
        sched = getattr(door, "_sched", None)
        if sched is not None:
            return sched.kv_match_len(tokens) > 0
    except Exception:  # noqa: BLE001 — a mid-recovery replica peek
        pass           # must never turn the shed door into a 500
    return False


def _sched_completion_chunks(state: ApiState, body: dict, chat: bool = True):
    """Scheduler-path generator for one /v1/completions or
    /v1/chat/completions request: enqueue onto the shared
    continuous-batching scheduler (runtime/scheduler.py) and stream pieces
    as the request's slot produces tokens — concurrent requests decode in
    ONE batched step loop instead of serializing on the engine.

    Per-request temperature/seed become a PRIVATE Sampler (the slot's RNG
    state), so concurrent requests never contend for the shared sampler's
    coin stream; omitted seeds derive from it (Sampler.next_seed) under the
    engine lock so results stay run-to-run deterministic. No prefix reuse
    on this path: slots are leased per request (the legacy single-engine
    path keeps the feature). Text-level stops cancel the request, freeing
    its slot immediately."""
    from ..sampler import Sampler

    tokenizer = state.tokenizer
    sched = state.scheduler()
    engine = sched.engine
    if chat and not _raw_prompt_body(body):
        prompt = build_chat_prompt(body.get("messages", []))
        markers: tuple = CHAT_EOS_MARKERS
    else:
        prompt = body.get("prompt") or ""
        markers = ()
    max_tokens = int(body.get("max_tokens", 0) or 0)
    stops = body.get("stop") or []
    if isinstance(stops, str):
        stops = [stops]

    tokens = tokenizer.encode(prompt)
    temp = (state.sampler.temperature if body.get("temperature") is None
            else float(body["temperature"]))
    with state.engine_lock:  # the shared stream is also the legacy path's
        seed = (int(body["seed"]) if body.get("seed") is not None
                else state.sampler.next_seed())
    sampler = Sampler(tokenizer.vocab_size, temperature=temp,
                      topp=state.sampler.topp, seed=seed)
    limit = engine.seq_len - len(tokens) - 1
    n_gen = min(max_tokens, limit) if max_tokens > 0 else limit
    # the fleet brain's overload door (runtime/fleet.py): walk the shed
    # ladder BEFORE submit — speculation off and max_tokens clamps are
    # invisible degradation, prefix-only and shed raise ShedReject which
    # the handler maps to a structured 429 (Retry-After from the live
    # drain rate). Runs before any slot work, so a shed costs nothing.
    tenant = body.get("tenant")
    priority = str(body.get("priority") or "normal")
    fleet = state.fleet()
    if fleet is not None:
        n_gen = fleet.admit(tenant=tenant, n_prompt=len(tokens),
                            max_tokens=n_gen,
                            prefix_hit=_prefix_would_hit(sched, tokens))
    # PromptTooLong raises HERE (before any event) — the handler still
    # turns it into a clean 400 through the queued/threaded path
    kwargs = {}
    if state.router_mode:
        # multi-replica tier: the OpenAI `user` field (or an explicit
        # `session`) keys replica stickiness, so a conversation keeps
        # hitting the replica whose radix tree caches its history
        session = body.get("session") or body.get("user")
        if session is not None:
            kwargs["session"] = str(session)
    req = sched.submit(tokens, n_gen, sampler, eos_id=tokenizer.eos_id,
                       tenant=tenant, priority=priority, **kwargs)

    scan = _piece_scanner(tokenizer, tokens[-1], markers, stops)
    emitted = 0
    finish = "length"
    err = None
    try:
        for tok in req.tokens():
            piece = scan(tok)
            if piece is None:  # eos / chat marker / stop sequence
                finish = "stop"
                break
            emitted += 1
            yield ("piece", piece)
    except RequestError as e:
        # structured failure frame (crash/stall recovery, deadline,
        # shutdown): the stream TERMINATES with finish_reason "error" and
        # the frame rides the done event — an already-streaming SSE client
        # receives an explicit error event, never a silent hang
        finish = "error"
        err = e.frame()
    finally:
        # no-op after a natural finish; on text-level stops, client
        # disconnects and generator teardown it frees the slot NOW
        req.cancel()
    done = {"finish_reason": finish,
            "prompt_tokens": len(tokens),
            "completion_tokens": emitted}
    if err is not None:
        done["error"] = err
    yield ("done", done)


def _batch_completion_chunks(state: ApiState, body: dict):
    """POST /v1/batch/completions generator: up to serve_batch prompts
    decoded in ONE batched engine (net-new vs the reference's batch=1
    server — decode is weight-read-bound, so b rows amortize one weight
    read; bench.py's _batch_row measures the aggregate-throughput win).

    Yields ("piece", (row, piece)) events then one ("done", {...}) with
    per-row finish/usage. Per-request temperature/seed apply to the whole
    batch through the shared reference-parity sampler stream (coins drawn
    in row order — Sampler.sample_batch); rows are independent sequences.
    No prefix reuse here: the batch cache is reset per request (the
    single-request endpoint keeps that feature). The engine is BORROWED
    from the scheduler (Scheduler.exclusive drains in-flight slot work
    first) — one process, one live batched KV cache."""
    sched = state.scheduler()
    engine = sched.engine
    tokenizer, sampler = state.tokenizer, state.sampler

    # parse EVERY request field BEFORE taking the scheduler's engine: a
    # malformed value (non-numeric temperature/seed, a non-string stop or
    # prompt) must fail THIS request as a 400, never leave the exclusive
    # lock held or read as a retryable engine failure
    try:
        if "prompts" in body:
            texts = body["prompts"]
            raw = True
        else:
            texts = [build_chat_prompt(m)
                     for m in body.get("messages_list", [])]
            raw = False
        b = len(texts)
        if not (1 <= b <= state.serve_batch):
            raise PromptTooLong(
                f"batch size {b} outside 1..{state.serve_batch} "
                "(server started with --serve-batch "
                f"{state.serve_batch})")
        max_tokens = int(body.get("max_tokens", 64))
        want_stream = bool(body.get("stream", False))
        stops = body.get("stop") or []
        if isinstance(stops, str):
            stops = [stops]

        rows = [tokenizer.encode(t) for t in texts]  # add_bos default,
        limit = engine.seq_len - 1                   # like the single path
        for i, r in enumerate(rows):
            if len(r) >= limit:
                raise PromptTooLong(
                    f"prompt {i}: {len(r)} tokens >= context {limit}")
        # budget: MAX over rows of the per-row cache headroom (rows share
        # the step loop; a longer-prompt row hitting seq_len retires only
        # itself — the engine's per-row pos guard — so one long prompt
        # must not cap the shorter rows' output). max_tokens <= 0 means
        # "generate to the context limit", like the single endpoint.
        headroom = max(limit - len(r) for r in rows)
        n_gen = min(max_tokens, headroom) if max_tokens > 0 else headroom
        n_prompt_toks = sum(len(r) for r in rows)  # before padding joins

        req_temp = (float(body["temperature"])
                    if body.get("temperature") is not None else None)
        req_seed = (int(body["seed"])
                    if body.get("seed") is not None else None)
        markers = () if raw else CHAT_EOS_MARKERS
        tail_len = max([len(m) for m in markers]
                       + [len(s) for s in stops] + [1]) + 16
        prev = [r[-1] for r in rows]
    except PromptTooLong:
        raise
    except (ValueError, TypeError, KeyError, AttributeError) as e:
        raise BadRequest(f"{type(e).__name__}: {e}") from e
    tails = [""] * b
    emitted = [0] * b
    finish = ["length"] * b
    # the engine's batch is a build-time shape: pad sub-batch requests with
    # pre-retired rows (flagged before the first step, so they never sample
    # — no coins leave the shared stream — and never emit)
    n_pad = engine.batch - b
    rows = rows + [[rows[0][0]]] * n_pad
    stop_flags = np.zeros(engine.batch, bool)
    stop_flags[b:] = True

    def scan_token(i: int, tok: int) -> str | None:
        """Shared per-token body of both batch paths: eos / marker /
        stop-sequence semantics live exactly once. Returns the decoded
        piece to emit, or None when row i just STOPPED (finish[i] set;
        the caller applies its own retirement mechanics)."""
        if tok == tokenizer.eos_id:
            finish[i] = "stop"
            return None
        piece = tokenizer.decode_piece(prev[i], tok).decode(
            "utf-8", errors="replace")
        prev[i] = tok
        tails[i] = (tails[i] + piece)[-tail_len:]
        if (any(m in tails[i] for m in markers)
                or (stops and any(s in tails[i] for s in stops))):
            finish[i] = "stop"
            return None
        emitted[i] += 1
        return piece

    # borrow the scheduler's engine for the whole-batch run: exclusive()
    # drains in-flight slot requests, then blocks the step loop until the
    # block exits. A real `with` (not manual __enter__/__exit__(None,..)):
    # a crash inside the borrow must propagate THROUGH the supervised
    # context manager so EngineSupervisor recovery runs, and a generator
    # teardown mid-stream (GeneratorExit) still unwinds it and releases
    # the scheduler. Everything fallible was parsed above.
    with sched.exclusive():
        saved_temp = sampler.temperature
        saved_rng_state = None
        if req_temp is not None:
            sampler.set_temp(req_temp)
        if req_seed is not None:
            saved_rng_state = sampler.rng_state
            sampler.set_seed(req_seed)
        try:
            engine.reset()  # slots drained; the borrowed cache starts clean
            if state.lookup_decode > 0 and sampler.temperature == 0.0:
                # greedy batch requests SPECULATE
                # (Engine.generate_batch_lookup — per-row drafts, one
                # verify forward per step, exact per-row greedy parity;
                # bench measured 368-407 aggregate tok/s vs 355
                # plain-batch). Collected, not streamed: text-level stop
                # sequences trim each row post-hoc — a stopped row may
                # have burned some extra forwards, which multi-token
                # accepts more than repay; the batch cache resets per
                # request, so the overrun positions leak nothing.
                # For STREAMING requests the collect runs on a helper
                # thread so keepalive events flow meanwhile (first byte
                # within KEEPALIVE_SECS, not full batch completion —
                # ADVICE r5). Non-streaming requests collect inline: a
                # keepalive has no one to reach, and keeping the whole
                # collect before the first yield preserves the clean
                # 400/503 mapping at the handler's next(gen)
                if want_stream:
                    box: dict = {}

                    def _collect():
                        try:
                            box["outs"] = engine.generate_batch_lookup(
                                rows, n_gen, eos_id=tokenizer.eos_id,
                                draft_len=state.lookup_decode,
                                vocab_size=tokenizer.vocab_size,
                                stop_flags=stop_flags)
                        except BaseException as e:  # noqa: BLE001 —
                            box["err"] = e  # re-raised on the generator
                    t = threading.Thread(target=_collect, daemon=True)
                    t.start()
                    try:
                        while True:
                            t.join(timeout=KEEPALIVE_SECS)
                            if not t.is_alive():
                                break
                            yield ("keepalive", None)
                    finally:
                        # a torn-down generator (client disconnect) must
                        # NOT release the exclusive borrow while the
                        # collect thread still drives the engine — block
                        # until done
                        t.join()
                    if "err" in box:
                        # inside the exclusive borrow: engine failures
                        # walk the same supervisor recovery as the sync
                        # path did
                        raise box["err"]
                    outs = box["outs"]
                else:
                    outs = engine.generate_batch_lookup(
                        rows, n_gen, eos_id=tokenizer.eos_id,
                        draft_len=state.lookup_decode,
                        vocab_size=tokenizer.vocab_size,
                        stop_flags=stop_flags)
                for i in range(b):
                    for tok in outs[i]:
                        piece = scan_token(i, tok)
                        if piece is None:
                            break
                        yield ("piece", (i, piece))
            else:
                for step in engine.generate_batch_stream(
                        rows, n_gen, sampler, stop_flags=stop_flags):
                    for i, tok in enumerate(step):
                        if tok is None or stop_flags[i]:
                            continue
                        piece = scan_token(i, tok)
                        if piece is None:
                            stop_flags[i] = True
                            continue
                        yield ("piece", (i, piece))
        finally:
            sampler.set_temp(saved_temp)
            if saved_rng_state is not None:
                sampler.rng_state = saved_rng_state
            engine.reset()  # the batch cache holds nothing reusable
    yield ("done", {
        "finish_reasons": finish,
        "prompt_tokens": n_prompt_toks,
        "completion_tokens": sum(emitted),
    })


def load_server_session(state: ApiState, path: str) -> None:
    """Restore a previous server process's prefix cache + token history
    (Engine.load_session — refuses a mismatched model via the content
    fingerprint). A follow-up request whose prompt extends the saved
    conversation then re-prefills only its suffix, and the response is
    byte-identical to the no-restart path (net-new — the reference resets
    all state per request AND per process, ref: dllama-api.cpp:236-249)."""
    tokens = state.engine.load_session(path)
    # the cache holds K/V for exactly engine.pos positions; tokens beyond
    # that (a chat's final unstepped token) must not count as cached
    state.cached_tokens = tokens[: state.engine.pos]


def save_server_session(state: ApiState, path: str) -> bool:
    """Persist the live prefix cache + its token history
    (Engine.save_session). Called on server shutdown — the cache fetch is
    O(pos * layers * kv_dim) host bytes, too heavy per-request for big
    models but free at exit.

    A shutdown landing mid-request (client disconnect, signal) leaves
    cached_tokens empty while engine.pos is large — saving then would
    clobber a previously good file with an unusable one, so the save is
    SKIPPED (False) and any prior file stays; it is self-consistent (its
    cache bytes came from the file's own tokens) even though the live
    engine moved past it. The cache is also never saved beyond the token
    history that describes it."""
    if not state.cached_tokens:
        return False
    eng = state.engine
    eng.pos = min(eng.pos, len(state.cached_tokens))
    eng.save_session(path, tokens=state.cached_tokens)  # atomic (tmp+rename)
    return True


def _chunk_env(rid: str, created: int, model: str, index: int,
               delta: dict, finish_reason) -> dict:
    """One SSE chat.completion.chunk envelope (shared by the single- and
    batch-request streams; only the choice index differs between them)."""
    return {"id": rid, "object": "chat.completion.chunk", "created": created,
            "model": model,
            "choices": [{"index": index, "delta": delta,
                         "finish_reason": finish_reason}]}


def _completion_env(rid: str, created: int, model: str, choices: list,
                    prompt_tokens: int, completion_tokens: int) -> dict:
    """The non-streamed chat.completion envelope + usage
    (ref: types.hpp:10-91)."""
    return {"id": rid, "object": "chat.completion", "created": created,
            "model": model, "choices": choices,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": completion_tokens,
                      "total_tokens": prompt_tokens + completion_tokens}}


def _text_chunk_env(rid: str, created: int, model: str, text: str,
                    finish_reason) -> dict:
    """One SSE text_completion chunk for the raw /v1/completions route."""
    return {"id": rid, "object": "text_completion", "created": created,
            "model": model,
            "choices": [{"index": 0, "text": text,
                         "finish_reason": finish_reason}]}


def _text_completion_env(rid: str, created: int, model: str, text: str,
                         finish_reason, prompt_tokens: int,
                         completion_tokens: int) -> dict:
    """The non-streamed text_completion envelope (/v1/completions)."""
    return {"id": rid, "object": "text_completion", "created": created,
            "model": model,
            "choices": [{"index": 0, "text": text,
                         "finish_reason": finish_reason}],
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": completion_tokens,
                      "total_tokens": prompt_tokens + completion_tokens}}


def make_handler(state: ApiState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *fargs):  # quiet
            pass

        def _json(self, code: int, obj: dict,
                  retry_after: float | None = None) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            if retry_after is not None:
                # overload/recovery rejections tell the client WHEN to come
                # back instead of letting it hammer or queue unboundedly
                self.send_header("Retry-After",
                                 str(max(1, int(round(retry_after)))))
            self.end_headers()
            self.wfile.write(data)

        # SSE chunked streaming (ref: dllama-api.cpp:125-145,183-200)
        def _sse_start(self) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()

        def _sse(self, obj: dict) -> None:
            self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
            self.wfile.flush()

        def _sse_done(self) -> None:
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()

        def do_GET(self):
            if self.path == "/v1/models":
                # ref: dllama-api.cpp:316-322
                self._json(200, {"object": "list", "data": [
                    {"id": state.model_name, "object": "model",
                     "created": int(time.time()), "owned_by": "user"}]})
            elif self.path in ("/", "/health", "/healthz"):
                # liveness: the process is up and serving HTTP — true even
                # while the engine recovers (that is /readyz's business) or
                # the server drains (it reports so, but stays 200: a
                # liveness-restart would cut the drain short). The build
                # block answers in EVERY tier (never 404s off a launch
                # flag — the PR-8 rule): version skew across a replica
                # fleet is an outage class, and the probe everyone
                # already scrapes is where it must show.
                self._json(200, {"status": "draining" if state.draining
                                 else "ok",
                                 "build": state.build_info()})
            elif self.path == "/readyz":
                self._readyz()
            elif self.path == "/stats":
                # serving observability: TTFT/ITL percentiles, slot
                # occupancy, queue depth (runtime/stats.ServeStats). A
                # stats read must never be the thing that allocates the
                # batched cache — report idle until a request builds it.
                if state.serve_batch <= 0:
                    # legacy tier: the speculative accept record still
                    # answers (a tier must not lose the family to a
                    # launch flag — the scheduler tiers carry theirs on
                    # the summary)
                    payload = {"scheduler": "off",
                               "spec": state.spec_stats.summary()}
                elif state._scheduler is None:
                    payload = {"scheduler": "idle"}
                else:
                    # supervisor summary: scheduler counters (totals carried
                    # across recoveries) + the resilience block
                    payload = state._scheduler.summary()
                # multihost root: the control-plane block (heartbeat
                # counters, peer losses, phase — runtime/stats.ClusterStats)
                from ..parallel.multihost import cluster_summary
                cluster = cluster_summary()
                if cluster is not None:
                    payload["cluster"] = cluster
                    # the measured wire ledger, hoisted as its own block
                    # (dlwire): per-peer bytes/frames by MSG kind and
                    # direction, heartbeat RTT, clock offsets
                    if cluster.get("wire"):
                        payload["wire"] = cluster["wire"]
                if "kv_transfer" not in payload:
                    # legacy/idle/single-supervisor tiers: the transfer
                    # plane cannot exist here (it needs replicas), but
                    # the family must not vanish off a launch flag —
                    # the block answers enabled=False (router tiers
                    # carry the real aggregate on their summary)
                    from ..runtime.stats import KVTransferStats
                    payload["kv_transfer"] = KVTransferStats().summary()
                # the fleet brain's block (runtime/fleet.py): autoscale
                # decisions, ladder rung, per-tenant accounting — the
                # same tier-invariance rule, so an idle/legacy tier
                # answers enabled=False instead of losing the family
                from ..runtime.stats import FleetStats
                payload["fleet"] = (state._fleet.summary()
                                    if state._fleet is not None
                                    else FleetStats().summary())
                from ..runtime.trace import TRACER
                if TRACER.enabled:
                    payload["trace"] = TRACER.summary()
                if state.autosize:
                    # the startup auto-sizing decision (chosen shape +
                    # every input) — present in EVERY scheduler state,
                    # idle included: the decision was made at startup
                    payload["autosize"] = state.autosize
                self._json(200, payload)
            elif self.path == "/metrics":
                self._metrics()
            elif (self.path == "/admin/trace"
                  or self.path.startswith("/admin/trace?")):
                self._admin_trace()
            else:
                self._json(404, {"error": "not found"})

        def _metrics(self) -> None:
            """GET /metrics — Prometheus text exposition, identical names
            in every serving tier (legacy single-engine, --serve-batch
            supervisor, --replicas thread router, --replica-procs/-hosts
            process router): the renderer consumes the SAME summary dict
            /stats already serves, so a tier cannot drift its own metric
            namespace. Answers in every tier (legacy/idle emit process-
            level series only) — a scrape target must never 404 off a
            launch flag."""
            from ..parallel.multihost import cluster_summary
            from ..runtime.trace import TRACER, render_prometheus

            # mode comes from the CONFIG, not the lazily-built front
            # door: a router tier must label its series mode="router"
            # from the first scrape (a label flip after the first
            # request would split every dllama_up series in two)
            if state.serve_batch <= 0:
                payload, mode, st = None, "legacy", "off"
            else:
                mode = "router" if state.router_mode else "scheduler"
                if state._scheduler is None:
                    # a scrape must never be the thing that allocates
                    # the batched cache (same rule as /stats, /readyz)
                    payload, st = None, "idle"
                else:
                    payload, st = state._scheduler.summary(), None
            cluster = cluster_summary()
            payload = dict(payload or {})
            if cluster is not None:
                payload["cluster"] = cluster
            if state.autosize:
                # dllama_autosize_* gauges from the startup decision —
                # visible from the FIRST scrape (idle included)
                payload["autosize"] = state.autosize
            # device-tier blocks for the tiers whose summary has none:
            # the compile ledger is process-global (legacy engines mint
            # through it too — the supervisor summary carries the same
            # singleton), and on NON-router tiers the engine's HBM is
            # live memory worth scraping. Router tiers deliberately
            # carry NO top-level hbm (runtime/router.Router.summary —
            # per-replica blocks are the truth there; state.engine is
            # an idle template whose headroom would mislead the batch
            # auto-sizing).
            if "compiles" not in payload:
                from ..runtime.profiler import COMPILES

                payload["compiles"] = COMPILES.summary()
            if "spec" not in payload and not state.router_mode:
                # legacy/idle tiers: the process-level accept record
                # (router tiers carry the family per replica — the
                # aggregate summary deliberately has no top-level block)
                payload["spec"] = state.spec_stats.summary()
            if "kv_transfer" not in payload:
                # same tier-invariance rule for the transfer plane: a
                # legacy/idle scrape renders the family as enabled=False
                from ..runtime.stats import KVTransferStats
                payload["kv_transfer"] = KVTransferStats().summary()
            if "fleet" not in payload:
                # dllama_fleet_* in every tier incl. idle: enabled=False
                # zeros until the controller exists (same rule again)
                from ..runtime.stats import FleetStats
                payload["fleet"] = (state._fleet.summary()
                                    if state._fleet is not None
                                    else FleetStats().summary())
            if ("hbm" not in payload and state.engine is not None
                    and not state.router_mode):
                from ..runtime.profiler import hbm_ledger

                try:
                    payload["hbm"] = hbm_ledger(state.engine)
                except Exception:  # noqa: BLE001 — a weightless front
                    pass           # template has no ledger-able arrays
            data = render_prometheus(payload, tracer=TRACER,
                                     model=state.model_name, mode=mode,
                                     state=st,
                                     build=state.build_info()).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _admin_trace(self) -> None:
            """GET /admin/trace[?n=200|?id=TID] — the flight-recorder
            ring as JSONL (docs/observability.md schema): first line the
            clock anchor, then one event per line, wall timestamps
            attached at export. Operator surface, so the same guard as
            the POST /admin/* verbs (loopback or --admin-token)."""
            if not _admin_authorized(state, self.client_address[0],
                                     self.headers.get("Authorization")):
                self._json(403, {"error": "admin endpoints need loopback "
                                          "or a valid --admin-token "
                                          "bearer"})
                return
            from urllib.parse import parse_qs, urlparse

            from ..runtime.trace import TRACER

            if not TRACER.enabled:
                self._json(404, {"error": "tracing off (start with "
                                          "--trace)"})
                return
            from ..runtime.trace import EVENT_KINDS

            try:
                # keep_blank_values: "kind=" must be rejected as garbage
                # below, not silently dropped into an unfiltered dump
                q = parse_qs(urlparse(self.path).query,
                             keep_blank_values=True)
                tid = int(q["id"][0]) if "id" in q else None
                n = int(q.get("n", ["200"])[0])
                if n < 0 or (tid is not None and tid < 0):
                    # a negative n would slice the WRONG end of the ring
                    # (evs[-n:] == evs[n:]) — reject, don't dump
                    raise ValueError(n)
                # kind= / since_ms= filters: validated, 400 on garbage —
                # a typo'd kind must not silently return an empty (or
                # unfiltered) dump an operator then misreads
                kind = q["kind"][0] if "kind" in q else None
                if kind is not None and kind not in EVENT_KINDS:
                    raise ValueError(kind)
                since_ms = (float(q["since_ms"][0]) if "since_ms" in q
                            else None)
                if since_ms is not None and not since_ms >= 0:
                    # `not >=` also rejects NaN, which every ts compare
                    # below would silently pass
                    raise ValueError(since_ms)
            except (ValueError, IndexError):
                self._json(400, {"error": "bad request"})
                return
            filtered = kind is not None or since_ms is not None
            # with filters on, filter over the WHOLE ring then tail n —
            # slicing first would make n pre-filter events, so a sparse
            # kind could return nothing even though matches exist
            events = TRACER.by_id(tid) if tid is not None \
                else TRACER.recent(0 if filtered else n)
            if kind is not None:
                events = [e for e in events if e.get("kind") == kind]
            if since_ms is not None:
                cut = time.perf_counter() - since_ms / 1e3
                events = [e for e in events if e.get("ts", 0.0) >= cut]
            if tid is None and filtered and n:
                events = events[-n:]
            lines = [json.dumps({"anchor_wall": TRACER.anchor_wall,
                                 "anchor_mono": TRACER.anchor_mono,
                                 "events": len(events)})]
            lines += [json.dumps({**e,
                                  "ts_wall": TRACER.to_wall(e["ts"])})
                      for e in events]
            data = ("\n".join(lines) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _readyz(self) -> None:
            """Readiness = engine healthy AND queue under bound (and not
            draining). 503 + Retry-After otherwise — the load balancer's
            signal to route elsewhere."""
            if state.cluster_lost is not None:
                # a cluster peer is gone: this replica cannot serve until
                # an operator restores it (the process is about to take
                # its diagnostic exit — answer honestly meanwhile)
                self._json(503, {"status": "cluster_lost",
                                 "detail": state.cluster_lost.summary()},
                           retry_after=30.0)
            elif state.draining:
                self._json(503, {"status": "draining"}, retry_after=1.0)
            elif state.serve_batch <= 0:
                # legacy single-engine server: always ready (requests
                # serialize behind engine_lock, no supervised loop)
                self._json(200, {"status": "ready", "scheduler": "off"})
            elif state._scheduler is None:
                # supervisor builds on first request; a readiness probe
                # must not be the thing that allocates the batched cache
                self._json(200, {"status": "ready", "scheduler": "idle"})
            else:
                sup = state._scheduler
                payload = {"state": sup.state}
                if state.router_mode:
                    # multi-replica tier: readiness is ANY-replica (one
                    # failure must not unready the service); the per-
                    # replica states ride along for the operator
                    # suffix the ROUTER-level conditions the supervisor
                    # state can't see — a replica can be supervisor-ready
                    # yet unrouted (drained or circuit open), and the
                    # operator needs to see WHY from the probe body
                    # a replica draining FOR REAP (fleet scale-down) is
                    # expected capacity loss, not ill health: it shows
                    # here as /reaping but never flips fleet readiness
                    # (Router.state + _routable exclude reap handles)
                    payload["replicas"] = {
                        f"r{h.id}": (h.state
                                     + ("/draining" if h.draining else "")
                                     + ("/reaping"
                                        if getattr(h, "reap", False)
                                        else "")
                                     + ("/breaker_open"
                                        if h.open_until > 0.0 else ""))
                        for h in sup.replicas}
                if sup.ready:
                    self._json(200, {"status": "ready", **payload})
                else:
                    self._json(503, {"status": "unready", **payload},
                               retry_after=sup._retry_after())

        def do_POST(self):
            if self.path.startswith("/admin/"):
                # operator surface: dispatched BEFORE the draining check —
                # an operator must be able to reset a breaker or undrain
                # a replica while the front door refuses client traffic
                self._admin_post()
                return
            if self.path not in ("/v1/chat/completions", "/v1/completions",
                                 "/v1/batch/completions"):
                self._json(404, {"error": "not found"})
                return
            if state.draining:
                # graceful drain: in-flight requests finish, NEW work is
                # refused fast so the client retries a live replica
                self._json(503, {"error": "server draining"},
                           retry_after=2.0)
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError):
                self._json(400, {"error": "bad request"})
                return
            if self.path == "/v1/batch/completions":
                self._batch_post(body)
            else:
                self._chat_post(body,
                                chat=self.path == "/v1/chat/completions")

        def _admin_post(self) -> None:
            """Operator endpoints (docs/operations.md "Multi-replica
            operations"): loopback-guarded (403 otherwise), never
            404-dependent on launch flags once --serve-batch is on.

              POST /admin/reset_breaker   {replica?: i}  — operator
                   half-open for the engine breaker (BROKEN state) and
                   the router circuit; omitting `replica` resets ALL.
                   This is the HTTP face of reset_breaker(): before it,
                   a BROKEN supervisor in api mode was an outage only a
                   Python REPL could end.
              POST /admin/drain_replica   {replica: i, timeout?: s}
              POST /admin/restart_replica {replica: i, timeout?: s}
              POST /admin/undrain_replica {replica: i}
                   — the rolling-restart recipe, one replica at a time
                   (multi-replica servers only)."""
            if not _admin_authorized(state, self.client_address[0],
                                     self.headers.get("Authorization")):
                self._json(403, {"error": "admin endpoints need loopback "
                                          "or a valid --admin-token "
                                          "bearer"})
                return
            if (self.path == "/admin/profile"
                    or self.path.startswith("/admin/profile?")):
                # on-demand capture: ALL tiers, legacy included — routed
                # before the supervised-scheduler checks below
                self._admin_profile()
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                replica = body.get("replica")
                if replica is not None:
                    replica = int(replica)
                timeout = float(body.get("timeout", 30.0))
            except (ValueError, TypeError, json.JSONDecodeError):
                self._json(400, {"error": "bad request"})
                return
            if state.serve_batch <= 0:
                self._json(404, {"error": "no supervised scheduler "
                                          "(start with --serve-batch N)"})
                return
            sup = state._scheduler
            if sup is None:
                # nothing built yet — nothing to reset or drain; answer
                # idempotently rather than building the engine stack
                # from an admin poke
                self._json(200, {"status": "idle"})
                return
            from ..runtime.router import Router
            is_router = isinstance(sup, Router)
            if replica is not None and not (
                    is_router and 0 <= replica < len(sup.replicas)):
                n = len(sup.replicas) if is_router else 1
                self._json(400, {"error": f"no replica {replica} "
                                 f"(tier has {n})"})
                return
            if self.path == "/admin/reset_breaker":
                if is_router:
                    sup.reset_breaker(replica)
                else:
                    sup.reset_breaker()
                self._json(200, {"status": "ok", "state": sup.state})
            elif self.path in ("/admin/drain_replica",
                               "/admin/restart_replica",
                               "/admin/undrain_replica"):
                if not is_router or replica is None:
                    self._json(400, {"error": "replica operations need "
                                              "--replicas N > 1 and a "
                                              "replica index"})
                    return
                if self.path == "/admin/drain_replica":
                    ok = sup.drain_replica(replica, timeout=timeout)
                    self._json(200, {"status": "drained" if ok
                                     else "drain_timeout",
                                     "replica": replica})
                elif self.path == "/admin/restart_replica":
                    sup.restart_replica(replica, timeout=timeout)
                    self._json(200, {"status": "restarted",
                                     "replica": replica})
                else:
                    sup.undrain_replica(replica)
                    self._json(200, {"status": "ok", "replica": replica})
            else:
                self._json(404, {"error": "not found"})

        def _admin_profile(self) -> None:
            """POST /admin/profile?ms=N — write one jax.profiler trace of
            the next N milliseconds (docs/observability.md "Device
            tier"). Synchronous: the 200 means the trace is on disk
            (the threaded accept loop keeps serving meanwhile). On the
            process tier the verb relays as RMSG_PROFILE into every
            replica worker — each captures into its own per-worker dir,
            concurrently, and the response lists them; otherwise the
            capture runs in THIS process (legacy, supervisor, and
            thread-router tiers all share one jax runtime). 409 when a
            capture is already running (jax.profiler is process-global).
            Admin-guarded like every /admin/* verb — a trace names every
            op and shape on the box."""
            import os
            import tempfile
            from urllib.parse import parse_qs, urlparse

            try:
                q = parse_qs(urlparse(self.path).query)
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                ms = (float(q["ms"][0]) if "ms" in q
                      else float(body.get("ms", 100.0)))
                if not 0.0 < ms <= 60_000.0:  # also rejects NaN
                    raise ValueError(ms)
            except (ValueError, TypeError, json.JSONDecodeError):
                self._json(400, {"error": "bad request: ms must be in "
                                          "(0, 60000]"})
                return
            sup = state._scheduler
            if sup is None and (state.replica_procs
                                or state.replica_hosts):
                # process tier, front door unbuilt: the device work
                # lives in workers that don't exist yet — answer idle
                # like the other admin verbs, never a 200 over a
                # parent-only (deviceless) capture
                self._json(200, {"status": "idle"})
                return
            if sup is not None and hasattr(sup, "profile"):
                workers = sup.profile(ms)  # Router: RMSG_PROFILE relay
                if workers is not None:    # None = no remote replicas
                    self._json(200, {"status": "ok", "ms": ms,
                                     "workers": workers})
                    return
            from ..runtime.profiler import PROFILER

            base = state.profile_dir or tempfile.mkdtemp(prefix="dlprof-")
            target = os.path.join(base,
                                  f"profile-{int(time.time() * 1e3):x}")
            try:
                out = PROFILER.capture(target, ms)
            except RuntimeError as e:  # a capture is already running
                self._json(409, {"error": str(e)}, retry_after=ms / 1e3)
                return
            self._json(200, {"status": "ok", **out})

        def _batch_post(self, body: dict) -> None:
            """POST /v1/batch/completions — up to serve_batch prompts in one
            batched decode. Response mirrors the chat shape with one choice
            per row (index = row); SSE chunks tag their row via `index`."""
            if state.serve_batch <= 0:
                self._json(404, {
                    "error": "batch endpoint off (start with --serve-batch N)"})
                return
            rid = f"batchcmpl-{int(time.time()*1000):x}"
            created = int(time.time())
            stream = bool(body.get("stream", False))
            gen = _batch_completion_chunks(state, body)
            try:
                first = next(gen)
            except (PromptTooLong, BadRequest) as e:
                self._json(400, {"error": str(e)})
                return
            except EngineUnready as e:
                # the exclusive borrow is refused while recovering/draining
                self._json(503, {"error": str(e), "state": e.state},
                           retry_after=e.retry_after)
                return
            except Exception as e:  # noqa: BLE001 — a crash inside the
                # borrow already triggered supervisor recovery (resilience
                # .exclusive); the client gets a retryable 503, not a
                # dropped connection
                self._json(503, {"error": f"engine failure: "
                                          f"{type(e).__name__}: {e}"},
                           retry_after=1.0)
                return

            def events():
                yield first
                yield from gen

            if stream:
                self._sse_start()
                usage = None
                try:
                    for kind, payload in events():
                        if kind == "piece":
                            i, piece = payload
                            self._sse(_chunk_env(rid, created,
                                                 state.model_name,
                                                 i, {"content": piece},
                                                 None))
                        elif kind == "keepalive":
                            # SSE comment frame: bytes on the wire while
                            # the collected lookup path runs, invisible
                            # to the client's event parser
                            self.wfile.write(b": keepalive\n\n")
                            self.wfile.flush()
                        else:
                            usage = payload
                except Exception as e:  # noqa: BLE001 — an engine crash
                    # AFTER the 200/SSE start (e.g. surfacing behind the
                    # keepalives): same mid-stream contract as the
                    # scheduler path — an explicit structured error event
                    # and a terminated stream, never a dropped connection
                    # (supervisor recovery already ran via exclusive())
                    self._sse({"error": f"engine failure: "
                                        f"{type(e).__name__}: {e}"})
                    self._sse_done()
                    return
                for i, fr in enumerate(usage["finish_reasons"]):
                    self._sse(_chunk_env(rid, created, state.model_name,
                                         i, {}, fr))
                self._sse_done()
                return

            texts: dict[int, str] = {}
            usage = None
            for kind, payload in events():
                if kind == "piece":
                    i, piece = payload
                    texts[i] = texts.get(i, "") + piece
                elif kind == "done":
                    usage = payload
            self._json(200, _completion_env(
                rid, created, state.model_name,
                [{"index": i,
                  "message": {"role": "assistant",
                              "content": texts.get(i, "")},
                  "finish_reason": fr}
                 for i, fr in enumerate(usage["finish_reasons"])],
                usage["prompt_tokens"], usage["completion_tokens"]))

        def _chat_post(self, body: dict, chat: bool = True) -> None:
            """/v1/chat/completions (chat=True) and /v1/completions. With
            the scheduler on (--serve-batch), the request enqueues onto the
            shared slot scheduler and streams as its slot produces tokens —
            concurrent clients batch-decode together. Otherwise the legacy
            single-engine path runs, serialized by state.engine_lock under
            the threaded accept loop."""
            rid = (f"{'chatcmpl' if chat else 'cmpl'}-"
                   f"{int(time.time() * 1000):x}")
            created = int(time.time())
            stream = bool(body.get("stream", False))
            # multi-tenant identity (runtime/fleet.py): the body's
            # `tenant` field wins, the X-Tenant header fills in — folded
            # into the body HERE so the multi-host replay and the
            # scheduler path read one source of truth
            if "tenant" not in body and self.headers.get("X-Tenant"):
                body["tenant"] = self.headers.get("X-Tenant")

            multihost = jax.process_count() > 1
            use_sched = state.serve_batch > 0 and not multihost
            # legacy single-engine path: serialize under the engine lock,
            # CONTEXT-MANAGED — the old bare acquire()/release() pair
            # could leave the lock held forever if anything raised
            # between the acquire and the try that released it, wedging
            # every later legacy request behind a dead handler thread
            lock = (contextlib.nullcontext() if use_sched
                    else state.engine_lock)
            with lock:
                if multihost:
                    # multi-host cluster: workers replay this exact request
                    # from the raw body (apps/dllama.py cmd_worker);
                    # broadcast before any engine work so their collectives
                    # line up with ours
                    from ..parallel import multihost as mh
                    mh.send_api(json.dumps(body).encode())

                # pull the first event before committing a 200 so prompt
                # errors can still return a clean 4xx (on the scheduler
                # path PromptTooLong surfaces from submit() — through the
                # queue, before any slot work)
                gen = (_sched_completion_chunks(state, body, chat=chat)
                       if use_sched else _completion_chunks(state, body))
                try:
                    first = next(gen)
                except PromptTooLong as e:
                    self._json(400, {"error": str(e)})
                    return
                except QueueFull as e:
                    # admission control: overload is a FAST 429, not an
                    # unboundedly growing queue
                    self._json(429, {"error": str(e)},
                               retry_after=e.retry_after)
                    return
                except ShedReject as e:
                    # the fleet brain's overload ladder turned the
                    # request away at the door: a structured 429 whose
                    # Retry-After derives from the LIVE drain rate
                    self._json(429, {"error": str(e), "shed": e.reason},
                               retry_after=e.retry_after)
                    return
                except EngineUnready as e:
                    self._json(503, {"error": str(e), "state": e.state},
                               retry_after=e.retry_after)
                    return

                def events():
                    yield first
                    yield from gen

                def drain():
                    # multi-host: workers replay the FULL request; if this
                    # handler aborts mid-stream (client disconnect), finish
                    # the engine steps anyway so cross-host collectives
                    # stay aligned
                    if multihost:
                        for _ in gen:
                            pass

                if chat:
                    def piece_env(p):
                        return _chunk_env(rid, created, state.model_name, 0,
                                          {"content": p}, None)

                    def final_env(fr):
                        return _chunk_env(rid, created, state.model_name, 0,
                                          {}, fr)
                else:
                    def piece_env(p):
                        return _text_chunk_env(rid, created,
                                               state.model_name, p, None)

                    def final_env(fr):
                        return _text_chunk_env(rid, created,
                                               state.model_name, "", fr)

                if stream:
                    self._sse_start()
                    usage = None
                    try:
                        for kind, payload in events():
                            if kind == "piece":
                                self._sse(piece_env(payload))
                            else:
                                usage = payload
                    finally:
                        drain()
                    if usage.get("error"):
                        # mid-stream failure: the client gets an EXPLICIT
                        # structured error event and a terminated stream
                        # (finish_reason "error"), never a silent hang
                        self._sse({"error": usage["error"]})
                    self._sse(final_env(usage["finish_reason"]))
                    self._sse_done()
                    return

                text = ""
                usage = {"finish_reason": "length", "prompt_tokens": 0,
                         "completion_tokens": 0}
                try:
                    for kind, payload in events():
                        if kind == "piece":
                            text += payload
                        else:
                            usage = payload
                finally:
                    drain()
                if usage.get("error") and not text:
                    # failed before any output: a clean retryable status
                    # beats a 200 carrying an empty completion
                    self._json(503, {"error": usage["error"]},
                               retry_after=1.0)
                    return
                if chat:
                    self._json(200, _completion_env(
                        rid, created, state.model_name,
                        [{"index": 0,
                          "message": {"role": "assistant", "content": text},
                          "finish_reason": usage["finish_reason"]}],
                        usage["prompt_tokens"], usage["completion_tokens"]))
                else:
                    self._json(200, _text_completion_env(
                        rid, created, state.model_name, text,
                        usage["finish_reason"], usage["prompt_tokens"],
                        usage["completion_tokens"]))

    return Handler


def serve(args) -> None:
    import os
    import signal
    import threading

    from .dllama import build_engine, check_session_flags

    session = getattr(args, "session", None)
    check_session_flags(args)
    serve_batch = getattr(args, "serve_batch", 0)
    if serve_batch:
        # the scheduler's batch engine is single-process by design (a
        # cluster needs request replay for b-row steps) and composes
        # with exactly ONE mesh axis: tp — the slot programs gate rows
        # by position, which is dp/sp/pp-agnostic only on paper, and tp
        # is what vocab sharding (ops/sharded_vocab.py) serves through.
        # Loud error beats a silently ignored flag for the rest.
        if getattr(args, "nnodes", 1) > 1 or jax.process_count() > 1:
            sys.exit("error: --serve-batch does not compose with --nnodes")
        if max(getattr(args, k, 1) for k in ("dp", "sp", "ep", "pp")) > 1:
            sys.exit("error: --serve-batch needs a single-process engine "
                     "(no --dp/--sp/--ep/--pp; --tp composes)")
        if getattr(args, "tp", 1) > 1 and (
                getattr(args, "replicas", 1) > 1
                or getattr(args, "replica_procs", 0)
                or getattr(args, "replica_hosts", None)):
            # one tp mesh = one engine's devices: replicas would contend
            # for the same chips (ROADMAP item 3's remaining work is
            # exactly workers spanning their own meshes)
            sys.exit("error: --serve-batch with --tp serves the "
                     "single-supervisor tier only (no --replicas/"
                     "--replica-procs/--replica-hosts)")
        if session:
            # scheduler slots are leased per request — there is no single
            # prefix cache a --session file could describe
            sys.exit("error: --serve-batch (continuous-batching scheduler) "
                     "does not compose with --session prefix persistence")
    # SLO-aware admission + auto-sizing flags (runtime/scheduler.
    # AdmissionPolicy / runtime/profiler.resolve_auto_shape): dead-flag
    # discipline like every knob family above — an SLO nobody enforces
    # or an artifact nobody reads must be a parse-time error
    slo_ttft = getattr(args, "slo_ttft_ms", None)
    slo_itl = getattr(args, "slo_itl_ms", None)
    if (slo_ttft is not None or slo_itl is not None) and not serve_batch:
        sys.exit("error: --slo-ttft-ms/--slo-itl-ms require "
                 "--serve-batch N|auto (the SLO-aware admission policy "
                 "adapts the scheduler's chunked-prefill width)")
    for name, v in (("--slo-ttft-ms", slo_ttft), ("--slo-itl-ms", slo_itl)):
        if v is not None and not v > 0:
            sys.exit(f"error: {name} must be > 0 "
                     "(omit the flag to disable)")
    prefix_blocks = getattr(args, "prefix_blocks", 0)
    auto_batch = serve_batch == "auto"
    auto_blocks = prefix_blocks == "auto"
    autotune_file = getattr(args, "autotune", None)
    if autotune_file and not (auto_batch or auto_blocks):
        sys.exit("error: --autotune has no effect without --serve-batch "
                 "auto or --prefix-blocks auto (tools/dlprof.py consumes "
                 "the artifact offline)")
    autotune_art = None
    if autotune_file:
        # a bad artifact must be a clear CLI error before any engine
        # work, never a wrong silent batch size
        from ..runtime.profiler import load_autotune
        try:
            autotune_art = load_autotune(autotune_file)
        except (OSError, ValueError) as e:
            sys.exit(f"error: --autotune {autotune_file}: {e}")
    if getattr(args, "prefix_cache", False) and not serve_batch:
        # the radix cache lives on the slot scheduler (the legacy path
        # keeps its own single-session prefix reuse) — loud error beats
        # a silently ignored flag
        sys.exit("error: --prefix-cache requires --serve-batch N "
                 "(the radix cache serves the slot scheduler; the legacy "
                 "path already reuses its single session's prefix)")
    if not getattr(args, "prefix_cache", False) and (
            auto_blocks or prefix_blocks > 0
            or getattr(args, "prefix_block_len", None) is not None):
        # same principle one flag over: sizing knobs without the cache
        # itself would be silently dead configuration (block-len uses a
        # None sentinel, so an EXPLICIT value — even the default 32 —
        # is caught, and changing the default cannot break this check)
        sys.exit("error: --prefix-blocks/--prefix-block-len have no "
                 "effect without --prefix-cache")
    replicas = getattr(args, "replicas", None)
    replicas = 1 if replicas is None else replicas
    if replicas < 1:
        # explicit `--replicas 0` must hit this, not coerce to 1
        sys.exit("error: --replicas must be >= 1")
    replica_procs = getattr(args, "replica_procs", 0) or 0
    replica_hosts_raw = getattr(args, "replica_hosts", None)
    if replica_procs < 0:
        sys.exit("error: --replica-procs must be >= 1")
    if replica_procs and replica_hosts_raw:
        sys.exit("error: --replica-procs (local spawn) and "
                 "--replica-hosts (connect to pre-started workers) are "
                 "mutually exclusive")
    process_tier = bool(replica_procs or replica_hosts_raw)
    if process_tier and replicas > 1:
        sys.exit("error: --replicas (thread tier) does not compose with "
                 "--replica-procs/--replica-hosts (process tier) — pick "
                 "one replication boundary")
    if process_tier and getattr(args, "nnodes", 1) > 1:
        sys.exit("error: --replica-procs/--replica-hosts do not compose "
                 "with --nnodes (each worker is its own single-host "
                 "engine; see ROADMAP item 2 for the composition)")
    if replica_hosts_raw and getattr(args, "draft", None):
        # same contract as the --slo-* refusal below: pre-started
        # workers own their configs — the parent cannot arm drafting in
        # them, and a silently plain-decoding fleet the operator
        # believes is speculating is the dead-flag hazard this
        # discipline exists for (review-found)
        sys.exit("error: --draft does not reach --replica-hosts workers "
                 "(their configs are their operators'): pass --draft in "
                 "each worker's own config instead")
    if replica_hosts_raw and (slo_ttft is not None or slo_itl is not None):
        # pre-started workers were launched with their OWN configs; the
        # parent cannot arm a policy in them (unlike --replica-procs,
        # whose spawned workers receive the SLOs via the shipped worker
        # config) — an SLO nobody enforces must be a parse-time error
        sys.exit("error: --slo-ttft-ms/--slo-itl-ms do not reach "
                 "--replica-hosts workers (their configs are their "
                 "operators'): set the SLOs in each worker's own config "
                 "instead")
    if (auto_batch or auto_blocks) and process_tier:
        # resolve_auto_shape needs a LOCAL engine's real array shapes;
        # the process tier's parent holds only a spec template — refuse
        # clearly at parse time instead of crashing mid-build
        sys.exit("error: --serve-batch/--prefix-blocks 'auto' need a "
                 "ledger-capable local engine; the process tier's "
                 "workers own their engines — pass explicit sizes "
                 "(calibrate with tools/autotune.py and use its "
                 "recommendation)")
    if not serve_batch and (
            replicas > 1 or process_tier
            or getattr(args, "retry_budget", None) is not None
            or getattr(args, "route_policy", None) is not None):
        # the router fronts N slot schedulers — without --serve-batch
        # these flags would be silently dead configuration (retry-budget
        # and route-policy use None sentinels so even an explicit
        # default value is caught)
        sys.exit("error: --replicas/--replica-procs/--replica-hosts/"
                 "--retry-budget/--route-policy require --serve-batch N "
                 "(the failover router fronts the continuous-batching "
                 "scheduler)")
    if replicas == 1 and not process_tier and (
            getattr(args, "retry_budget", None) is not None
            or getattr(args, "route_policy", None) is not None):
        sys.exit("error: --retry-budget/--route-policy have no effect "
                 "without --replicas N > 1 or a process tier")
    # KV block transfer + disaggregation (runtime/kv_transfer.py):
    # dead-flag discipline — a transfer plane with nothing to transfer
    # (no prefix cache) or nobody to transfer between (one replica) is
    # silently-dead configuration
    kv_transfer = bool(getattr(args, "kv_transfer", False))
    tier_raw = getattr(args, "tier", None)
    if kv_transfer and not getattr(args, "prefix_cache", False):
        sys.exit("error: --kv-transfer moves published prefix-cache "
                 "blocks and requires --prefix-cache")
    n_fleet = (int(replica_procs) if replica_procs
               else len(str(replica_hosts_raw).split(","))
               if replica_hosts_raw else int(replicas))
    if kv_transfer and n_fleet < 2:
        sys.exit("error: --kv-transfer needs >= 2 replicas "
                 "(--replicas N, --replica-procs N, or --replica-hosts "
                 "h:p,...) — one replica has no sibling to transfer "
                 "with")
    tiers = None
    if tier_raw is not None:
        if not kv_transfer:
            sys.exit("error: --tier requires --kv-transfer (a prefill-"
                     "tier replica is useless unless its blocks can "
                     "move to the decode tier)")
        if replica_hosts_raw:
            sys.exit("error: --tier does not reach --replica-hosts "
                     "workers (their configs are their operators'): "
                     "set `tier` in each worker's own config — the "
                     "router adopts it from the health PONG")
        n_rep = int(replica_procs) if replica_procs else int(replicas)
        tiers = [t.strip() for t in str(tier_raw).split(",")]
        if len(tiers) == 1:
            tiers = tiers * n_rep
        if len(tiers) != n_rep:
            sys.exit(f"error: --tier lists {len(tiers)} roles for "
                     f"{n_rep} replicas (one value, or one per replica)")
        bad = [t for t in tiers if t not in ("prefill", "decode",
                                             "mixed")]
        if bad:
            sys.exit(f"error: --tier roles must be prefill|decode|"
                     f"mixed (got {bad[0]!r})")
        if all(t == "prefill" for t in tiers):
            sys.exit("error: --tier needs at least one decode or mixed "
                     "replica (prefill-tier replicas never serve "
                     "requests)")
    # fleet brain (runtime/fleet.py): same dead-flag discipline — an
    # autoscaling window nothing can scale, or tenant budgets nothing
    # enqueues fairly, must refuse at parse time, not silently no-op
    min_reps = getattr(args, "min_replicas", 0) or 0
    max_reps = getattr(args, "max_replicas", 0) or 0
    if min_reps < 0 or max_reps < 0:
        sys.exit("error: --min-replicas/--max-replicas must be >= 1")
    if (min_reps or max_reps) and not serve_batch:
        sys.exit("error: --min-replicas/--max-replicas require "
                 "--serve-batch N (the fleet controller scales the "
                 "replica set behind the scheduler front door)")
    if min_reps and max_reps and min_reps > max_reps:
        sys.exit(f"error: --min-replicas {min_reps} exceeds "
                 f"--max-replicas {max_reps}")
    if max_reps and replica_hosts_raw:
        sys.exit("error: autoscaling does not reach --replica-hosts "
                 "workers (their lifetimes are their operators'): the "
                 "controller can only spawn/reap locally supervised "
                 "replicas (--replicas/--replica-procs)")
    if max_reps and max_reps > n_fleet and not (replicas > 1
                                                or replica_procs):
        sys.exit("error: --max-replicas needs a replica tier to grow "
                 "(--replicas N or --replica-procs N)")
    tenant_budgets_raw = getattr(args, "tenant_budgets", None)
    if tenant_budgets_raw is not None:
        if not serve_batch:
            sys.exit("error: --tenant-budgets requires --serve-batch N "
                     "(weighted-fair queueing replaces the scheduler's "
                     "FIFO admission queue)")
        if replica_hosts_raw:
            # same contract as --draft/--slo-*: pre-started workers own
            # their configs — fairness the parent cannot arm worker-side
            # would silently degrade to FIFO where the queueing happens
            sys.exit("error: --tenant-budgets does not reach "
                     "--replica-hosts workers (their configs are their "
                     "operators'): set tenant_budgets in each worker's "
                     "own config instead")
        from ..runtime.fleet import parse_tenant_budgets
        try:
            # parse NOW so a malformed spec refuses at startup, never
            # mid-traffic in a worker process
            parse_tenant_budgets(tenant_budgets_raw)
        except ValueError as e:
            sys.exit(f"error: --tenant-budgets: {e}")
    trace_on = bool(getattr(args, "trace", False))
    if not trace_on and (
            getattr(args, "trace_dir", None)
            or getattr(args, "trace_sample", None) is not None
            or getattr(args, "trace_buffer", None) is not None
            or getattr(args, "trace_decode_every", None) is not None):
        # dead-flag discipline, same as the prefix/router knobs: sizing
        # a recorder that is off is silently-dead configuration
        sys.exit("error: --trace-dir/--trace-sample/--trace-buffer/"
                 "--trace-decode-every have no effect without --trace")
    if trace_on:
        sample = getattr(args, "trace_sample", None)
        if sample is not None and not 0.0 <= sample <= 1.0:
            sys.exit("error: --trace-sample must be in [0, 1]")
        from ..runtime.trace import TRACER
        TRACER.configure(
            capacity=getattr(args, "trace_buffer", None) or 8192,
            sample=1.0 if sample is None else float(sample),
            decode_every=getattr(args, "trace_decode_every", None) or 8,
            sink_dir=getattr(args, "trace_dir", None))
    # device-tier observability (runtime/profiler.py): the recompile
    # sentinel's freeze and the sampled attribution both hang off the
    # slot scheduler (warmup arms the sentinel; the sampler hooks
    # scheduler steps) — without --serve-batch they are dead flags
    freeze_compiles = bool(getattr(args, "freeze_compiles", False))
    profile_sample = getattr(args, "profile_sample", None)
    if (freeze_compiles or profile_sample is not None) and not serve_batch:
        sys.exit("error: --freeze-compiles/--profile-sample require "
                 "--serve-batch N (the sentinel arms at scheduler "
                 "warmup; the sampler hooks scheduler steps)")
    if profile_sample is not None and profile_sample < 1:
        sys.exit("error: --profile-sample must be >= 1 (capture every "
                 "Nth step; omit the flag to disable)")
    if freeze_compiles or profile_sample:
        from ..runtime.profiler import COMPILES, PROFILER

        COMPILES.freeze = freeze_compiles
        # on the process tier the WORKERS sample (config_from_cli_args
        # ships both knobs); setting the parent too is harmless — it
        # steps no scheduler
        PROFILER.sample_every = int(profile_sample or 0)
    replica_hosts = None
    if replica_hosts_raw:
        replica_hosts = []
        for spec in str(replica_hosts_raw).split(","):
            host, _, port = spec.strip().rpartition(":")
            if not host or not port.isdigit():
                sys.exit(f"error: --replica-hosts entry {spec.strip()!r} "
                         "is not host:port")
            replica_hosts.append((host, int(port)))
    worker_config = None
    if replica_procs:
        if not getattr(args, "model", None):
            sys.exit("error: --replica-procs workers load their own "
                     "weights and need --model")
        from ..runtime.replica_worker import config_from_cli_args
        worker_config = config_from_cli_args(args, serve_batch)

    if process_tier:
        # the workers own the weights — the parent reads only the .m
        # spec header (shape validation) + tokenizer: no N+1-th weight
        # copy locally, and a pure --replica-hosts router box holds none
        from .dllama import build_front_template
        engine, tokenizer, sampler = build_front_template(args)
    else:
        engine, tokenizer, sampler = build_engine(args)
    draft_spec = getattr(args, "draft", None)
    if draft_spec:
        # depth bound needs the spec — validate at STARTUP, not on the
        # first request (runtime/draft.parse_draft_spec already vetted
        # the format at parse time)
        from ..runtime.draft import parse_draft_spec
        kind, arg = parse_draft_spec(draft_spec)
        if kind == "self" and not 1 <= int(arg) < engine.spec.n_layers:
            sys.exit(f"error: --draft self:{arg}: depth must be in "
                     f"1..{engine.spec.n_layers - 1} (the model has "
                     f"{engine.spec.n_layers} layers)")
        if kind == "model" and getattr(engine, "mesh", None) is not None:
            # DraftModel.from_file refuses meshed targets — fail at
            # STARTUP where the mesh is known, not mid-serve inside the
            # lazily-built supervisor (review-found; the legacy api
            # path is the only way to combine --draft with a mesh,
            # --serve-batch already refuses meshes)
            sys.exit("error: --draft model:PATH needs a mesh-less "
                     "engine (use --draft self:<depth>, which shares "
                     "the target's sharded buffers)")
        if worker_config is not None:
            # the verify argmax truncates at the TOKENIZER vocab; the
            # workers have no tokenizer, so the bound ships in the config
            worker_config["draft_vocab"] = tokenizer.vocab_size
    prefix_block_len = getattr(args, "prefix_block_len", None) or 32
    if getattr(args, "prefix_cache", False):
        # validate the arena config against the REAL engine context at
        # startup — the supervisor builds lazily on the first request,
        # and a bad block length must be a CLI error, not a 500 every
        # request (PrefixCache.__init__ would assert there)
        bl = prefix_block_len
        if not 1 <= bl <= engine.seq_len:
            sys.exit(f"error: --prefix-block-len {bl} outside 1.."
                     f"{engine.seq_len} (the engine context)")
        if not auto_blocks and prefix_blocks < 0:
            sys.exit("error: --prefix-blocks must be >= 0 "
                     "(0 = the 2xBxcontext default, or 'auto')")
    autosize = None
    if auto_batch or auto_blocks:
        # resolve the sentinels against the REAL engine's ledger, once,
        # before any scheduler exists: measured headroom capped by the
        # calibrated (or default-heuristic) knee. The decision record is
        # logged here and exported on /stats + /metrics so an operator
        # can always see what was chosen and why.
        from ..runtime.profiler import resolve_auto_shape
        try:
            autosize = resolve_auto_shape(
                engine, serve_batch=serve_batch,
                prefix_blocks=prefix_blocks,
                prefix_block_len=prefix_block_len, replicas=replicas,
                autotune=autotune_art, slo_itl_ms=slo_itl)
        except ValueError as e:
            sys.exit(f"error: {e}")
        serve_batch = autosize["serve_batch"]
        prefix_blocks = autosize["prefix_blocks"]
        inp = autosize["inputs"]
        print(f"⚖️  auto-sized: --serve-batch {serve_batch} "
              f"({autosize['serve_batch_basis']})"
              + (f", --prefix-blocks {prefix_blocks} "
                 f"({autosize['prefix_blocks_basis']})"
                 if auto_blocks else "")
              + f" — knee={inp['knee_rows']} [{inp['knee_basis']}], "
                f"headroom_bytes={inp['headroom_bytes']}, "
                f"slots_addable={inp['slots_addable']}")
    state = ApiState(engine, tokenizer, sampler,
                     lookup_decode=getattr(args, "lookup_decode", 0),
                     serve_batch=serve_batch,
                     serve_chunk=getattr(args, "serve_chunk", 0),
                     queue_depth=getattr(args, "queue_depth", 0),
                     request_deadline=getattr(args, "request_deadline", 0.0),
                     stall_timeout=getattr(args, "stall_timeout", 0.0),
                     prefix_cache=getattr(args, "prefix_cache", False),
                     prefix_blocks=prefix_blocks,
                     prefix_block_len=prefix_block_len,
                     slo_ttft_ms=slo_ttft, slo_itl_ms=slo_itl,
                     autosize=autosize,
                     draft=draft_spec,
                     draft_len=(getattr(args, "draft_len", None) or 7
                                if draft_spec else 0),
                     replicas=replicas,
                     retry_budget=(1 if getattr(args, "retry_budget", None)
                                   is None else args.retry_budget),
                     route_policy=(getattr(args, "route_policy", None)
                                   or "cache_aware"),
                     replica_procs=replica_procs,
                     replica_hosts=replica_hosts,
                     worker_config=worker_config,
                     admin_token=getattr(args, "admin_token", None),
                     profile_dir=getattr(args, "profile_dir", None),
                     kv_transfer=kv_transfer, tiers=tiers,
                     min_replicas=getattr(args, "min_replicas", 0) or 0,
                     max_replicas=getattr(args, "max_replicas", 0) or 0,
                     tenant_budgets=getattr(args, "tenant_budgets", None))
    if session and os.path.exists(session):
        load_server_session(state, session)
        print(f"💾 resumed session from {session} "
              f"({engine.pos} cached positions)")
    if jax.process_count() > 1:
        # multihost api root: a lost worker means every future forward
        # would hang in an orphaned collective. Map the detection onto the
        # supervisor's BROKEN path first (structured cluster_peer_lost
        # error frames to anything in flight, circuit open) — were a
        # cluster-capable scheduler ever live — flip /readyz to 503
        # cluster_lost, give handler threads a beat to flush those frames,
        # then take the standard diagnostic exit (43): an orchestrator
        # restart beats a zombie that 503s forever
        from ..parallel import multihost as mh

        def _on_peer_lost(exc):
            state.cluster_lost = exc
            sup = state._scheduler
            if sup is not None:
                sup.trip_cluster(exc)
            time.sleep(0.5)
            mh.diagnostic_exit(exc)

        mh.install_peer_lost_exit(_on_peer_lost)
        mh.set_phase("serve")
    # threaded accept loop (daemon handler threads): the scheduler path
    # serves concurrent clients from one batched decode; legacy paths
    # serialize on state.engine_lock / Scheduler.exclusive
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_handler(state))
    drain_timeout = getattr(args, "drain_timeout", 30.0)

    def _begin_drain(*_):
        # graceful drain (SIGTERM — docker stop, k8s rollout, systemd):
        # stop admitting (POSTs 503, /readyz unready), stop accepting,
        # let serve_forever return; the finally below finishes in-flight
        # work up to --drain-timeout, saves the session, and exits. The
        # default SIGTERM handler would exit WITHOUT unwinding the stack
        # — no drain, no save.
        state.draining = True
        threading.Thread(target=server.shutdown, daemon=True).start()

    def _hup(*_):
        # SIGHUP = the conventional "reload" signal: run the zero-failed-
        # requests rolling restart (drain + rebuild each replica in turn)
        # in a background thread — a signal handler must return fast, and
        # the restart takes seconds per replica. Router tiers only: a
        # single supervisor has no sibling to absorb traffic, so a
        # "rolling" restart of it would just be an outage.
        if not state.router_mode:
            print("🔁 SIGHUP ignored: rolling restart needs a replica "
                  "tier (--replicas/--replica-procs)")
            return
        print("🔁 SIGHUP: rolling restart started")

        def _run():
            # scheduler() builds lazily on first use — a SIGHUP that
            # arrives before any traffic must still restart, not no-op
            state.scheduler().rolling_restart()

        threading.Thread(target=_run, name="dllama-sighup-restart",
                         daemon=True).start()

    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, _begin_drain)
        if hasattr(signal, "SIGHUP"):
            signal.signal(signal.SIGHUP, _hup)
    print(f"🔌 dllama-api listening on {args.host}:{args.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        state.draining = True
        server.server_close()
        if state._fleet is not None:
            # stop the fleet brain BEFORE draining the door: a scale
            # decision landing mid-shutdown would race the close below
            state._fleet.close()
        if state._scheduler is not None:
            # finish in-flight/queued scheduler work before exiting; past
            # the deadline, close() fails stragglers with structured
            # shutdown frames (no waiter ever hangs on a dead process)
            if state._scheduler.drain(timeout=drain_timeout):
                print("🔌 drained: all in-flight requests completed")
            else:
                print(f"🔌 drain deadline ({drain_timeout:.0f}s) elapsed; "
                      "failing stragglers")
            state._scheduler.close()
        if session:
            if save_server_session(state, session):
                print(f"💾 saved session to {session} "
                      f"({engine.pos} cached positions)")
            else:
                print("💾 no completed session to save "
                      f"(leaving {session} untouched)")
