"""OpenAI-compatible HTTP API server.

TPU-native equivalent of the reference's dllama-api
(ref: src/apps/dllama-api/dllama-api.cpp):

  * POST /v1/chat/completions — completion + SSE streaming
    (ref: dllama-api.cpp:202-314)
  * GET /v1/models (ref: dllama-api.cpp:316-322)
  * Llama-3 header chat template (ref: dllama-api.cpp:173-181)
  * per-request temperature / seed / max_tokens / stop
    (ref: dllama-api.cpp:211-232), applied via Sampler setters
    (ref: src/tokenizer.cpp:358-364)
  * stop-sequence scan over the trailing pieces (ref: dllama-api.cpp:272-286)
  * prefix/session reuse (net-new — the reference resets the KV cache per
    request, ref: dllama-api.cpp:236-249): the longest common token prefix
    of the previous session stays cached and only the suffix re-prefills,
    which on TPU removes the dominant cost of a chat follow-up turn.
    Single-process only — multi-host clusters reset per request so a
    worker-side resync can never desync the processes' prefill shapes

Single-threaded accept loop like the reference (ref: dllama-api.cpp:341-352);
stdlib http.server, no external deps.
"""

from __future__ import annotations

import json
import sys
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import jax
import numpy as np

CHAT_EOS_MARKERS = ("<|eot_id|>", "<|end_of_text|>")


class PromptTooLong(ValueError):
    pass


def build_chat_prompt(messages: list[dict]) -> str:
    """Llama-3 header template (ref: dllama-api.cpp:173-181)."""
    out = []
    for m in messages:
        out.append(f"<|start_header_id|>{m.get('role', 'user')}<|end_header_id|>\n\n"
                   f"{m.get('content', '')}<|eot_id|>")
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


class ApiState:
    def __init__(self, engine, tokenizer, sampler, model_name: str = "dllama",
                 lookup_decode: int = 0, serve_batch: int = 0):
        self.engine = engine
        self.tokenizer = tokenizer
        self.sampler = sampler
        self.model_name = model_name
        # token history whose K/V writes are live in the engine cache
        # (prefix/session reuse — see _completion_chunks)
        self.cached_tokens: list[int] = []
        # greedy requests draft+verify up to this many tokens per forward
        # (prompt-lookup speculation, runtime/speculative.py); 0 = off
        self.lookup_decode = lookup_decode
        # POST /v1/batch/completions serves up to this many prompts per
        # request through one batched engine (0 = endpoint off). Decode is
        # weight-read-bound, so b rows amortize one weight read — the
        # single-chip serving-throughput lever (bench.py _batch_row).
        self.serve_batch = serve_batch
        self._batch_engine = None

    def batch_engine(self):
        """The batch=serve_batch engine, built on first use. It SHARES the
        single engine's param device buffers (weights are never duplicated;
        only the extra b-row KV cache is new memory) and mirrors its
        dtypes/seq_len. Single-device only — serve() refuses --serve-batch
        on meshes/clusters at startup."""
        if self._batch_engine is None:
            from ..runtime.engine import Engine

            e = self.engine
            self._batch_engine = Engine(
                e.spec, e.params, batch=self.serve_batch,
                max_seq_len=e.seq_len, compute_dtype=e.compute_dtype,
                cache_dtype=e.cache_dtype, use_pallas=e.use_pallas,
                pallas_interpret=e.pallas_interpret,
                activation_q80=e.activation_q80,
                prefill_chunk=e.prefill_chunk)
        return self._batch_engine


def _completion_chunks(state: ApiState, body: dict):
    """Generator of generated text pieces for one request."""
    engine, tokenizer, sampler = state.engine, state.tokenizer, state.sampler

    messages = body.get("messages", [])
    prompt = build_chat_prompt(messages)
    max_tokens = int(body.get("max_tokens", 0) or 0)
    stops = body.get("stop") or []
    if isinstance(stops, str):
        stops = [stops]

    tokens = tokenizer.encode(prompt)
    if len(tokens) >= engine.seq_len:
        raise PromptTooLong(
            f"prompt is {len(tokens)} tokens; context is {engine.seq_len}")

    # prefix/session reuse (net-new vs the reference's full per-request
    # reset, ref: dllama-api.cpp:236-249): chat turns share the system
    # prompt + history, and on TPU the re-prefill is the expensive part of
    # a turn. Keep the longest common token prefix of the previous
    # session's cache and prefill only the suffix — positions >= the kept
    # prefix hold stale K/V that this request overwrites position-by-
    # position before any of its queries can attend them (the same
    # invariant decode overruns rely on, runtime/engine.py).
    lcp = 0
    if jax.process_count() == 1:
        # multi-host clusters skip reuse: it is only collective-safe while
        # every process's cached_tokens agree, and a worker-local failure
        # resync (apps/dllama.cmd_worker) legitimately clears one side —
        # the next request must then prefill identically everywhere
        while (lcp < len(state.cached_tokens) and lcp < len(tokens) - 1
               and state.cached_tokens[lcp] == tokens[lcp]):
            lcp += 1
    if lcp > 0:
        engine.pos = lcp
    else:
        engine.reset()
    suffix = tokens[lcp:]
    state.cached_tokens = []  # repopulated on success below

    # per-request sampler params must not leak into later requests that omit
    # them — temperature AND the RNG stream position are restored in the
    # finally below (a request's "seed" must not permanently reseed the
    # shared sampler)
    saved_temp = sampler.temperature
    saved_rng_state = None
    if body.get("temperature") is not None:
        sampler.set_temp(float(body["temperature"]))
    if body.get("seed") is not None:
        saved_rng_state = sampler.rng_state
        sampler.set_seed(int(body["seed"]))

    limit = engine.seq_len - len(tokens) - 1
    n_gen = min(max_tokens, limit) if max_tokens > 0 else limit

    prev = tokens[-1]
    n_prompt = len(tokens)
    tail = ""  # bounded scan window for markers/stop sequences
    tail_len = max([len(m) for m in CHAT_EOS_MARKERS]
                   + [len(s) for s in stops] + [1]) + 16
    emitted = 0
    finish = "length"
    def plain_tokens():
        """Reference-parity sampled loop as a token iterator: yield, then
        step the token only if the consumer pulls again (so the last
        emitted token is never stepped — same as the host generate())."""
        logits = engine.prefill(suffix)
        for _ in range(n_gen):
            tok = sampler.sample(engine.fetch_logits(logits)[0])
            yield tok
            if engine.pos >= engine.seq_len:
                return
            logits = engine.step(np.asarray([[tok]], np.int32), engine.pos)
            history.append(tok)  # stepping tok wrote its K/V

    # requests can speculate: prompt-lookup drafts verified in one forward.
    # Greedy requests stream the EXACT greedy tokens (argmax verify); at
    # temperature > 0 the rejection-resampling mode keeps every emitted
    # token distributed exactly as a host-sampler draw, but on a DERIVED
    # numpy RNG — the token stream is not the plain path's xorshift stream
    # (acceptance consumes a data-dependent number of uniforms, so coin
    # parity is impossible by construction — runtime/speculative.py). Safe
    # on multi-host clusters: prefix reuse is off there, so every process
    # replays the identical request from token 0, mines identical drafts,
    # and (sampled mode) derives the identical seed from the replicated
    # sampler stream (Sampler.next_seed) — same verify widths, collectives
    # in lock-step (the --lookup-decode flag itself is in the cluster
    # config fingerprint)
    use_lookup = state.lookup_decode > 0
    history = list(tokens)  # every prompt position is written by prefill
    # history bookkeeping ownership: the lookup streams do NOT append their
    # emitted tokens (their K/V is already written by the verify forward, so
    # the consumer loop appends), while plain_tokens() appends as it steps.
    # `speculating` — not `use_lookup` — gates the consumer-side append, so a
    # request that falls through to the plain loop (e.g. a client-supplied
    # NEGATIVE temperature) keeps exactly one owner and the prefix cache
    # stays aligned with real K/V positions.
    speculating = False
    try:
        if use_lookup and sampler.temperature == 0.0:
            speculating = True
            token_iter = engine.generate_lookup_stream(
                suffix, n_gen, history=tokens,
                draft_len=state.lookup_decode,
                vocab_size=tokenizer.vocab_size)
        elif use_lookup and sampler.temperature > 0.0:
            speculating = True
            token_iter = engine.generate_lookup_sampled_stream(
                suffix, n_gen, history=tokens,
                temperature=sampler.temperature, topp=sampler.topp,
                seed=sampler.next_seed(),
                draft_len=state.lookup_decode,
                vocab_size=tokenizer.vocab_size)
        else:
            token_iter = plain_tokens()
        for tok in token_iter:
            if tok == tokenizer.eos_id:
                finish = "stop"
                break
            piece = tokenizer.decode_piece(prev, tok).decode("utf-8", errors="replace")
            prev = tok
            tail = (tail + piece)[-tail_len:]
            if any(m in tail for m in CHAT_EOS_MARKERS):
                finish = "stop"
                break
            # stop-sequence scan over the trailing window (ref: dllama-api.cpp:272-286)
            if stops and any(s in tail for s in stops):
                finish = "stop"
                break
            emitted += 1
            if speculating:
                history.append(tok)  # its K/V position is already written
            yield ("piece", piece)
        state.cached_tokens = history[: engine.pos]
    finally:
        sampler.set_temp(saved_temp)
        if saved_rng_state is not None:
            sampler.rng_state = saved_rng_state
    yield ("done", {"finish_reason": finish,
                    "prompt_tokens": n_prompt,
                    "completion_tokens": emitted})


def _batch_completion_chunks(state: ApiState, body: dict):
    """POST /v1/batch/completions generator: up to serve_batch prompts
    decoded in ONE batched engine (net-new vs the reference's batch=1
    server — decode is weight-read-bound, so b rows amortize one weight
    read; bench.py's _batch_row measures the aggregate-throughput win).

    Yields ("piece", (row, piece)) events then one ("done", {...}) with
    per-row finish/usage. Per-request temperature/seed apply to the whole
    batch through the shared reference-parity sampler stream (coins drawn
    in row order — Sampler.sample_batch); rows are independent sequences.
    No prefix reuse here: the batch cache is reset per request (the
    single-request endpoint keeps that feature)."""
    engine = state.batch_engine()
    tokenizer, sampler = state.tokenizer, state.sampler

    if "prompts" in body:
        texts = body["prompts"]
        raw = True
    else:
        texts = [build_chat_prompt(m) for m in body.get("messages_list", [])]
        raw = False
    b = len(texts)
    if not (1 <= b <= state.serve_batch):
        raise PromptTooLong(
            f"batch size {b} outside 1..{state.serve_batch} "
            "(server started with --serve-batch "
            f"{state.serve_batch})")
    max_tokens = int(body.get("max_tokens", 64))
    stops = body.get("stop") or []
    if isinstance(stops, str):
        stops = [stops]

    rows = [tokenizer.encode(t) for t in texts]  # add_bos default, like the single path
    limit = engine.seq_len - 1
    for i, r in enumerate(rows):
        if len(r) >= limit:
            raise PromptTooLong(
                f"prompt {i}: {len(r)} tokens >= context {limit}")
    # budget: MAX over rows of the per-row cache headroom (rows share the
    # step loop; a longer-prompt row hitting seq_len retires only itself —
    # the engine's per-row pos guard — so one long prompt must not cap the
    # shorter rows' output). max_tokens <= 0 means "generate to the context
    # limit", mirroring the single-request endpoint's semantics.
    headroom = max(limit - len(r) for r in rows)
    n_gen = min(max_tokens, headroom) if max_tokens > 0 else headroom
    n_prompt_toks = sum(len(r) for r in rows)  # before padding rows join

    saved_temp = sampler.temperature
    saved_rng_state = None
    if body.get("temperature") is not None:
        sampler.set_temp(float(body["temperature"]))
    if body.get("seed") is not None:
        saved_rng_state = sampler.rng_state
        sampler.set_seed(int(body["seed"]))

    markers = () if raw else CHAT_EOS_MARKERS
    tail_len = max([len(m) for m in markers]
                   + [len(s) for s in stops] + [1]) + 16
    prev = [r[-1] for r in rows]
    tails = [""] * b
    emitted = [0] * b
    finish = ["length"] * b
    # the engine's batch is a build-time shape: pad sub-batch requests with
    # pre-retired rows (flagged before the first step, so they never sample
    # — no coins leave the shared stream — and never emit)
    n_pad = engine.batch - b
    rows = rows + [[rows[0][0]]] * n_pad
    stop_flags = np.zeros(engine.batch, bool)
    stop_flags[b:] = True
    engine.reset()

    def scan_token(i: int, tok: int) -> str | None:
        """Shared per-token body of both batch paths: eos / marker /
        stop-sequence semantics live exactly once. Returns the decoded
        piece to emit, or None when row i just STOPPED (finish[i] set;
        the caller applies its own retirement mechanics)."""
        if tok == tokenizer.eos_id:
            finish[i] = "stop"
            return None
        piece = tokenizer.decode_piece(prev[i], tok).decode(
            "utf-8", errors="replace")
        prev[i] = tok
        tails[i] = (tails[i] + piece)[-tail_len:]
        if (any(m in tails[i] for m in markers)
                or (stops and any(s in tails[i] for s in stops))):
            finish[i] = "stop"
            return None
        emitted[i] += 1
        return piece

    try:
        if state.lookup_decode > 0 and sampler.temperature == 0.0:
            # greedy batch requests SPECULATE (Engine.generate_batch_lookup
            # — per-row drafts, one verify forward per step, exact per-row
            # greedy parity; bench measured 368-407 aggregate tok/s vs 355
            # plain-batch). Collected, not streamed: text-level stop
            # sequences trim each row post-hoc — a stopped row may have
            # burned some extra forwards, which multi-token accepts more
            # than repay; the batch cache resets per request, so the
            # overrun positions leak nothing
            outs = engine.generate_batch_lookup(
                rows, n_gen, eos_id=tokenizer.eos_id,
                draft_len=state.lookup_decode,
                vocab_size=tokenizer.vocab_size, stop_flags=stop_flags)
            for i in range(b):
                for tok in outs[i]:
                    piece = scan_token(i, tok)
                    if piece is None:
                        break
                    yield ("piece", (i, piece))
        else:
            for step in engine.generate_batch_stream(
                    rows, n_gen, sampler, stop_flags=stop_flags):
                for i, tok in enumerate(step):
                    if tok is None or stop_flags[i]:
                        continue
                    piece = scan_token(i, tok)
                    if piece is None:
                        stop_flags[i] = True
                        continue
                    yield ("piece", (i, piece))
    finally:
        sampler.set_temp(saved_temp)
        if saved_rng_state is not None:
            sampler.rng_state = saved_rng_state
        engine.reset()  # the batch cache holds nothing reusable
    yield ("done", {
        "finish_reasons": finish,
        "prompt_tokens": n_prompt_toks,
        "completion_tokens": sum(emitted),
    })


def load_server_session(state: ApiState, path: str) -> None:
    """Restore a previous server process's prefix cache + token history
    (Engine.load_session — refuses a mismatched model via the content
    fingerprint). A follow-up request whose prompt extends the saved
    conversation then re-prefills only its suffix, and the response is
    byte-identical to the no-restart path (net-new — the reference resets
    all state per request AND per process, ref: dllama-api.cpp:236-249)."""
    tokens = state.engine.load_session(path)
    # the cache holds K/V for exactly engine.pos positions; tokens beyond
    # that (a chat's final unstepped token) must not count as cached
    state.cached_tokens = tokens[: state.engine.pos]


def save_server_session(state: ApiState, path: str) -> bool:
    """Persist the live prefix cache + its token history
    (Engine.save_session). Called on server shutdown — the cache fetch is
    O(pos * layers * kv_dim) host bytes, too heavy per-request for big
    models but free at exit.

    A shutdown landing mid-request (client disconnect, signal) leaves
    cached_tokens empty while engine.pos is large — saving then would
    clobber a previously good file with an unusable one, so the save is
    SKIPPED (False) and any prior file stays; it is self-consistent (its
    cache bytes came from the file's own tokens) even though the live
    engine moved past it. The cache is also never saved beyond the token
    history that describes it."""
    if not state.cached_tokens:
        return False
    eng = state.engine
    eng.pos = min(eng.pos, len(state.cached_tokens))
    eng.save_session(path, tokens=state.cached_tokens)  # atomic (tmp+rename)
    return True


def _chunk_env(rid: str, created: int, model: str, index: int,
               delta: dict, finish_reason) -> dict:
    """One SSE chat.completion.chunk envelope (shared by the single- and
    batch-request streams; only the choice index differs between them)."""
    return {"id": rid, "object": "chat.completion.chunk", "created": created,
            "model": model,
            "choices": [{"index": index, "delta": delta,
                         "finish_reason": finish_reason}]}


def _completion_env(rid: str, created: int, model: str, choices: list,
                    prompt_tokens: int, completion_tokens: int) -> dict:
    """The non-streamed chat.completion envelope + usage
    (ref: types.hpp:10-91)."""
    return {"id": rid, "object": "chat.completion", "created": created,
            "model": model, "choices": choices,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": completion_tokens,
                      "total_tokens": prompt_tokens + completion_tokens}}


def make_handler(state: ApiState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *fargs):  # quiet
            pass

        def _json(self, code: int, obj: dict) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        # SSE chunked streaming (ref: dllama-api.cpp:125-145,183-200)
        def _sse_start(self) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()

        def _sse(self, obj: dict) -> None:
            self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
            self.wfile.flush()

        def _sse_done(self) -> None:
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()

        def do_GET(self):
            if self.path == "/v1/models":
                # ref: dllama-api.cpp:316-322
                self._json(200, {"object": "list", "data": [
                    {"id": state.model_name, "object": "model",
                     "created": int(time.time()), "owned_by": "user"}]})
            elif self.path in ("/", "/health"):
                self._json(200, {"status": "ok"})
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path not in ("/v1/chat/completions",
                                 "/v1/batch/completions"):
                self._json(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError):
                self._json(400, {"error": "bad request"})
                return
            if self.path == "/v1/batch/completions":
                self._batch_post(body)
            else:
                self._chat_post(body)

        def _batch_post(self, body: dict) -> None:
            """POST /v1/batch/completions — up to serve_batch prompts in one
            batched decode. Response mirrors the chat shape with one choice
            per row (index = row); SSE chunks tag their row via `index`."""
            if state.serve_batch <= 0:
                self._json(404, {
                    "error": "batch endpoint off (start with --serve-batch N)"})
                return
            rid = f"batchcmpl-{int(time.time()*1000):x}"
            created = int(time.time())
            stream = bool(body.get("stream", False))
            gen = _batch_completion_chunks(state, body)
            try:
                first = next(gen)
            except PromptTooLong as e:
                self._json(400, {"error": str(e)})
                return

            def events():
                yield first
                yield from gen

            if stream:
                self._sse_start()
                usage = None
                for kind, payload in events():
                    if kind == "piece":
                        i, piece = payload
                        self._sse(_chunk_env(rid, created, state.model_name,
                                             i, {"content": piece}, None))
                    else:
                        usage = payload
                for i, fr in enumerate(usage["finish_reasons"]):
                    self._sse(_chunk_env(rid, created, state.model_name,
                                         i, {}, fr))
                self._sse_done()
                return

            texts: dict[int, str] = {}
            usage = None
            for kind, payload in events():
                if kind == "piece":
                    i, piece = payload
                    texts[i] = texts.get(i, "") + piece
                else:
                    usage = payload
            self._json(200, _completion_env(
                rid, created, state.model_name,
                [{"index": i,
                  "message": {"role": "assistant",
                              "content": texts.get(i, "")},
                  "finish_reason": fr}
                 for i, fr in enumerate(usage["finish_reasons"])],
                usage["prompt_tokens"], usage["completion_tokens"]))

        def _chat_post(self, body: dict) -> None:
            rid = f"chatcmpl-{int(time.time()*1000):x}"
            created = int(time.time())
            stream = bool(body.get("stream", False))

            multihost = jax.process_count() > 1
            if multihost:
                # multi-host cluster: workers replay this exact request from
                # the raw body (apps/dllama.py cmd_worker); broadcast before
                # any engine work so their collectives line up with ours
                from ..parallel import multihost as mh
                mh.send_api(json.dumps(body).encode())

            # pull the first event before committing a 200 so prompt errors
            # can still return a clean 4xx
            gen = _completion_chunks(state, body)
            try:
                first = next(gen)
            except PromptTooLong as e:
                self._json(400, {"error": str(e)})
                return

            def events():
                yield first
                yield from gen

            def drain():
                # multi-host: workers replay the FULL request; if this
                # handler aborts mid-stream (client disconnect), finish the
                # engine steps anyway so cross-host collectives stay aligned
                if multihost:
                    for _ in gen:
                        pass

            if stream:
                self._sse_start()
                usage = None
                try:
                    for kind, payload in events():
                        if kind == "piece":
                            self._sse(_chunk_env(
                                rid, created, state.model_name, 0,
                                {"content": payload}, None))
                        else:
                            usage = payload
                finally:
                    drain()
                self._sse(_chunk_env(rid, created, state.model_name, 0, {},
                                     usage["finish_reason"]))
                self._sse_done()
                return

            text = ""
            usage = {"finish_reason": "length", "prompt_tokens": 0, "completion_tokens": 0}
            try:
                for kind, payload in events():
                    if kind == "piece":
                        text += payload
                    else:
                        usage = payload
            finally:
                drain()
            self._json(200, _completion_env(
                rid, created, state.model_name,
                [{"index": 0,
                  "message": {"role": "assistant", "content": text},
                  "finish_reason": usage["finish_reason"]}],
                usage["prompt_tokens"], usage["completion_tokens"]))

    return Handler


def serve(args) -> None:
    import os
    import signal
    import threading

    from .dllama import build_engine, check_session_flags

    session = getattr(args, "session", None)
    check_session_flags(args)
    if session and threading.current_thread() is threading.main_thread():
        # non-interactive shutdown (docker stop, systemd) sends SIGTERM,
        # whose default handler exits WITHOUT unwinding the stack — the
        # finally below would never save. Convert it to SystemExit so the
        # save runs for service deployments too.
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    serve_batch = getattr(args, "serve_batch", 0)
    if serve_batch:
        # the batch engine is single-process/single-device by design: a
        # mesh needs sharded-batch plumbing and a cluster needs request
        # replay for b-row steps — loud error beats a silently ignored flag
        if getattr(args, "nnodes", 1) > 1 or jax.process_count() > 1:
            sys.exit("error: --serve-batch does not compose with --nnodes")
        if max(getattr(args, k, 1) for k in ("tp", "dp", "sp", "ep", "pp")) > 1:
            sys.exit("error: --serve-batch needs a single-device engine "
                     "(no --tp/--dp/--sp/--ep/--pp)")

    engine, tokenizer, sampler = build_engine(args)
    state = ApiState(engine, tokenizer, sampler,
                     lookup_decode=getattr(args, "lookup_decode", 0),
                     serve_batch=serve_batch)
    if session and os.path.exists(session):
        load_server_session(state, session)
        print(f"💾 resumed session from {session} "
              f"({engine.pos} cached positions)")
    server = HTTPServer((args.host, args.port), make_handler(state))
    print(f"🔌 dllama-api listening on {args.host}:{args.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        if session:
            if save_server_session(state, session):
                print(f"💾 saved session to {session} "
                      f"({engine.pos} cached positions)")
            else:
                print("💾 no completed session to save "
                      f"(leaving {session} untouched)")
