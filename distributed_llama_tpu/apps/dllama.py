"""dllama CLI — inference / generate / chat modes.

TPU-native equivalent of the reference CLI (ref: src/apps/dllama/dllama.cpp):

  inference  prompt completion with a per-token benchmark line and end-of-run
             averages (ref: dllama.cpp:43-91)
  generate   plain streaming completion (ref: dllama.cpp:96-131)
  chat       interactive chat with the Llama-2 [INST]/<<SYS>> template
             (ref: dllama.cpp:133-178)
  api        OpenAI-compatible HTTP server (ref: src/apps/dllama-api)
  worker     join a multi-host cluster as a non-root process
             (ref: dllama.cpp:180-193). Single-host multi-device needs no
             workers — use --tp N. Across hosts, start workers with
             `dllama worker --nnodes N --node-rank r --coordinator h:p`
             and the root with the same --nnodes/--coordinator plus any
             mode; the mesh then spans every host's devices and workers
             follow the broadcast protocol (parallel/multihost.py)

Flag surface mirrors AppArgs::parse (ref: src/app.cpp:19-93) plus TPU mesh
flags. --weights-float-type / --buffer-float-type keep the reference
semantics: the former must match the model file, the latter selects the Q80
activation round-trip.
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import numpy as np


def _int_or_auto(v: str):
    """argparse type for --serve-batch/--prefix-blocks: a plain int, or
    the literal 'auto' — resolved at engine build from HBM-ledger
    headroom capped by the calibrated batch knee (runtime/profiler.
    resolve_auto_shape; docs/serving.md "Auto-sizing")."""
    s = v.strip().lower()
    if s == "auto":
        return "auto"
    try:
        return int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer or 'auto', got {v!r}")


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dllama",
        description="TPU-native distributed-llama: run Llama/Mixtral/Grok-1 "
                    "inference from reference-format .m/.t files.")
    p.add_argument("mode", choices=["inference", "generate", "chat", "api", "worker"])
    p.add_argument("--model", help="path to .m model file")
    p.add_argument("--tokenizer", help="path to .t tokenizer file")
    p.add_argument("--prompt", default=None)
    p.add_argument("--steps", type=int, default=0,
                   help="max tokens to generate (0 = until seq_len, ref app.cpp:117-119)")
    p.add_argument("--temperature", type=float, default=0.8)  # ref: app.cpp:31
    p.add_argument("--topp", type=float, default=0.9)         # ref: app.cpp:32
    p.add_argument("--seed", type=int, default=None,
                   help="sampler seed (default: time, ref app.cpp:88-91)")
    p.add_argument("--weights-float-type", default=None,
                   choices=["f32", "f16", "q40", "q80"],
                   help="must match the model file (ref: app.cpp:47-48)")
    p.add_argument("--buffer-float-type", default="q80", choices=["f32", "q80"],
                   help="activation exchange dtype (q80 reproduces the "
                        "reference's quantized wire buffers, ref: app.cpp:49-50). "
                        "NOT honored with --pp > 1: pipeline stages reduce "
                        "with GSPMD-exact collectives (the quantized "
                        "exchange cannot nest inside the manual-pp region), "
                        "so q80 is ignored there and f32 exact collectives "
                        "run instead")
    p.add_argument("--nthreads", type=int, default=None,
                   help="accepted for reference CLI parity; XLA manages "
                        "device parallelism (ref: app.cpp:84)")
    p.add_argument("--workers", nargs="*", default=None,
                   help="n/a on TPU; use --tp (ref: app.cpp:51-74)")
    p.add_argument("--port", type=int, default=9990)
    p.add_argument("--host", default="0.0.0.0")
    # TPU-native flags
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel mesh size")
    p.add_argument("--dp", type=int, default=1, help="data-parallel mesh size")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel mesh size (ring-attention prefill)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel mesh size (MoE models: each device "
                        "holds n_experts/ep experts)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel mesh size (each device holds "
                        "n_layers/pp layers and their KV cache). Contract "
                        "exclusions: --session is refused (stage-stacked "
                        "pp caches are not host-fetchable) and "
                        "--buffer-float-type q80 is ignored in favor of "
                        "exact f32 collectives")
    p.add_argument("--shard-vocab", default="auto",
                   choices=["auto", "on", "off"],
                   help="row-split the embedding table and logits head "
                        "over the vocab dim (ops/sharded_vocab.py): the "
                        "replicated 533 MB/chip table at 70B widths "
                        "becomes vocab/tp per chip, and serving never "
                        "materializes full logits (sharded argmax + "
                        "candidate top-k/top-p, greedy bit-identical, "
                        "sampled distribution-exact). auto = on whenever "
                        "the mesh's tp axes divide the vocab; off keeps "
                        "the replicated parity oracle")
    p.add_argument("--max-seq-len", type=int, default=None)
    p.add_argument("--compute-dtype", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--cache-dtype", default="bf16",
                   choices=["bf16", "f32", "f8"],
                   help="KV-cache element type; f8 (e4m3) halves cache "
                        "memory — 2x context per device (net-new vs the "
                        "reference's f32-only cache) — at decode-rate "
                        "PARITY with bf16: the flash kernel upcasts f8 "
                        "blocks via in-register bit reassembly "
                        "(ops/pallas_attention._f8_bits_to; measured 7B "
                        "decode at 7680-deep fill 18.9 vs 18.8 ms/token, "
                        "r5 A/B — r4's 2.3x astype stall is gone)")
    p.add_argument("--pallas", action="store_true", default=None,
                   help="force the fused Pallas kernels on (default: on for "
                        "TPU backends, including multi-device meshes via "
                        "shard_map; off on CPU where Mosaic can't compile)")
    p.add_argument("--no-pallas", dest="pallas", action="store_false",
                   help="force the XLA dequant path instead of the Pallas "
                        "kernels")
    p.add_argument("--system-prompt", default=None, help="chat mode system prompt")
    p.add_argument("--session", default=None, metavar="FILE",
                   help="chat/api modes: persist the KV-cache session to "
                        "FILE (chat: after every turn; api: on shutdown) "
                        "and resume from it on start — a conversation "
                        "survives process restarts without re-prefilling "
                        "its history (net-new: the reference has no "
                        "session persistence, SURVEY.md §5.4)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace of the generation to DIR "
                        "(view with tensorboard/xprof; net-new — the "
                        "reference has no profiler hooks, SURVEY.md §5.1)")
    p.add_argument("--device-sampling", action="store_true",
                   help="run the whole sampled decode loop on device (one "
                        "lax.while_loop that exits at eos; temperature/"
                        "top-p + reference-parity xorshift on the TPU — no "
                        "host round-trip per token). Composes with --dp: "
                        "batch row i gets its own device RNG stream seeded "
                        "seed+i (same prompt, distinct samples). "
                        "Output streams after the loop. Net-new: the "
                        "reference samples on CPU every token")
    p.add_argument("--lookup-decode", type=int, default=0, metavar="K",
                   help="speculative decoding: draft up to K tokens per "
                        "step from the context's own n-grams and verify "
                        "them in ONE forward (prompt lookup — decode is "
                        "weight-read-bound on TPU, so confirmed draft "
                        "tokens are nearly free). At --temperature 0 the "
                        "token stream is exactly the greedy stream; at "
                        "temperature > 0 tokens are accepted/resampled "
                        "rejection-style, distribution-exact vs the host "
                        "sampler (different RNG stream). Net-new: the "
                        "reference is strictly 1 token/forward")
    p.add_argument("--draft", default=None, metavar="self:D|model:PATH",
                   help="REAL-draft speculative decoding (runtime/draft"
                        ".py): 'self:D' runs the model's own first D "
                        "layers + logits head as a zero-extra-weights "
                        "draft (reuses the loaded buffers, keeps a small "
                        "D-layer KV cache); 'model:PATH' loads a "
                        "separate draft .m (same tokenizer) onto the "
                        "same machinery. Greedy output is BIT-IDENTICAL "
                        "to the plain stream (drafts only batch the "
                        "confirmation — and unlike --lookup-decode they "
                        "pay on ARBITRARY text, not just repetitive "
                        "text); temperature > 0 uses general rejection "
                        "resampling (min(1, p/q) accept against the "
                        "draft's real distribution), distribution-exact. "
                        "In api mode with --serve-batch, every slot "
                        "drafts per row through one fixed-width verify "
                        "forward, and the SLO admission policy degrades "
                        "to no-speculation when inter-token latency "
                        "endangers --slo-itl-ms. Mutually exclusive "
                        "with --lookup-decode")
    p.add_argument("--draft-len", type=int, default=None, metavar="K",
                   help="with --draft: tokens proposed per draft forward "
                        "(default 7). The verify width is 1 + K and is "
                        "compiled once; larger K amortizes more per "
                        "accept but wastes more draft work when the "
                        "draft diverges (watch dllama_spec_accept_rate, "
                        "docs/serving.md)")
    p.add_argument("--serve-batch", type=_int_or_auto, default=0,
                   metavar="B|auto",
                   help="api mode: run the continuous-batching scheduler "
                        "with B KV slots (runtime/scheduler.py, docs/"
                        "serving.md) — /v1/completions and /v1/chat/"
                        "completions join and leave the running decode "
                        "batch per step, and POST /v1/batch/completions "
                        "borrows the same engine. Decode is weight-read-"
                        "bound — B live slots amortize one weight read per "
                        "step for near-Bx aggregate tok/s; only the B-row "
                        "KV cache is new memory. 'auto' sizes B at startup "
                        "from HBM-ledger headroom capped by the batch knee "
                        "(--autotune artifact, or a conservative default) "
                        "— the decision is logged and exported on /stats "
                        "(docs/serving.md 'Auto-sizing'). Single-process "
                        "engines only; --tp composes (the vocab-sharded "
                        "serving path), other mesh axes and --nnodes are "
                        "refused. Net-new: the reference serves batch=1")
    p.add_argument("--serve-chunk", type=int, default=0, metavar="C",
                   help="api mode: prefill chunk width for the continuous-"
                        "batching scheduler (tail chunks pad to C, so C is "
                        "the ONLY prefill compilation key; 0 = the "
                        "engine's prefill chunk, capped to the context). "
                        "Smaller C bounds the inter-token stall admission "
                        "adds to running requests; larger C prefills new "
                        "prompts in fewer steps (docs/serving.md). With "
                        "--slo-ttft-ms/--slo-itl-ms this is the WIDEST "
                        "rung of the adaptive width ladder")
    # SLO-aware self-tuning admission (api mode, with --serve-batch;
    # runtime/scheduler.AdmissionPolicy, docs/serving.md "Auto-sizing and
    # SLO-aware admission"): either flag arms the policy
    p.add_argument("--slo-ttft-ms", type=float, default=None, metavar="MS",
                   help="api mode, with --serve-batch: time-to-first-token "
                        "target. The admission policy widens the chunked-"
                        "prefill width (toward --serve-chunk) when the "
                        "live TTFT EWMA endangers this bound and inter-"
                        "token latency has headroom — new prompts finish "
                        "prefilling in fewer iterations")
    p.add_argument("--slo-itl-ms", type=float, default=None, metavar="MS",
                   help="api mode, with --serve-batch: inter-token-latency "
                        "target. Every scheduler iteration with prefill "
                        "work stretches running streams' token gap by one "
                        "chunk forward; the admission policy shrinks the "
                        "chunk width one warmed rung at a time when the "
                        "live step-time EWMA approaches this bound, and "
                        "widens again when decode rows idle. Host-side "
                        "only: the width ladder is warmed up front, so "
                        "--freeze-compiles stays green while it adapts")
    p.add_argument("--autotune", default=None, metavar="FILE",
                   help="api mode, with --serve-batch auto or "
                        "--prefix-blocks auto: AUTOTUNE.json calibration "
                        "artifact (tools/autotune.py) supplying the "
                        "measured batch knee that caps the auto-sizing; "
                        "without it a conservative default knee applies. "
                        "tools/dlprof.py consumes the same artifact "
                        "offline to flag knee drift")
    # prefix-cache flags (api mode; runtime/prefix_cache.py,
    # docs/serving.md "Prefix caching")
    p.add_argument("--prefix-cache", action="store_true",
                   help="api mode, with --serve-batch: radix prefix cache "
                        "— cross-request KV reuse (runtime/prefix_cache"
                        ".py). Admissions seed the longest cached token "
                        "prefix (shared system prompts, few-shot "
                        "templates, chat history) from an on-device "
                        "block arena and prefill only the suffix; "
                        "finished prompts publish their blocks back. "
                        "GET /stats gains a prefix_cache hit-rate/"
                        "tokens-saved block. Net-new: the reference "
                        "recomputes every prompt from scratch")
    p.add_argument("--prefix-blocks", type=_int_or_auto, default=0,
                   metavar="N|auto",
                   help="prefix-cache arena size in blocks (0 = the "
                        "2 x serve-batch x context default; 'auto' = that "
                        "target capped by measured HBM headroom — the "
                        "arena never eats the slots' room; decision on "
                        "/stats like --serve-batch auto). Arena bytes = "
                        "N x 2 x layers x kv_heads x block_len x "
                        "head_size x cache dtype — budget it against the "
                        "B-row KV cache (docs/serving.md)")
    p.add_argument("--prefix-block-len", type=int, default=None,
                   metavar="L",
                   help="prefix-cache block granularity in tokens "
                        "(default 32): reuse is whole-blocks-only, so "
                        "smaller L matches more of a shared prefix but "
                        "spends more index/publish work per token "
                        "(docs/serving.md)")
    # serving-resilience flags (api mode; runtime/resilience.py,
    # docs/operations.md)
    p.add_argument("--queue-depth", type=int, default=0, metavar="N",
                   help="api mode: bound the scheduler admission queue at "
                        "N waiting requests — overload returns HTTP 429 + "
                        "Retry-After instead of queueing unboundedly "
                        "(0 = 4x --serve-batch)")
    p.add_argument("--request-deadline", type=float, default=0.0,
                   metavar="SECS",
                   help="api mode: per-request end-to-end budget; a "
                        "request past it (queued or mid-decode) fails "
                        "fast with a structured 'deadline' error frame "
                        "(0 = off)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   metavar="SECS",
                   help="api mode: watchdog bound on one scheduler step — "
                        "a step stalled longer (the TPU-tunnel hang "
                        "signature) marks the engine unhealthy and "
                        "triggers recovery (0 = default 10; must exceed "
                        "the worst-case step, compiles are warmed off "
                        "the clock)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   metavar="SECS",
                   help="api mode: graceful-drain budget on SIGTERM — "
                        "admissions stop immediately, in-flight requests "
                        "get this long to finish before being failed "
                        "with structured shutdown frames")
    # multi-replica serving-tier flags (api mode; runtime/router.py,
    # docs/operations.md "Multi-replica operations")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="api mode, with --serve-batch: run N supervised "
                        "engine replicas behind a cache-aware failover "
                        "router (runtime/router.py) — weights SHARED, "
                        "each replica its own KV cache + prefix arena. "
                        "A crashed/stalled/broken replica is invisible "
                        "to clients: not-yet-streamed requests retry on "
                        "a healthy sibling (token-identical for greedy), "
                        "/readyz stays ready while any replica serves, "
                        "and replicas drain/restart one at a time "
                        "(POST /admin/drain_replica) with zero failed "
                        "requests")
    p.add_argument("--retry-budget", type=int, default=None, metavar="K",
                   help="api mode, with --replicas: automatic failover "
                        "resubmits per request (default 1). Only "
                        "requests that have not streamed a token are "
                        "retried; mid-stream failures surface a "
                        "structured non-retryable error frame instead")
    p.add_argument("--route-policy", default=None,
                   choices=["cache_aware", "least_loaded", "round_robin"],
                   help="api mode, with --replicas: placement policy "
                        "(default cache_aware — route to the replica "
                        "whose radix tree caches the longest prompt "
                        "prefix, fall back to least-loaded; the SGLang "
                        "cache-aware routing idea). Session affinity "
                        "(body `session`/`user` field) applies under "
                        "every policy")
    # process-isolated replica flags (api mode; runtime/replica_worker.py,
    # docs/operations.md "Process-isolated replicas")
    p.add_argument("--replica-procs", type=int, default=0, metavar="N",
                   help="api mode, with --serve-batch: run N replicas as "
                        "supervised OS PROCESSES (each its own "
                        "interpreter + weights, served over the framed "
                        "replica protocol) instead of threads — the real "
                        "fault boundary: a segfault, OOM kill, or "
                        "SIGKILL costs ONE replica, the router fails "
                        "not-yet-streamed requests over to a sibling "
                        "(token-identical for greedy), and the process "
                        "supervisor respawns the dead worker under "
                        "backoff with exit-code classification. "
                        "Mutually exclusive with --replicas")
    p.add_argument("--replica-hosts", default=None, metavar="H:P,...",
                   help="api mode, with --serve-batch: comma-separated "
                        "host:port list of PRE-STARTED replica workers "
                        "(python -m distributed_llama_tpu.runtime."
                        "replica_worker on each host) — the cross-host "
                        "tier. No spawn supervision: each worker's "
                        "lifetime belongs to its host's operator. "
                        "Mutually exclusive with --replica-procs")
    # KV block transfer + prefill/decode disaggregation (runtime/
    # kv_transfer.py, docs/serving.md "KV block transfer")
    p.add_argument("--kv-transfer", action="store_true",
                   help="api mode, with --prefix-cache and a replica "
                        "tier: let replicas SHIP published KV blocks to "
                        "each other (RMSG_BLOCK_* over the framed "
                        "codec) — a replica placed cold on a prefix a "
                        "sibling caches FETCHES the blocks and seeds "
                        "them instead of re-prefilling (greedy outputs "
                        "bit-identical, transfer failures degrade to a "
                        "plain re-prefill). Also the carrier of --tier "
                        "disaggregation. Block frames ride the dlwire "
                        "ledger (dllama_kv_transfer_* on /metrics)")
    p.add_argument("--tier", default=None, metavar="T[,T...]",
                   help="api mode, with --kv-transfer: per-replica "
                        "disaggregation roles (prefill|decode|mixed; "
                        "one value applies to all, or a comma list "
                        "matching the replica count). prefill-tier "
                        "replicas run ONLY prompt prefills (big "
                        "chunks, no decode occupancy) and stream their "
                        "blocks to decode-tier replicas, which admit "
                        "already-seeded — the vLLM-lineage split that "
                        "kills prefill/decode interference. The router "
                        "falls back to the unified mixed path when no "
                        "prefill replica is routable. Not with "
                        "--replica-hosts (set `tier` in each worker's "
                        "own config; the router learns it from the "
                        "health PONG)")
    # fleet brain (runtime/fleet.py, docs/operations.md "Overload and
    # autoscaling"): load-adaptive replica autoscaling, SLO-aware
    # overload shedding, multi-tenant weighted fairness
    p.add_argument("--min-replicas", type=int, default=0, metavar="N",
                   help="api mode, with a replica tier: floor of the "
                        "fleet controller's autoscaling window (default: "
                        "the boot replica count — autoscaling off). The "
                        "controller drains + reaps sustained-idle "
                        "replicas down to this floor, folding their "
                        "lifetime counters into the router totals")
    p.add_argument("--max-replicas", type=int, default=0, metavar="N",
                   help="api mode, with a replica tier: ceiling of the "
                        "autoscaling window (default: the boot count — "
                        "autoscaling off). Under sustained queue growth "
                        "the controller spawns replicas up to N, hard-"
                        "capped by the HBM ledger's slots_addable "
                        "headroom; fresh replicas warm their caches "
                        "from siblings via --kv-transfer fills before "
                        "taking traffic")
    p.add_argument("--tenant-budgets", default=None,
                   metavar="NAME=W[:TPS],...",
                   help="api mode, with --serve-batch: per-tenant "
                        "weighted-fair queueing + token budgets. Each "
                        "entry names a tenant with fair-share weight W "
                        "and optional sustained tokens/sec budget (e.g. "
                        "'gold=4:2000,free=1:100'). Tenants come from "
                        "the request body `tenant` field or X-Tenant "
                        "header (unknown tenants get weight 1, no "
                        "budget); an over-budget tenant is served only "
                        "when no in-budget tenant waits, so a hog's "
                        "overage can never move a victim's p99")
    p.add_argument("--admin-token", default=None, metavar="TOKEN",
                   help="api mode: bearer token accepted on /admin/* as "
                        "an alternative to the loopback-only default "
                        "(constant-time compare) — required for "
                        "operating a remote-replica tier from off-box")
    # flight recorder (runtime/trace.py, docs/observability.md): request
    # spans + step timeline into a bounded ring, exported by
    # GET /metrics (Prometheus) and GET /admin/trace (JSONL)
    p.add_argument("--trace", action="store_true",
                   help="api mode: enable the flight recorder — per-"
                        "request lifecycle spans and the per-iteration "
                        "step timeline, in a fixed-capacity ring served "
                        "by /admin/trace and the dllama_step_ms /metrics "
                        "family. Host-side; disabled it is a no-op "
                        "(docs/observability.md quantifies the well-"
                        "under-2%% enabled overhead)")
    p.add_argument("--trace-buffer", type=int, default=None, metavar="N",
                   help="ring capacity in events (default 8192; oldest "
                        "events fall off first)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="also persist events as rotating JSONL files "
                        "under DIR (16 MB x 8 files per process; replica "
                        "workers write worker-rK/ subdirs)")
    p.add_argument("--trace-sample", type=float, default=None, metavar="R",
                   help="fraction of request SPANS persisted to "
                        "--trace-dir (deterministic per trace id; the "
                        "in-memory ring and /metrics always see "
                        "everything). Default 1.0")
    p.add_argument("--trace-decode-every", type=int, default=None,
                   metavar="N",
                   help="decode progress event cadence in tokens "
                        "(default 8) — bounds how much ring one long "
                        "stream can occupy")
    # device-tier observability (runtime/profiler.py,
    # docs/observability.md "Device tier"): compile ledger + recompile
    # sentinel, HBM ledger, on-demand capture, sampled attribution
    p.add_argument("--freeze-compiles", action="store_true",
                   help="api mode (needs --serve-batch): after warmup "
                        "compiles the serving set, any NEW compile key "
                        "is refused with a structured error instead of "
                        "compiled — the runtime twin of dlgrind's "
                        "static fingerprint gate. Covers everything "
                        "minted post-warmup, including the batch "
                        "endpoint's whole-batch executables (warm those "
                        "shapes first or leave the freeze off; "
                        "docs/operations.md 'Recompile storms')")
    p.add_argument("--profile-sample", type=int, default=None, metavar="N",
                   help="api mode (needs --serve-batch): capture every "
                        "Nth scheduler step under a short jax.profiler "
                        "trace and attribute device ms per entry point "
                        "(/stats device_time block, dllama_device_ms "
                        "/metrics). Off by default — disabled it costs "
                        "nothing, like --trace")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="where POST /admin/profile captures land "
                        "(default: a fresh temp dir per capture; replica "
                        "workers write worker-rK/ subdirs)")
    # multi-host cluster flags (the reference's root + worker nodes,
    # ref: src/app.cpp:51-74; here one jax.distributed SPMD cluster)
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of host processes in the cluster (rank 0 is "
                        "the root; others run `dllama worker`)")
    p.add_argument("--node-rank", type=int, default=0,
                   help="this process's rank (0..nnodes-1)")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator address host:port, "
                        "reachable from every node (required with --nnodes)")
    p.add_argument("--push-weights", action="store_true",
                   help="cluster weight distribution: rank 0 streams the "
                        ".m and broadcasts each tensor's bytes, so workers "
                        "need NO local model file (the reference root's "
                        "per-worker TCP weight push, transformer.cpp:562-"
                        "591). Pass on EVERY process; workers may omit "
                        "--model")
    # cluster control-plane resilience flags (parallel/multihost.py,
    # docs/operations.md "Cluster failure modes"). The root's
    # --heartbeat-interval / --worker-timeout are authoritative: workers
    # adopt them from the HELLO ack, so only the root's values matter
    p.add_argument("--connect-timeout", type=float, default=30.0,
                   metavar="SECS",
                   help="cluster formation budget: workers retry the "
                        "root's control port with exponential backoff "
                        "until this deadline, and the root waits this "
                        "long for every worker's versioned HELLO — past "
                        "it, a structured formation error (exit 44), "
                        "never a silent hang")
    p.add_argument("--heartbeat-interval", type=float, default=2.0,
                   metavar="SECS",
                   help="root->worker MSG_PING cadence on the control "
                        "channel (workers answer MSG_PONG; both sides "
                        "time out silent peers)")
    p.add_argument("--worker-timeout", type=float, default=10.0,
                   metavar="SECS",
                   help="peer-loss detection bound: a node silent on the "
                        "control channel this long (dead, wedged, or "
                        "partitioned) is declared lost with a structured "
                        "ClusterPeerLost diagnostic (exit 43) instead of "
                        "hanging a collective forever; must comfortably "
                        "exceed --heartbeat-interval")
    return p


def build_engine(args):
    """model file -> (engine, tokenizer, sampler). Mirrors App::run wiring
    (ref: src/app.cpp:103-132)."""
    import jax.numpy as jnp

    from ..io.model_file import content_fingerprint, read_spec
    from ..models.loader import load_params_streamed
    from ..quants.types import FloatType
    from ..runtime.engine import Engine
    from ..sampler import Sampler
    from ..tokenizer import Tokenizer

    multihost = jax.process_count() > 1
    push = getattr(args, "push_weights", False)
    # root-push mode: only rank 0 needs the .m — workers receive spec +
    # weights over the broadcast protocol (parallel/multihost.py)
    pushed_worker = push and multihost and jax.process_index() > 0
    if (not args.model and not pushed_worker) or not args.tokenizer:
        sys.exit("error: --model and --tokenizer are required "
                 "(--model optional for --push-weights workers)")

    wft = None
    if args.weights_float_type:
        wft = FloatType[args.weights_float_type.upper()]

    if multihost:
        # spec broadcast runs on EVERY multihost startup (push or not) so
        # the collective sequence is flag-independent — a --push-weights
        # mismatch then reaches check_config as a symmetric error instead
        # of deadlocking in mismatched collectives (bcast_spec docstring)
        from ..parallel.multihost import bcast_spec
        if jax.process_index() == 0:
            spec = read_spec(args.model, weights_float_type=wft)
            model_fp = content_fingerprint(args.model)
            bcast_spec(spec, model_fp, push=push)
        else:
            rspec, rfp, _ = bcast_spec(None)
            if pushed_worker:
                spec, model_fp = rspec, rfp
            else:
                spec = read_spec(args.model, weights_float_type=wft)
                model_fp = content_fingerprint(args.model)
    else:
        spec = read_spec(args.model, weights_float_type=wft)
        # sampled content hash of the weights file — folded into the
        # KV-session fingerprint always, and into the cluster config check
        # when multihost
        model_fp = content_fingerprint(args.model)
    print(f"⏩ {args.model or '<pushed>'}: arch={spec.arch.name} "
          f"dim={spec.dim} layers={spec.n_layers} "
          f"heads={spec.n_heads}/{spec.n_kv_heads} seq={spec.seq_len}")

    mode = "q40" if spec.weights_float_type == FloatType.Q40 else "dense"
    cdt = jnp.bfloat16 if args.compute_dtype == "bf16" else jnp.float32
    kdt = {"bf16": jnp.bfloat16, "f32": jnp.float32,
           "f8": jnp.float8_e4m3fn}[args.cache_dtype]
    if multihost:
        # every process must agree on the mesh/dtype flags (the reference
        # memcpys its spec struct over the socket and hopes — we verify).
        # The MODEL and TOKENIZER files are fingerprinted too: hosts loading
        # different .m/.t files would desync eos step counts and hang the
        # cluster in a mismatched collective instead of erroring (ADVICE r2).
        # The model hash samples file size + start/middle/end chunks, so
        # same-architecture different-weight builds (fine-tunes, requants)
        # are caught without reading a 40 GB file
        import dataclasses
        import zlib

        from ..parallel.multihost import check_config
        spec_fp = zlib.crc32(repr(dataclasses.astuple(spec)).encode())
        with open(args.tokenizer, "rb") as f:
            tok_fp = zlib.crc32(f.read())
        check_config([spec_fp, model_fp, tok_fp,
                      args.tp, args.dp, args.sp, args.ep, args.pp,
                      int(args.buffer_float_type == "q80"),
                      int(args.compute_dtype == "bf16"),
                      ["bf16", "f32", "f8"].index(args.cache_dtype),
                      # a seq-len or kernel-path mismatch would compile
                      # different step programs / loop bounds per process ->
                      # a cross-host collective hang, not an error
                      args.max_seq_len if args.max_seq_len is not None else -1,
                      2 if args.pallas is None else int(args.pallas),
                      # API-mode sampling uses each process's OWN sampler
                      # flags (MSG_RUN headers carry them, MSG_API doesn't)
                      # — a mismatch would silently diverge token streams
                      int(np.float32(args.temperature).view(np.int32)),
                      int(np.float32(args.topp).view(np.int32)),
                      # API-mode speculation likewise uses each process's
                      # own --lookup-decode: a mismatch would diverge the
                      # verify-forward widths and hang a collective
                      args.lookup_decode,
                      # weight-push changes the LOAD phase's broadcast
                      # sequence; reachable because bcast_spec above runs
                      # flag-independently
                      int(push)])

    mesh = None
    if (args.tp > 1 or args.dp > 1 or args.sp > 1 or args.ep > 1
            or args.pp > 1 or multihost):
        from ..parallel.mesh import make_mesh
        # multihost with all-default axes: tp spans every device cluster-wide
        tp = None if (multihost and args.tp == 1) else args.tp
        mesh = make_mesh(tp=tp, dp=args.dp, sp=args.sp, ep=args.ep,
                         pp=args.pp)

    q80 = args.buffer_float_type == "q80"
    if q80 and args.pp > 1:
        # pipeline stages reduce with GSPMD-exact collectives; the quantized
        # exchange cannot nest inside the manual-pp region
        print("⏩ --pp uses exact collectives; ignoring --buffer-float-type q80")
        q80 = False

    # streamed sharded load: one tensor resident at a time, each shard
    # placed straight onto its device (ref weight push: transformer.cpp:562-621)
    t0 = time.perf_counter()
    tensor_src = None
    if getattr(args, "push_weights", False) and multihost:
        # rank 0 streams its file into the broadcast; workers consume the
        # identical tensor stream with no local .m
        from ..parallel.multihost import bcast_model_tensors
        tensor_src = bcast_model_tensors(spec, args.model or None)
    # ONE resolution of the --shard-vocab tri-state, shared by the loader
    # and the engine: they MUST agree — the loader places tok_emb/wcls in
    # the layout the engine keeps, so a drift here would silently
    # reintroduce the load-time reshard (a transient replicated 524
    # MB/chip table at 70B widths)
    shard_vocab = {"auto": None, "on": True, "off": False}[
        getattr(args, "shard_vocab", "auto")]
    params, lstats = load_params_streamed(
        spec, args.model, mesh, mode=mode, dtype=cdt, q80_collectives=q80,
        tensors=tensor_src, shard_vocab=shard_vocab)
    print(f"⏩ loaded {lstats.total_bytes / 1e9:.2f} GB in "
          f"{time.perf_counter()-t0:.1f}s (peak host "
          f"{lstats.peak_host_bytes / 1e6:.0f} MB)")
    engine = Engine(
        spec, params, mesh,
        batch=max(args.dp, 1),
        max_seq_len=args.max_seq_len,
        compute_dtype=cdt, cache_dtype=kdt,
        activation_q80=(q80 and mode == "q40"),
        q80_collectives=q80,
        use_pallas=args.pallas,  # None -> engine default (on for TPU)
        # folded into the KV-session fingerprint: a session saved from a
        # same-shape different-weight model must be refused (ADVICE r3)
        model_fingerprint=model_fp,
        # vocab sharding: None (auto) enables whenever the mesh's tp
        # axes divide the vocab; resolved ONCE above, shared with the
        # loader placement
        shard_vocab=shard_vocab,
    )

    tokenizer = Tokenizer.from_file(args.tokenizer)
    seed = args.seed if args.seed is not None else int(time.time())
    if multihost:
        # one sampler stream cluster-wide: every process reproduces the
        # root's sampling decisions locally (no per-token control traffic,
        # unlike the reference's per-step pos broadcast, tasks.cpp:165-182)
        from ..parallel.multihost import broadcast_seed
        seed = broadcast_seed(seed)
    sampler = Sampler(tokenizer.vocab_size, args.temperature, args.topp, seed)
    return engine, tokenizer, sampler


class FrontDoorTemplate:
    """The slice of the Engine surface a PROCESS-TIER api front end
    actually reads (shape validation at startup; the handlers use the
    router's remote shape shim per request). Built by
    ``build_front_template`` WITHOUT loading weights: the workers own the
    model — loading it in the parent too would hold N+1 copies locally,
    and force a pure --replica-hosts router box to hold one at all."""

    def __init__(self, spec, max_seq_len=None):
        self.spec = spec
        self.seq_len = min(max_seq_len or spec.seq_len, spec.seq_len)


def build_front_template(args):
    """model file -> (shape template, tokenizer, sampler) for the
    process-replica front door (api --replica-procs/--replica-hosts):
    reads only the spec header of the .m — no weight load, no Engine, no
    KV cache. Tokenizing, routing, retry policy, and shape validation
    are everything the parent does; the worker processes own the model
    (runtime/replica_worker.build_supervisor_factory)."""
    from ..io.model_file import read_spec
    from ..quants.types import FloatType
    from ..sampler import Sampler
    from ..tokenizer import Tokenizer

    if not args.model or not args.tokenizer:
        sys.exit("error: --model and --tokenizer are required")
    wft = (FloatType[args.weights_float_type.upper()]
           if args.weights_float_type else None)
    spec = read_spec(args.model, weights_float_type=wft)
    print(f"⏩ {args.model}: arch={spec.arch.name} dim={spec.dim} "
          f"layers={spec.n_layers} heads={spec.n_heads}/{spec.n_kv_heads} "
          f"seq={spec.seq_len} (front door: spec only, workers own the "
          "weights)")
    tokenizer = Tokenizer.from_file(args.tokenizer)
    seed = args.seed if args.seed is not None else int(time.time())
    sampler = Sampler(tokenizer.vocab_size, args.temperature, args.topp,
                      seed)
    return FrontDoorTemplate(spec, args.max_seq_len), tokenizer, sampler


def check_session_flags(args) -> None:
    """--session needs a host-fetchable, stage-flat KV cache:
    save_session fetches it to the host — impossible for a multi-process
    mesh (non-addressable shards) and unsupported for stage-stacked pp
    caches. Shared by the chat CLI and the API server so the constraint
    cannot diverge; fails before any engine work."""
    if getattr(args, "session", None) and (args.nnodes > 1 or args.pp > 1):
        sys.exit("error: --session does not compose with --nnodes or --pp")


def _steps(args, engine) -> int:
    s = args.steps if args.steps > 0 else engine.seq_len
    return min(s, engine.seq_len)  # clamp like ref: app.cpp:117-119


def _safe_print(piece: str) -> None:
    """Print only printable pieces (ref: safePrintf, src/tokenizer.cpp:18-36)."""
    out = "".join(c for c in piece if c.isprintable() or c in "\n\t ")
    print(out, end="", flush=True)


def _announce_run(tokens: list[int], max_tokens: int, reset: bool = False,
                  sampler=None, lookup: int = 0) -> None:
    """Root side of the multi-host protocol: tell worker processes to enter
    the same generate() call (no-op single-process). lookup > 0 replays a
    speculative run — deterministic draft mining keeps the verify shapes
    in lock-step. With the flight recorder on, the run rides one minted
    trace id (header slot) so the workers' span events (shipped back via
    MSG_TRACE) land on the root's timeline under it."""
    if jax.process_count() > 1:
        from ..parallel import multihost as mh
        from ..runtime.trace import TRACER

        tid = 0
        if TRACER.enabled:
            tid = TRACER.new_id()
            link = mh.get_link()
            if link is not None:
                link.trace_tid = tid
            TRACER.event("cluster_tick", tid, phase="run", role="root",
                         rank=0, n_prompt=len(tokens))
        mh.set_phase("run")
        mh.send_run(tokens, max_tokens,
                    sampler.rng_state if sampler else 0,
                    sampler.temperature if sampler else 0.0,
                    sampler.topp if sampler else 0.0, reset,
                    lookup=lookup, trace_tid=tid)


import contextlib


@contextlib.contextmanager
def _maybe_profile(args, trace_dir=None):
    """jax.profiler trace of the generation when --profile DIR is given (or
    an explicit dir — the benchmark mode's per-step T capture)."""
    target = trace_dir or args.profile
    if not target:
        yield
        return
    import jax.profiler
    with jax.profiler.trace(target):
        yield
    if args.profile:
        print(f"📈 profiler trace written to {args.profile}")


def _stream_pieces(tokenizer, prev_token: int, toks: list[int]) -> None:
    """Print a token list as decoded text (single place for the piece loop)."""
    for tok in toks:
        _safe_print(tokenizer.decode_piece(prev_token, tok).decode(
            "utf-8", errors="replace"))
        prev_token = tok
    print()


def cmd_generate(args, benchmark: bool) -> None:
    if args.device_sampling and args.nnodes > 1:
        sys.exit("error: --device-sampling does not compose with "
                 "--nnodes (the worker protocol drives generate())")
    if args.lookup_decode:
        if args.device_sampling:
            sys.exit("error: --lookup-decode is host-loop decoding; it "
                     "does not compose with --device-sampling")
        if args.dp > 1 and args.temperature != 0:
            sys.exit("error: --lookup-decode with --dp is greedy-only "
                     "(Engine.generate_batch_lookup); set --temperature 0")
        if args.dp > 1 and args.nnodes > 1:
            # the worker protocol's lookup replay is single-row
            # (cmd_worker -> generate_lookup); a batched root would run a
            # different forward program and hang the cluster
            sys.exit("error: --lookup-decode with --dp does not compose "
                     "with --nnodes")
    engine, tokenizer, sampler = build_engine(args)
    prompt = args.prompt or "Hello"
    tokens = tokenizer.encode(prompt)
    print(f"💡 prompt tokens: {len(tokens)}")

    if engine.batch > 1:
        # dp throughput mode: the batch rows generate independently (here the
        # same prompt replicated); row 0 streams to stdout
        t0 = time.perf_counter()
        if args.lookup_decode:
            # batched speculation (round 5): per-row drafts, one verify
            # forward per step, exact per-row greedy parity
            _announce_run(tokens, _steps(args, engine), sampler=sampler,
                          lookup=args.lookup_decode)
            outs = engine.generate_batch_lookup(
                [tokens] * engine.batch, _steps(args, engine),
                eos_id=tokenizer.stop_token_ids(),
                draft_len=args.lookup_decode,
                vocab_size=tokenizer.vocab_size)
        elif args.device_sampling:
            with _maybe_profile(args):
                outs = engine.generate_batch_device(
                    [tokens] * engine.batch, _steps(args, engine),
                    temperature=args.temperature, topp=args.topp,
                    seed=sampler.rng_state,
                    eos_id=tokenizer.stop_token_ids(),
                    vocab_size=tokenizer.vocab_size)
        else:
            _announce_run(tokens, _steps(args, engine), sampler=sampler)
            outs = engine.generate_batch([tokens] * engine.batch,
                                         _steps(args, engine), sampler,
                                         eos_id=tokenizer.stop_token_ids())
        dt = time.perf_counter() - t0
        _stream_pieces(tokenizer, tokens[-1], outs[0])
        if benchmark:
            n = sum(len(o) for o in outs)
            print(f"Generated tokens:    {n} ({engine.batch} sequences)")
            print(f"Avg tokens / second: {n / max(dt, 1e-9):.2f}")
        return

    if args.device_sampling:
        t0 = time.perf_counter()
        with _maybe_profile(args):
            out = engine.generate_device(
                tokens, _steps(args, engine),
                temperature=args.temperature, topp=args.topp,
                seed=sampler.rng_state,
                eos_id=tokenizer.stop_token_ids(),
                vocab_size=tokenizer.vocab_size)
        dt = time.perf_counter() - t0
        _stream_pieces(tokenizer, tokens[-1], out)
        if benchmark:
            # honest accounting: this first call's wall time includes the
            # loop's jit compile — don't fake a per-token rate
            print(f"Generated tokens:    {len(out)} (on-device loop, "
                  f"{engine.last_device_steps} device steps)")
            print(f"Wall time:           {dt:.2f} s "
                  "(includes one-time loop compile)")
        return

    prev = [tokens[-1]]

    def on_token(tok: int) -> None:
        _safe_print(tokenizer.decode_piece(prev[0], tok).decode("utf-8", errors="replace"))
        prev[0] = tok

    if args.draft:
        # real-draft speculation (runtime/draft.py): greedy is
        # bit-identical to the plain stream, sampled is
        # distribution-exact via general rejection resampling
        from ..runtime.draft import build_draft
        try:
            draft = build_draft(engine, args.draft)
        except ValueError as e:
            sys.exit(f"error: {e}")
        dl = args.draft_len or 7
        t0 = time.perf_counter()
        with _maybe_profile(args):
            if args.temperature > 0:
                res = engine.generate_draft_sampled(
                    tokens, _steps(args, engine), draft=draft,
                    temperature=float(np.float32(args.temperature)),
                    topp=float(np.float32(args.topp)),
                    seed=sampler.rng_state,
                    eos_id=tokenizer.stop_token_ids(), draft_len=dl,
                    on_token=on_token, vocab_size=tokenizer.vocab_size)
            else:
                res = engine.generate_draft(
                    tokens, _steps(args, engine), draft=draft,
                    eos_id=tokenizer.stop_token_ids(), draft_len=dl,
                    on_token=on_token, vocab_size=tokenizer.vocab_size)
        dt = time.perf_counter() - t0
        print()
        if benchmark:
            fwd, n = engine.last_accept_stats
            print(f"Generated tokens:    {n} in {fwd} forwards "
                  f"({n / max(fwd, 1):.2f} tokens/forward, "
                  f"draft {args.draft})")
            print(f"Wall time:           {dt:.2f} s (includes draft + "
                  "verify compiles)")
        return

    if args.lookup_decode:
        _announce_run(tokens, _steps(args, engine), sampler=sampler,
                      lookup=args.lookup_decode)
        t0 = time.perf_counter()
        with _maybe_profile(args):
            if args.temperature > 0:
                # sampled speculation: distribution-exact via rejection
                # resampling (Engine.generate_lookup_sampled) — NOT
                # xorshift-stream-parity with the plain sampled loop.
                # temperature/topp go through the same float32 roundtrip
                # the cluster header applies: a worker seeing
                # 0.69999998807 where the root used 0.7 could flip one
                # accept decision, diverge the verify widths, and hang a
                # cross-host collective
                res = engine.generate_lookup_sampled(
                    tokens, _steps(args, engine),
                    temperature=float(np.float32(args.temperature)),
                    topp=float(np.float32(args.topp)),
                    seed=sampler.rng_state,
                    eos_id=tokenizer.stop_token_ids(),
                    draft_len=args.lookup_decode, on_token=on_token,
                    vocab_size=tokenizer.vocab_size)
            else:
                res = engine.generate_lookup(
                    tokens, _steps(args, engine),
                    eos_id=tokenizer.stop_token_ids(),
                    draft_len=args.lookup_decode, on_token=on_token,
                    vocab_size=tokenizer.vocab_size)
        dt = time.perf_counter() - t0
        print()
        if benchmark:
            fwd, n = engine.last_accept_stats
            print(f"Generated tokens:    {n} in {fwd} forwards "
                  f"({n / max(fwd, 1):.2f} tokens/forward)")
            print(f"Wall time:           {dt:.2f} s (includes compiles for "
                  "each distinct verify length)")
        return

    _announce_run(tokens, _steps(args, engine), sampler=sampler)
    # benchmark mode on a single-process multi-device mesh: capture a trace
    # so T is the MEASURED per-step collective time from the device
    # timeline (netstats.per_step_op_ms), not a repeated microbench
    # constant — the reference's T column is genuinely per-token
    # (ref: src/apps/dllama/dllama.cpp:74-79)
    trace_dir = args.profile
    auto_trace = (benchmark and trace_dir is None and engine.mesh is not None
                  and engine.mesh.size > 1 and jax.process_count() == 1)
    if auto_trace:
        import tempfile
        trace_dir = tempfile.mkdtemp(prefix="dllama-trace-")
    try:
        with _maybe_profile(args, trace_dir):
            res = engine.generate(tokens, _steps(args, engine), sampler,
                                  eos_id=tokenizer.stop_token_ids(),
                                  on_token=on_token)
        print()
        if benchmark:
            _print_benchmark(args, engine, res, trace_dir=trace_dir)
    finally:
        if auto_trace:  # parsed above; traces are tens of MB per run
            import shutil
            shutil.rmtree(trace_dir, ignore_errors=True)


def _print_benchmark(args, engine, res, trace_dir=None) -> None:
    """Per-token G/I/T/S lines + averages (ref: dllama.cpp:47-48,74-91);
    S = modeled per-device collective kB, T = measured per-step collective
    time from the trace (falling back to the all-reduce microbench scaled
    to the per-layer reduce count — netstats.py)."""
    wire = engine.wire_estimate()
    # the first stats step is the whole prefill: its fallback T follows the
    # schedule prefill actually ran (GPipe ppermute hops on pp meshes —
    # engine.measure_prefill_transfer_ms), not the per-token decode model
    n_prompt = max(engine.pos - (len(res.tokens) - 1), 1)
    if jax.process_count() > 1:
        # workers join the IDENTICAL microbench sequence: n_prompt rides
        # the header so their measure_prefill_transfer_ms runs the same
        # per-segment collectives (incl. pp ppermute) as ours — the root
        # measuring a collective the workers skip deadlocks the mesh
        # (ADVICE r5 high; regression: tests/test_multihost.py
        # test_two_process_benchmark_completes)
        from ..parallel import multihost as mh
        mh.set_phase("bench")
        mh.send_xfer_bench(n_prompt)
    t_ms = engine.measure_transfer_ms()
    t_pre_ms = engine.measure_prefill_transfer_ms(n_prompt)
    t_steps: list[float] = []
    if trace_dir:
        from ..runtime.netstats import per_step_op_ms

        # the engine names its jitted wrappers by role (decode_step /
        # prefill_chunk_N / prefill_seg — engine._compiled_step), so decode
        # executions are matched exactly instead of tail-aligning every
        # module named 'run' (ADVICE r3: extra executions in the window
        # shifted T onto the wrong steps). A count mismatch means the
        # window caught unrelated executions — fall back to the microbench.
        dec_t = per_step_op_ms(trace_dir, module_hint="decode_step")
        pre_t = per_step_op_ms(trace_dir, module_hint="prefill")
        n_dec = len(res.stats.steps) - 1
        if len(dec_t) == n_dec and (dec_t or pre_t):
            t_steps = [sum(pre_t)] + dec_t  # n_dec == 0: prefill-only run
        elif dec_t or pre_t:
            print(f"⏩ trace module count mismatch (decode {len(dec_t)} vs "
                  f"{n_dec} steps); using the microbench T estimate")
    for i, s in enumerate(res.stats.steps):
        tv = (t_steps[i] if i < len(t_steps)
              else (t_pre_ms if i == 0 else t_ms))
        print(f"🔶 G {s.generation_ms:7.2f} ms I {s.device_ms:7.2f} ms "
              f"T {tv:6.2f} ms H {s.host_ms:5.2f} ms "
              f"S {wire.sent_kb_per_token:7.1f} kB")
    avg = res.stats.averages()
    n = len(res.tokens)
    print(f"Generated tokens:    {n}")
    print(f"Avg tokens / second: {1000.0 / max(avg.generation_ms, 1e-9):.2f}")
    print(f"Avg generation time: {avg.generation_ms:.2f} ms")
    print(f"Avg inference time:  {avg.device_ms:.2f} ms")
    if len(t_steps) > 1:
        t_avg = sum(t_steps[1:]) / len(t_steps[1:])
        print(f"Avg transfer:        {t_avg:.2f} ms/token measured "
              f"(trace; microbench estimate {t_ms:.2f} ms), "
              f"{wire.sent_kb_per_token:.1f} kB/token/device")
    else:
        print(f"Avg transfer (est):  {t_ms:.2f} ms, "
              f"{wire.sent_kb_per_token:.1f} kB/token/device")
    for kname, kb in wire.breakdown.items():
        print(f"  {kname}: {kb:.1f} kB")
    print(f"Avg sampling time:   {avg.host_ms:.2f} ms")


def cmd_chat(args) -> None:
    """Interactive chat with the Llama-2 template (ref: dllama.cpp:133-178)."""
    import os

    if args.lookup_decode and args.nnodes > 1:
        # same loud guard as generate mode — a silently ignored flag is
        # worse than an error
        sys.exit("error: --lookup-decode does not compose with --nnodes")
    check_session_flags(args)
    engine, tokenizer, sampler = build_engine(args)
    chat_draft = None
    if args.draft:
        from ..runtime.draft import build_draft
        try:
            chat_draft = build_draft(engine, args.draft)
        except ValueError as e:
            sys.exit(f"error: {e}")
    convo: list[int] = []  # whole-conversation tokens: the draft miner's
    # n-gram source (chat history is full of quotable n-grams) AND the
    # real draft's catch-up stream (token at position i = convo[i])
    resumed = False
    if args.session and os.path.exists(args.session):
        convo = engine.load_session(args.session)
        resumed = True
        print(f"💾 resumed session from {args.session} "
              f"({engine.pos} cached positions)")
    system = args.system_prompt
    if system is None and not resumed:
        try:
            system = input("💻 System prompt (optional): ")
        except EOFError:
            system = ""
    first = not resumed
    while True:
        try:
            user = input("\n👱 User\n> ")
        except EOFError:
            break
        if not user:
            continue
        if first and system:
            text = f"[INST] <<SYS>>\n{system}\n<</SYS>>\n\n{user} [/INST]"
        else:
            text = f"[INST] {user} [/INST]"
        first = False
        tokens = tokenizer.encode(text, add_bos=True)
        print("\n🤖 Assistant")
        prev = [tokens[-1]]
        stops = tokenizer.stop_token_ids()

        def on_token(tok: int) -> None:
            if tok not in stops:
                _safe_print(tokenizer.decode_piece(prev[0], tok).decode("utf-8", errors="replace"))
            prev[0] = tok

        # the prompt itself must also fit before any generation can start
        remaining = engine.seq_len - engine.pos - len(tokens)
        if remaining <= 1:
            print("(context window full)")
            break
        budget = min(_steps(args, engine), remaining)
        convo.extend(tokens)
        if chat_draft is not None:
            # real-draft turns: the draft's own forward proposes — the
            # chat history is its catch-up stream, not an n-gram mine
            dl = args.draft_len or 7
            if args.temperature > 0:
                res = engine.generate_draft_sampled(
                    tokens, budget, draft=chat_draft,
                    temperature=args.temperature, topp=args.topp,
                    seed=sampler.rng_state, eos_id=stops, draft_len=dl,
                    on_token=on_token, vocab_size=tokenizer.vocab_size,
                    history=convo)
                sampler.set_seed(sampler.rng_state + len(res.tokens) + 1)
            else:
                res = engine.generate_draft(
                    tokens, budget, draft=chat_draft, eos_id=stops,
                    draft_len=dl, on_token=on_token,
                    vocab_size=tokenizer.vocab_size, history=convo)
            convo.extend(res.tokens)
        elif args.lookup_decode:
            # chat turns speculate, mining drafts from the WHOLE
            # conversation so far — prior turns are the richest n-gram
            # source. Greedy turns are token-stream-exact; sampled turns
            # are distribution-exact (rejection resampling)
            if args.temperature > 0:
                res = engine.generate_lookup_sampled(
                    tokens, budget, temperature=args.temperature,
                    topp=args.topp, seed=sampler.rng_state, eos_id=stops,
                    draft_len=args.lookup_decode, on_token=on_token,
                    vocab_size=tokenizer.vocab_size, history=convo)
                # advance the shared seed so the next turn draws fresh
                sampler.set_seed(sampler.rng_state + len(res.tokens) + 1)
            else:
                res = engine.generate_lookup(tokens, budget, eos_id=stops,
                                             draft_len=args.lookup_decode,
                                             on_token=on_token,
                                             vocab_size=tokenizer.vocab_size,
                                             history=convo)
            convo.extend(res.tokens)
        else:
            _announce_run(tokens, budget, sampler=sampler)
            res = engine.generate(tokens, budget, sampler,
                                  eos_id=stops, on_token=on_token)
            convo.extend(res.tokens)
        print()
        if args.session:
            # token history rides along so a resumed process keeps mining
            # speculative drafts from pre-restart turns
            engine.save_session(args.session, tokens=convo)


def cmd_worker(args) -> None:
    """Worker process: hold this host's weight shards, lock-step the root's
    runs (ref: src/apps/dllama/dllama.cpp:180-193, Worker::work
    tasks.cpp:230-256 — the TaskLoop pass per `pos` trigger becomes a full
    generate() per broadcast run; per-token sync is unnecessary because the
    sampler stream is deterministic and logits are replicated)."""
    from ..parallel import multihost as mh
    from ..runtime.trace import TRACER

    if getattr(args, "trace", False):
        # worker-side flight recorder (dlwire): ring only — span events
        # ship ROOT-ward over MSG_TRACE after each run, so the root's
        # /admin/trace (or trace sink) is the one merged timeline; a
        # local sink would just split the story across hosts
        TRACER.configure(
            capacity=getattr(args, "trace_buffer", None) or 8192,
            enabled=True)
    engine, tokenizer, sampler = build_engine(args)
    stops = tokenizer.stop_token_ids()
    api_state = None
    print(f"⏳ worker rank {jax.process_index()} of {jax.process_count()} "
          "ready")
    while True:
        mh.set_phase("idle")
        # supervised wait: a root that dies or wedges surfaces as a
        # structured ClusterPeerLost within --worker-timeout (the link's
        # receiver thread also hard-exits via the installed handler when
        # this thread is itself wedged in a collective) — never the
        # reference's unbounded socket read
        msg = mh.recv_msg()
        if msg.kind == mh.MSG_SHUTDOWN:
            print("🔌 root shut down — exiting")
            return
        if msg.kind == mh.MSG_RUN:
            mh.set_phase("run")
            tid = msg.trace_tid
            t_run = time.perf_counter()
            if TRACER.enabled and tid:
                # adopt the root's id: advance the local mint counter
                # past it so this worker's own scheduler-door mints
                # (MSG_API replays) can never collide with a run tid
                TRACER.reserve(tid)
                link = mh.get_link()
                if link is not None:
                    link.trace_tid = tid  # a mid-run casualty links here
                TRACER.event("cluster_tick", tid, phase="run",
                             role="worker", rank=jax.process_index(),
                             n_prompt=len(msg.tokens or ()))
            if msg.reset:
                engine.reset()
            if msg.lookup:
                # speculative replay: drafts mine the replicated token
                # stream, so every process computes the same verify widths
                # (send_run's lock-step contract); the sampled mode's
                # rejection draws come from the header seed — identical
                # numpy streams on every process
                if msg.temperature > 0:
                    engine.generate_lookup_sampled(
                        msg.tokens, msg.max_tokens,
                        temperature=msg.temperature, topp=msg.topp,
                        seed=msg.seed, eos_id=stops,
                        draft_len=msg.lookup,
                        vocab_size=tokenizer.vocab_size)
                else:
                    engine.generate_lookup(msg.tokens, msg.max_tokens,
                                           eos_id=stops,
                                           draft_len=msg.lookup,
                                           vocab_size=tokenizer.vocab_size)
            else:
                # sample with the ROOT's params and rng state from the
                # header — immune to any sampler-flag mismatch between
                # the processes
                from ..sampler import Sampler
                run_sampler = Sampler(tokenizer.vocab_size, msg.temperature,
                                      msg.topp, msg.seed)
                if engine.batch > 1:
                    engine.generate_batch([msg.tokens] * engine.batch,
                                          msg.max_tokens, run_sampler,
                                          eos_id=stops)
                else:
                    engine.generate(msg.tokens, msg.max_tokens, run_sampler,
                                    eos_id=stops)
            if TRACER.enabled and tid:
                TRACER.event("cluster_tick", tid, phase="run_done",
                             role="worker", rank=jax.process_index(),
                             ms=round((time.perf_counter() - t_run) * 1e3,
                                      3))
                # one ship per run (tids are per-run unique — no delta
                # bookkeeping needed): best-effort, the root's casualty
                # path covers a worker that dies before shipping
                lk = mh.get_link()
                if lk is not None and hasattr(lk, "ship_trace"):
                    lk.ship_trace(TRACER.export_span(tid))
        elif msg.kind == mh.MSG_API:
            mh.set_phase("api")
            # replay the root's API request end-to-end from the raw body —
            # prompt build, sampling, stop scan are all deterministic
            import json

            from .api_server import ApiState, PromptTooLong, _completion_chunks
            if api_state is None:
                api_state = ApiState(engine, tokenizer, sampler,
                                     lookup_decode=args.lookup_decode)
            try:
                for _ in _completion_chunks(api_state, json.loads(msg.body)):
                    pass
            except (PromptTooLong, json.JSONDecodeError, KeyError,
                    TypeError) as e:
                # deterministic request errors: the root raised the SAME
                # error at the same point, so state stays in lock-step
                print(f"⚠️  request failed: {type(e).__name__}: {e}")
            except Exception as e:  # noqa: BLE001 — worker-LOCAL failure
                # (OOM, I/O) the root never hit: engine/session state has
                # diverged from the root's. Resync to a known state — fresh
                # cache, empty session — so subsequent requests line their
                # collectives up again (the sampler state was restored by
                # _completion_chunks' finally) (ADVICE r2)
                print(f"⚠️  request failed locally ({type(e).__name__}: {e})"
                      " — resyncing engine state")
                api_state.cached_tokens = []
                engine.reset()
        elif msg.kind == mh.MSG_XFER_BENCH:
            # the EXACT sequence the root runs in _print_benchmark —
            # decode microbench THEN the prefill-schedule microbench for
            # the header's n_prompt (ADVICE r5 high: the old handler
            # stopped after measure_transfer_ms, so the root's prefill
            # collectives had no worker counterpart and --benchmark hung
            # the cluster)
            mh.set_phase("bench")
            engine.measure_transfer_ms()
            engine.measure_prefill_transfer_ms(max(msg.max_tokens, 1))
            mh.set_phase("idle")


def main(argv: list[str] | None = None) -> None:
    args = build_argparser().parse_args(argv)
    if args.workers:
        sys.exit("error: --workers is not applicable on TPU — the reference's "
                 "TCP root/worker star is one SPMD program here; use --tp N "
                 "for one host's devices, or --nnodes/--coordinator + "
                 "`dllama worker` processes for a multi-host cluster")
    # pp contract holes closed at PARSE time, before any engine or cluster
    # work: a flag combination that cannot work must not cost a model load
    # (or, worse, be silently ignored for a whole run)
    if args.draft_len is not None and not args.draft:
        sys.exit("error: --draft-len has no effect without --draft "
                 "(self:<depth> or model:<path>)")
    if args.draft_len is not None and args.draft_len < 1:
        sys.exit("error: --draft-len must be >= 1")
    if args.draft:
        if args.lookup_decode:
            sys.exit("error: --draft and --lookup-decode both pick the "
                     "draft source — use one (the real draft pays on "
                     "arbitrary text; prompt lookup only on repetitive)")
        from ..runtime.draft import parse_draft_spec
        try:
            kind, arg = parse_draft_spec(args.draft)
        except ValueError as e:
            sys.exit(f"error: {e}")
        if kind == "model":
            import os as _os
            if not _os.path.exists(arg):
                sys.exit(f"error: --draft model:{arg}: no such file")
        if args.nnodes > 1:
            sys.exit("error: --draft does not compose with --nnodes "
                     "(the worker protocol has no draft replay)")
        if args.pp > 1:
            sys.exit("error: --draft does not compose with --pp "
                     "(stage-stacked layers cannot be depth-sliced)")
        if args.dp > 1:
            sys.exit("error: --draft is single-sequence outside api "
                     "mode and per-slot inside it; it does not compose "
                     "with --dp")
        if args.device_sampling:
            sys.exit("error: --draft is host-loop decoding; it does "
                     "not compose with --device-sampling")
    if (getattr(args, "shard_vocab", "auto") == "on" and args.tp <= 1
            and args.nnodes <= 1):
        # dead-flag discipline: an explicit "on" needs a tp mesh to split
        # over (auto simply stays off); multihost defaults tp to the
        # cluster width, so only the unambiguous single-node case refuses
        sys.exit("error: --shard-vocab on needs a tensor-parallel mesh "
                 "(--tp > 1) to split the vocab over; 'auto' enables it "
                 "whenever the mesh allows")
    if args.session and args.pp > 1:
        sys.exit("error: --session does not compose with --pp > 1 — "
                 "save_session fetches the KV cache to the host, and "
                 "stage-stacked pipeline caches are not host-fetchable "
                 "(see docs/parallelism.md)")
    if args.session and args.nnodes > 1:
        sys.exit("error: --session does not compose with --nnodes > 1 — "
                 "a multi-process mesh's cache shards are not addressable "
                 "from one host")
    if args.nnodes > 1:
        if not args.coordinator:
            sys.exit("error: --nnodes > 1 requires --coordinator host:port")
        if args.mode == "worker" and args.node_rank == 0:
            sys.exit("error: rank 0 is the root — run a non-worker mode")
        if args.mode != "worker" and args.node_rank != 0:
            sys.exit("error: non-root ranks must run `dllama worker`")
        if args.heartbeat_interval <= 0 or args.worker_timeout <= 0:
            sys.exit("error: --heartbeat-interval and --worker-timeout "
                     "must be positive")
        if args.worker_timeout < 2 * args.heartbeat_interval:
            # healthy workers only produce frames in response to PINGs: a
            # detection bound under ~2 pings declares live nodes dead on
            # one delayed heartbeat — a self-destructing config, refused
            # up front like the other flag-contract holes above
            sys.exit(f"error: --worker-timeout {args.worker_timeout:g} "
                     "must be at least 2x --heartbeat-interval "
                     f"({args.heartbeat_interval:g}) — a node is only "
                     "expected to produce a frame per heartbeat, so a "
                     "tighter bound declares healthy peers lost "
                     "(recommended: 3-5x)")
        from ..parallel import multihost as mh
        try:
            mh.init_multihost(args.coordinator, args.nnodes, args.node_rank,
                              connect_timeout=args.connect_timeout,
                              heartbeat_interval=args.heartbeat_interval,
                              worker_timeout=args.worker_timeout)
        except mh.ClusterProtocolError as e:
            print(f"🔴 cluster formation failed: {e}", flush=True)
            sys.exit(mh.EXIT_FORMATION)
        # peer loss during ANY later phase (weight load, a generate()'s
        # collectives, idle) -> one structured diagnostic line + exit 43,
        # fired from the link's detection thread — the only thread
        # guaranteed not to be wedged inside the very collective the dead
        # peer just orphaned
        mh.install_peer_lost_exit()
        mh.set_phase("load")
    elif args.mode == "worker":
        sys.exit("error: worker mode needs a cluster — pass --nnodes N "
                 "--node-rank r --coordinator host:port (single-host "
                 "multi-device runs need no workers: use --tp N)")
    clean = True
    try:
        if args.mode == "worker":
            cmd_worker(args)
        elif args.mode == "inference":
            cmd_generate(args, benchmark=True)
        elif args.mode == "generate":
            cmd_generate(args, benchmark=False)
        elif args.mode == "chat":
            cmd_chat(args)
        elif args.mode == "api":
            from .api_server import serve
            serve(args)
    except BaseException as e:
        clean = False
        if args.nnodes > 1:
            from ..parallel.multihost import (EXIT_PEER_LOST,
                                              ClusterPeerLost)
            if isinstance(e, ClusterPeerLost):
                # surfaced on the driving thread (a send/recv raced the
                # detection threads' callback): same structured exit
                import json
                print("🔴 cluster: " + json.dumps(e.summary()), flush=True)
                sys.exit(EXIT_PEER_LOST)
        raise
    finally:
        if args.nnodes > 1:
            from ..parallel import multihost as mh
            if args.mode != "worker" and clean:
                # clean exit: the SHUTDOWN frame reaches workers wherever
                # they are (the control channel is out-of-band — no
                # collective pairing needed). After a mid-run crash the
                # heartbeat EOF tells them instead, within
                # --worker-timeout, so no broadcast is required (or safe)
                mh.send_shutdown()
            mh.close_link()


if __name__ == "__main__":
    main()
