"""Converter tests.

The HF tests are golden-oracle end-to-end: build a tiny HF model with
transformers, convert its safetensors checkpoint to `.m`, run OUR forward,
and require the logits to match HF's torch forward. This validates the whole
chain — tensor-name mapping, rotary permutation (llama) vs native layout
(mixtral), file format, params loading, and model math — against an
independent implementation (stronger than the reference's hardcoded golden
floats, SURVEY.md §4).
"""

import base64
import struct

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.converters.hf import convert_hf, permute_rotary
from distributed_llama_tpu.converters.tokenizer_llama3 import llama3_to_tokenizer_data
from distributed_llama_tpu.converters.tokenizer_spm import parse_spm_model, spm_to_tokenizer_data
from distributed_llama_tpu.io.model_file import read_model
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.quants.types import FloatType
from distributed_llama_tpu.runtime.engine import Engine


def _hf_logits(model, tokens):
    import torch

    with torch.no_grad():
        out = model(torch.tensor([tokens], dtype=torch.long))
    return out.logits[0, -1].float().numpy()


def _our_logits(mpath, tokens):
    spec, tensors = read_model(mpath)
    params = load_params(spec, tensors, mode="dense", dtype=jnp.float32)
    engine = Engine(spec, params, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    logits = engine.prefill(list(tokens))
    return np.asarray(logits)[0]


def test_hf_llama_oracle(tmp_path):
    transformers = pytest.importorskip("transformers")

    config = transformers.LlamaConfig(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, vocab_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    import torch
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(config).eval().float()
    hf_dir = str(tmp_path / "hf")
    model.save_pretrained(hf_dir, safe_serialization=True)

    mpath = str(tmp_path / "model.m")
    spec = convert_hf(hf_dir, mpath, FloatType.F32, progress=False)
    assert spec.n_kv_heads == 2

    tokens = [1, 17, 93, 5, 64, 22]
    ref = _hf_logits(model, tokens)
    got = _our_logits(mpath, tokens)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_hf_mixtral_oracle(tmp_path):
    transformers = pytest.importorskip("transformers")

    config = transformers.MixtralConfig(
        hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, vocab_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_local_experts=4, num_experts_per_tok=2, tie_word_embeddings=False)
    import torch
    torch.manual_seed(1)
    model = transformers.MixtralForCausalLM(config).eval().float()
    hf_dir = str(tmp_path / "hf")
    model.save_pretrained(hf_dir, safe_serialization=True)

    mpath = str(tmp_path / "model.m")
    spec = convert_hf(hf_dir, mpath, FloatType.F32, progress=False)
    assert spec.n_experts == 4 and spec.n_active_experts == 2

    tokens = [1, 40, 99, 3]
    ref = _hf_logits(model, tokens)
    got = _our_logits(mpath, tokens)
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_permute_rotary_roundtrip():
    """The permutation maps HF half-split rows to interleaved rows."""
    h, hs, n = 2, 8, 4
    w = np.arange(h * hs * n, dtype=np.float32).reshape(h * hs, n)
    p = permute_rotary(w, h)
    for head in range(h):
        for j in range(hs // 2):
            np.testing.assert_array_equal(p[head * hs + 2 * j], w[head * hs + j])
            np.testing.assert_array_equal(p[head * hs + 2 * j + 1],
                                          w[head * hs + hs // 2 + j])


# --- tokenizer converters --------------------------------------------------

def _encode_varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _spm_piece(piece: bytes, score: float, ptype: int | None = None) -> bytes:
    body = bytes([0x0A]) + _encode_varint(len(piece)) + piece   # field 1, wire 2
    body += bytes([0x15]) + struct.pack("<f", score)            # field 2, wire 5
    if ptype is not None:
        body += bytes([0x18]) + _encode_varint(ptype)           # field 3, wire 0
    return bytes([0x0A]) + _encode_varint(len(body)) + body     # ModelProto field 1


def test_spm_parser_and_convert(tmp_path):
    pieces = [(b"<unk>", 0.0, 2), (b"<s>", 0.0, 3), (b"</s>", 0.0, 3),
              ("▁hi".encode(), -1.5, None), (b"x", -2.0, None)]
    raw = b"".join(_spm_piece(p, s, t) for p, s, t in pieces)
    path = str(tmp_path / "tok.model")
    with open(path, "wb") as f:
        f.write(raw)

    parsed = parse_spm_model(path)
    assert [p[0] for p in parsed] == [p[0] for p in pieces]
    assert parsed[3][1] == pytest.approx(-1.5)

    data = spm_to_tokenizer_data(path)
    assert data.vocab[3] == b" hi"  # U+2581 -> space
    assert data.vocab_size == 5 and data.bos_id == 1 and data.eos_id == 2
    # bos/eos pieces rewritten to the reference exporter's display form
    # (ref: convert-tokenizer-sentencepiece.py:42-45)
    assert data.vocab[1] == b"\n<s>\n" and data.vocab[2] == b"\n</s>\n"


def test_llama3_tokenizer_convert(tmp_path):
    toks = [b"a", b"b", b"ab", b" the"]
    path = str(tmp_path / "tokenizer.model")
    with open(path, "wb") as f:
        for i, t in enumerate(toks):
            f.write(base64.b64encode(t) + b" " + str(i).encode() + b"\n")

    data = llama3_to_tokenizer_data(path)
    assert data.vocab[:4] == toks
    assert data.vocab_size == 4 + 256
    # merge priority: lower rank -> higher score; specials continue the
    # -rank sequence (reference parity)
    assert data.scores[0] > data.scores[3]
    assert data.scores[4] == -4.0
    # reference special-token table + base-model eos (<|end_of_text|>)
    assert data.vocab[data.bos_id] == b"<|begin_of_text|>"
    assert data.vocab[data.eos_id] == b"<|end_of_text|>"
    assert data.vocab[4 + 9] == b"<|eot_id|>"
    assert data.vocab[4 + 8] == b"<|reserved_special_token_4|>"
    assert data.vocab[-1] == b"<|reserved_special_token_250|>"
    # instruct override
    inst = llama3_to_tokenizer_data(path, eos_id=4 + 9)
    assert inst.vocab[inst.eos_id] == b"<|eot_id|>"


# --- meta / grok1 checkpoint converters ------------------------------------

def _direct_logits(spec, dense, tokens):
    """Oracle: build params straight from the dense arrays (no file/convert
    step) and run our forward."""
    from distributed_llama_tpu.io.model_file import HostTensor, model_tensor_plan

    host = {name: HostTensor(name, FloatType.F32, shape, data=dense[name])
            for name, shape, _ in model_tensor_plan(spec)}
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    engine = Engine(spec, params, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    return np.asarray(engine.prefill(list(tokens)))[0]


def _random_dense(spec, seed):
    from distributed_llama_tpu.io.model_file import model_tensor_plan

    rng = np.random.default_rng(seed)
    return {name: rng.standard_normal(shape, dtype=np.float32) * 0.05
            for name, shape, _ in model_tensor_plan(spec)}


def test_meta_llama_converter_golden(tmp_path):
    """Synthetic 2-shard consolidated.*.pth -> .m: shard re-concat per role
    (axis 1 for tok_emb/wo/w2, axis 0 otherwise, ref: convert-llama.py:73-90)
    must reproduce the unsplit weights bit-exactly, and our logits on the
    converted file must match the direct-construction oracle."""
    torch = pytest.importorskip("torch")

    import json

    from distributed_llama_tpu.converters.meta_llama import convert_meta
    from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec

    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=96, seq_len=32,
                     hidden_act=HiddenAct.SILU)
    dense = _random_dense(spec, seed=21)

    meta_names = {
        "tok_emb": "tok_embeddings.weight", "rms_final": "norm.weight",
        "wcls": "output.weight",
    }
    axis1 = {"tok_emb", "wo", "w2"}

    def meta_name(plan):
        if plan in meta_names:
            return meta_names[plan]
        _, l, rest = plan.split(".", 2)
        table = {"wq": "attention.wq", "wk": "attention.wk",
                 "wv": "attention.wv", "wo": "attention.wo",
                 "w1": "feed_forward.w1", "w2": "feed_forward.w2",
                 "w3": "feed_forward.w3", "rms_att": "attention_norm",
                 "rms_ffn": "ffn_norm"}
        return f"layers.{l}.{table[rest]}.weight"

    n_shards = 2
    shards = [dict() for _ in range(n_shards)]
    for name, x in dense.items():
        base = name.split(".")[-1]
        mname = meta_name(name)
        if x.ndim == 1:
            for s in shards:
                s[mname] = torch.tensor(x)  # norms replicated per shard
        else:
            ax = 1 if base in axis1 else 0
            for i, part in enumerate(np.array_split(x, n_shards, axis=ax)):
                shards[i][mname] = torch.tensor(part.copy())
    folder = tmp_path / "meta"
    folder.mkdir()
    for i, s in enumerate(shards):
        torch.save(s, str(folder / f"consolidated.{i:02d}.pth"))
    with open(folder / "params.json", "w") as f:
        json.dump({"dim": 64, "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                   "vocab_size": 96, "max_seq_len": 32,
                   "rope_theta": 10000.0}, f)

    mpath = str(tmp_path / "meta.m")
    out_spec = convert_meta(str(folder), mpath, FloatType.F32, progress=False)
    assert out_spec.seq_len == 32  # read from params.json (ADVICE r1)
    assert out_spec.hidden_dim == 128  # derived from w1 shard x n_shards

    _, tensors = read_model(mpath)
    for name, x in dense.items():
        np.testing.assert_array_equal(tensors[name].to_f32(), x, err_msg=name)

    tokens = [1, 9, 33, 7]
    np.testing.assert_allclose(_our_logits(mpath, tokens),
                               _direct_logits(spec, dense, tokens),
                               atol=1e-5, rtol=1e-5)


def test_grok1_converter_real_19file_layout(tmp_path):
    """The REAL dump layout (VERDICT r4 #5): 19 shard files named
    pytorch_model-000NN-of-00019.bin with tensors distributed SEQUENTIALLY
    in checkpoint order (like keyfan/grok-1-hf — consecutive layers span
    file boundaries mid-layer), walked with the converter's default
    n_files=19. Exercises the forward-seek + index-backtrack logic on the
    production file count; dims stay shrunken (the mapping and walk, not
    the arithmetic, are what the 19-file path adds)."""
    torch = pytest.importorskip("torch")

    from distributed_llama_tpu.converters.grok1 import _grok_name, convert_grok1
    from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec

    spec = ModelSpec(arch=ArchType.GROK1, dim=64, hidden_dim=96, n_layers=4,
                     n_heads=4, n_kv_heads=2, n_experts=8, n_active_experts=2,
                     vocab_size=96, seq_len=32, hidden_act=HiddenAct.GELU)
    dense = _random_dense(spec, seed=29)

    # sequential split across exactly 19 files, uneven sizes (the real dump
    # packs ~3.4 layers per shard; emulate mid-layer boundaries)
    n_files = 19
    names = list(dense)
    shards = [dict() for _ in range(n_files)]
    per = max(1, len(names) // n_files)
    for i, name in enumerate(names):
        shards[min(i // per, n_files - 1)][_grok_name(name)] = torch.tensor(
            dense[name])
    folder = tmp_path / "grok19"
    folder.mkdir()
    for i, s in enumerate(shards):
        torch.save(
            s, str(folder / f"pytorch_model-{i + 1:05d}-of-{n_files:05d}.bin"))

    mpath = str(tmp_path / "grok19.m")
    convert_grok1(str(folder), mpath, FloatType.F32, progress=False,
                  spec=spec)  # default n_files=19 — the production walk

    _, tensors = read_model(mpath)
    for name, x in dense.items():
        np.testing.assert_array_equal(tensors[name].to_f32(), x, err_msg=name)

    tokens = [1, 9, 33]
    np.testing.assert_allclose(_our_logits(mpath, tokens),
                               _direct_logits(spec, dense, tokens),
                               atol=2e-5, rtol=2e-5)


def test_grok1_converter_golden(tmp_path):
    """Synthetic multi-file Grok torch dump of a shrunken spec -> .m: the
    19-file-walk name mapping (ref: convert-grok-1.py) must reproduce every
    tensor bit-exactly and match the direct-construction oracle logits."""
    torch = pytest.importorskip("torch")

    from distributed_llama_tpu.converters.grok1 import _grok_name, convert_grok1
    from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec

    spec = ModelSpec(arch=ArchType.GROK1, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, n_experts=4, n_active_experts=2,
                     vocab_size=96, seq_len=32, hidden_act=HiddenAct.GELU)
    dense = _random_dense(spec, seed=22)

    # spread tensors across 3 files round-robin (walker must seek across
    # files in both directions)
    n_files = 3
    shards = [dict() for _ in range(n_files)]
    for i, (name, x) in enumerate(dense.items()):
        shards[i % n_files][_grok_name(name)] = torch.tensor(x)
    folder = tmp_path / "grok"
    folder.mkdir()
    for i, s in enumerate(shards):
        torch.save(s, str(folder / f"pytorch_model-{i + 1:05d}-of-{n_files:05d}.bin"))

    mpath = str(tmp_path / "grok.m")
    convert_grok1(str(folder), mpath, FloatType.F32, progress=False,
                  spec=spec, n_files=n_files)

    _, tensors = read_model(mpath)
    for name, x in dense.items():
        np.testing.assert_array_equal(tensors[name].to_f32(), x, err_msg=name)

    tokens = [1, 9, 33]
    np.testing.assert_allclose(_our_logits(mpath, tokens),
                               _direct_logits(spec, dense, tokens),
                               atol=2e-5, rtol=2e-5)
