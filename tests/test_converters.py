"""Converter tests.

The HF tests are golden-oracle end-to-end: build a tiny HF model with
transformers, convert its safetensors checkpoint to `.m`, run OUR forward,
and require the logits to match HF's torch forward. This validates the whole
chain — tensor-name mapping, rotary permutation (llama) vs native layout
(mixtral), file format, params loading, and model math — against an
independent implementation (stronger than the reference's hardcoded golden
floats, SURVEY.md §4).
"""

import base64
import struct

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.converters.hf import convert_hf, permute_rotary
from distributed_llama_tpu.converters.tokenizer_llama3 import llama3_to_tokenizer_data
from distributed_llama_tpu.converters.tokenizer_spm import parse_spm_model, spm_to_tokenizer_data
from distributed_llama_tpu.io.model_file import read_model
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.quants.types import FloatType
from distributed_llama_tpu.runtime.engine import Engine


def _hf_logits(model, tokens):
    import torch

    with torch.no_grad():
        out = model(torch.tensor([tokens], dtype=torch.long))
    return out.logits[0, -1].float().numpy()


def _our_logits(mpath, tokens):
    spec, tensors = read_model(mpath)
    params = load_params(spec, tensors, mode="dense", dtype=jnp.float32)
    engine = Engine(spec, params, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    logits = engine.prefill(list(tokens))
    return np.asarray(logits)[0]


def test_hf_llama_oracle(tmp_path):
    transformers = pytest.importorskip("transformers")

    config = transformers.LlamaConfig(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, vocab_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    import torch
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(config).eval().float()
    hf_dir = str(tmp_path / "hf")
    model.save_pretrained(hf_dir, safe_serialization=True)

    mpath = str(tmp_path / "model.m")
    spec = convert_hf(hf_dir, mpath, FloatType.F32, progress=False)
    assert spec.n_kv_heads == 2

    tokens = [1, 17, 93, 5, 64, 22]
    ref = _hf_logits(model, tokens)
    got = _our_logits(mpath, tokens)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_hf_mixtral_oracle(tmp_path):
    transformers = pytest.importorskip("transformers")

    config = transformers.MixtralConfig(
        hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, vocab_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_local_experts=4, num_experts_per_tok=2, tie_word_embeddings=False)
    import torch
    torch.manual_seed(1)
    model = transformers.MixtralForCausalLM(config).eval().float()
    hf_dir = str(tmp_path / "hf")
    model.save_pretrained(hf_dir, safe_serialization=True)

    mpath = str(tmp_path / "model.m")
    spec = convert_hf(hf_dir, mpath, FloatType.F32, progress=False)
    assert spec.n_experts == 4 and spec.n_active_experts == 2

    tokens = [1, 40, 99, 3]
    ref = _hf_logits(model, tokens)
    got = _our_logits(mpath, tokens)
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_permute_rotary_roundtrip():
    """The permutation maps HF half-split rows to interleaved rows."""
    h, hs, n = 2, 8, 4
    w = np.arange(h * hs * n, dtype=np.float32).reshape(h * hs, n)
    p = permute_rotary(w, h)
    for head in range(h):
        for j in range(hs // 2):
            np.testing.assert_array_equal(p[head * hs + 2 * j], w[head * hs + j])
            np.testing.assert_array_equal(p[head * hs + 2 * j + 1],
                                          w[head * hs + hs // 2 + j])


# --- tokenizer converters --------------------------------------------------

def _encode_varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _spm_piece(piece: bytes, score: float, ptype: int | None = None) -> bytes:
    body = bytes([0x0A]) + _encode_varint(len(piece)) + piece   # field 1, wire 2
    body += bytes([0x15]) + struct.pack("<f", score)            # field 2, wire 5
    if ptype is not None:
        body += bytes([0x18]) + _encode_varint(ptype)           # field 3, wire 0
    return bytes([0x0A]) + _encode_varint(len(body)) + body     # ModelProto field 1


def test_spm_parser_and_convert(tmp_path):
    pieces = [(b"<unk>", 0.0, 2), (b"<s>", 0.0, 3), (b"</s>", 0.0, 3),
              ("▁hi".encode(), -1.5, None), (b"x", -2.0, None)]
    raw = b"".join(_spm_piece(p, s, t) for p, s, t in pieces)
    path = str(tmp_path / "tok.model")
    with open(path, "wb") as f:
        f.write(raw)

    parsed = parse_spm_model(path)
    assert [p[0] for p in parsed] == [p[0] for p in pieces]
    assert parsed[3][1] == pytest.approx(-1.5)

    data = spm_to_tokenizer_data(path)
    assert data.vocab[3] == b" hi"  # U+2581 -> space
    assert data.vocab_size == 5 and data.bos_id == 1 and data.eos_id == 2
    # bos/eos pieces rewritten to the reference exporter's display form
    # (ref: convert-tokenizer-sentencepiece.py:42-45)
    assert data.vocab[1] == b"\n<s>\n" and data.vocab[2] == b"\n</s>\n"


def test_llama3_tokenizer_convert(tmp_path):
    toks = [b"a", b"b", b"ab", b" the"]
    path = str(tmp_path / "tokenizer.model")
    with open(path, "wb") as f:
        for i, t in enumerate(toks):
            f.write(base64.b64encode(t) + b" " + str(i).encode() + b"\n")

    data = llama3_to_tokenizer_data(path)
    assert data.vocab[:4] == toks
    assert data.vocab_size == 4 + 256
    # merge priority: lower rank -> higher score; specials continue the
    # -rank sequence (reference parity)
    assert data.scores[0] > data.scores[3]
    assert data.scores[4] == -4.0
    # reference special-token table + base-model eos (<|end_of_text|>)
    assert data.vocab[data.bos_id] == b"<|begin_of_text|>"
    assert data.vocab[data.eos_id] == b"<|end_of_text|>"
    assert data.vocab[4 + 9] == b"<|eot_id|>"
    assert data.vocab[4 + 8] == b"<|reserved_special_token_4|>"
    assert data.vocab[-1] == b"<|reserved_special_token_250|>"
    # instruct override
    inst = llama3_to_tokenizer_data(path, eos_id=4 + 9)
    assert inst.vocab[inst.eos_id] == b"<|eot_id|>"
