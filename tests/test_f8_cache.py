"""fp8 (e4m3) KV cache: half the cache bytes, bounded accuracy cost.

Net-new vs the reference, whose cache is f32 only
(ref: src/transformer.cpp:161-171). The invariants: the cache really
stores 1 byte/value, every attention path accepts it (XLA decode, flash
kernel, sp-sharded), and logits stay close to the bf16-cache engine —
q and the softmax state never drop below the compute dtype (k/v upcast at
the read).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights

PROMPT = [1, 7, 3, 9, 4, 2]


def engines(mesh=None, **kw):
    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256)
    host, _ = dense_weights(spec, seed=5)
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    ref = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False, **kw)
    f8 = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                cache_dtype=jnp.float8_e4m3fn, use_pallas=False, **kw)
    return spec, ref, f8


def test_f8_cache_halves_bytes_and_tracks_reference():
    spec, ref, f8 = engines()
    assert f8.cache.k[0].dtype == jnp.float8_e4m3fn
    assert f8.cache.k[0].nbytes * 4 == ref.cache.k[0].nbytes  # 1 vs 4 bytes
    tok = np.asarray([PROMPT], np.int32)
    lr = np.asarray(ref.step(tok, 0))
    lf = np.asarray(f8.step(tok, 0))
    assert np.isfinite(lf).all()
    # prefill writes then re-reads the quantized cache; e4m3 carries ~2
    # significant digits — logits agree to coarse tolerance on O(1) values
    np.testing.assert_allclose(lf, lr, rtol=0, atol=0.15)
    # decode continues from the f8 cache
    l2 = np.asarray(f8.step(np.asarray([[5]], np.int32), len(PROMPT)))
    assert np.isfinite(l2).all()


def test_f8_cache_generation_runs():
    spec, ref, f8 = engines()
    greedy = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    out = f8.generate(PROMPT, max_tokens=6, sampler=greedy).tokens
    assert len(out) == 6 and all(0 <= t < spec.vocab_size for t in out)


def test_f8_cache_with_sp_sharded_decode():
    """The sp-sharded cache path upcasts chunks to f32 before the flash
    stats, so f8 composes with sequence parallelism."""
    spec, ref, f8 = engines(mesh=make_mesh(sp=2, tp=4))
    assert f8.cache.k[0].dtype == jnp.float8_e4m3fn
    greedy = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    out = f8.generate(PROMPT, max_tokens=4, sampler=greedy).tokens
    assert len(out) == 4


def test_f8_cache_saturates_outliers():
    """K/V outliers beyond e4m3's +-448 must saturate, not become NaN (the
    raw jax cast is non-saturating and one NaN at position p would poison
    every later attention read past p)."""
    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256)
    host, _ = dense_weights(spec, seed=5)
    # scale one layer's wk up so the projected K values overflow e4m3
    host = dict(host)
    import dataclasses

    wk = host["layers.0.wk"]
    host["layers.0.wk"] = dataclasses.replace(
        wk, data=wk.to_f32() * 4000.0)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    f8 = Engine(spec, params, compute_dtype=jnp.float32,
                cache_dtype=jnp.float8_e4m3fn, use_pallas=False)
    logits = f8.step(np.asarray([PROMPT], np.int32), 0)
    assert np.isfinite(np.asarray(logits)).all()
    assert not np.isnan(np.asarray(f8.cache.k[0]).astype(np.float32)).any()


def test_f8_cache_flash_kernel_interpret():
    """flash decode attention upcasts f8 k/v blocks in-kernel; q stays at
    compute dtype (never narrowed to the cache dtype)."""
    from distributed_llama_tpu.ops.attention import decode_attention
    from distributed_llama_tpu.ops.pallas_attention import flash_decode_attention

    rng = np.random.default_rng(3)
    b, h, kvh, s, hs = 1, 8, 4, 256, 128
    q = jnp.asarray(rng.standard_normal((b, 1, h, hs)), jnp.float32)
    k8 = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float8_e4m3fn)
    v8 = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float8_e4m3fn)
    pos = jnp.asarray([[100]], jnp.int32)
    want = decode_attention(q, k8, v8, pos)
    got = flash_decode_attention(q, k8, v8, pos, interpret=True)
    assert got.dtype == q.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=2e-2)


def test_f8_seed_guard_saturates_nan_codes():
    """saturate_f8_nan_codes (the cache-SEEDING boundary guard, ADVICE
    r5): e4m3 NaN bit patterns (0x7F/0xFF) map to the saturated max
    (+-448) — _f8_bits_to would otherwise decode them as a finite 480.0
    — and every other code passes through bit-identically."""
    from distributed_llama_tpu.ops.pallas_attention import (
        saturate_f8_nan_codes)

    codes = jnp.arange(256, dtype=jnp.uint8)
    f8 = jax.lax.bitcast_convert_type(codes, jnp.float8_e4m3fn)
    out = saturate_f8_nan_codes(f8)
    bits = np.asarray(jax.lax.bitcast_convert_type(out, jnp.uint8))
    want = np.asarray(codes).copy()
    want[0x7F] = 0x7E                  # +NaN -> +448
    want[0xFF] = 0xFE                  # -NaN -> -448
    np.testing.assert_array_equal(bits, want)
    assert not np.isnan(np.asarray(out, np.float32)).any()
    # non-f8 inputs pass through untouched (the guard is dtype-gated)
    x32 = jnp.asarray([1.0, float("nan")], jnp.float32)
    assert saturate_f8_nan_codes(x32) is x32


def test_f8_session_restore_sanitizes_nan_codes(tmp_path):
    """A session file whose f8 cache bytes carry the NaN code (a
    non-saturating foreign producer) must restore to a NaN-free cache:
    Engine.load_session runs the seed guard, so the 0x7F pattern can
    never reach the flash kernel's _f8_bits_to."""
    spec, ref, f8 = engines()
    f8.step(np.asarray([PROMPT], np.int32), 0)
    path = str(tmp_path / "sess.npz")
    f8.save_session(path, tokens=PROMPT)

    z = dict(np.load(path))
    k0 = z["k0"].copy()                # stored as raw uint8 bit patterns
    k0[..., 0] = 0x7F                  # poison: e4m3 NaN at position 0..
    k0[..., 1] = 0xFF                  # ..both signs
    z["k0"] = k0
    with open(path, "wb") as f:
        np.savez(f, **z)

    restored = Engine(spec, load_params(
        spec, dense_weights(spec, seed=5)[0], mode="q40",
        dtype=jnp.float32), compute_dtype=jnp.float32,
        cache_dtype=jnp.float8_e4m3fn, use_pallas=False)
    restored.model_fingerprint = f8.model_fingerprint
    restored.load_session(path)
    bits = np.asarray(jax.lax.bitcast_convert_type(
        restored.cache.k[0], jnp.uint8))
    assert not ((bits & 0x7F) == 0x7F).any()
    # the restored cache decodes finite everywhere a forward will read
    logits = restored.step(np.asarray([[5]], np.int32), restored.pos)
    assert np.isfinite(np.asarray(logits)).all()


def test_f8_bits_reassembly_exact_all_codes():
    """_f8_bits_to (the in-kernel e4m3->bf16/f32 reassembly that replaced
    Mosaic's slow fp8 astype — tools/exp_f8_flash.py) must agree with the
    reference astype on EVERY non-NaN e4m3 bit pattern, normals and
    subnormals, both signs. NaN codes (0x7F/0xFF) are excluded: writes
    saturate, so the cache never stores them."""
    from distributed_llama_tpu.ops.pallas_attention import _f8_bits_to

    codes = np.asarray([c for c in range(256) if c & 0x7F != 0x7F],
                       np.uint8)
    f8 = jax.lax.bitcast_convert_type(jnp.asarray(codes), jnp.float8_e4m3fn)
    for out_dtype in (jnp.float32, jnp.bfloat16):
        want = np.asarray(f8.astype(out_dtype), np.float32)
        got = np.asarray(_f8_bits_to(jnp.asarray(codes), out_dtype),
                         np.float32)
        np.testing.assert_array_equal(got, want)
