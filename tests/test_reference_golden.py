"""Replay of the reference's own golden block vectors (VERDICT r4 #5/#6).

The reference pins its block math with hard-coded expected outputs computed
from xorshift-seeded weights:

* `llama2-tasks-test.cpp:12-525` — 4096 expected floats for one
  Llama-2-7B-shaped block (dim 4096, hidden 11008, 32 heads) at pos 0,
  tolerance 1e-5;
* `grok1-tasks-test.cpp:13-15` — three 4-float ranges for one Grok-shaped
  MoE block (dim 6144, 8 experts, GELU), tolerance 3.5e-5.

Replaying those exact constants against the JAX forward is the strongest
cross-framework anchor (SURVEY §7 step 1): the weights regenerate from the
bit-exact xorshift* port (native.rng_fill_f32 — ~200M sequential draws),
the expected outputs are the reference's own test DATA
(tests/data/llama2_golden_block.npy holds the 4096 constants verbatim),
and the comparison tolerance is the reference's own.

Weight-stream layout (ref: llama2-tasks-test.cpp:555-569): the llama test
fills rmsAtt|rmsFfn FIRST (they sit at the block's tail in file order but
are drawn first), then the matmul block q,k,v,wo,w1,w2,w3, then the input
x — all as float32((float64(raw) / 120.0)). The grok test fills the whole
block in FILE order (q,k,v,wo,router,experts(up,gate,down)x8,rms x 4) at
/100.0, then x pre-divided by the embedding scale its first task
(grokMulInput) multiplies back.
"""

import os

import numpy as np
import pytest

from distributed_llama_tpu import native

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not native.available(),
        reason="native library not built (make -C native)"),
]

DATA = os.path.join(os.path.dirname(__file__), "data")


def _draw(state: int, n: int, div: float) -> tuple[int, np.ndarray]:
    """n golden-stream weights: float32(float64(xorshift f32 raw) / div) —
    C's `randomF32(&state) / div` double arithmetic narrowed on store."""
    state, raw = native.rng_fill_f32(state, n)
    return state, (raw.astype(np.float64) / div).astype(np.float32)


def _host(name, arr):
    from distributed_llama_tpu.io.model_file import FloatType, HostTensor

    return HostTensor(name, FloatType.F32, arr.shape, data=arr)


def _run_block(spec, layer_host: dict, x: np.ndarray) -> np.ndarray:
    """One _layer forward at pos 0, f32, plain XLA path — returns the
    residual stream (dim,) like the reference's task loop leaves in x."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.params import load_params
    from distributed_llama_tpu.models.transformer import KVCache, _layer

    host = dict(layer_host)
    host["tok_emb"] = _host("tok_emb", np.zeros(
        (spec.vocab_size, spec.dim), np.float32))
    host["rms_final"] = _host("rms_final", np.ones(spec.dim, np.float32))
    host["wcls"] = _host("wcls", np.zeros(
        (spec.vocab_size, spec.dim), np.float32))
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)

    cache = KVCache.create(spec, batch=1)
    cfg = dict(activation_q80=False, compute_dtype=jnp.float32,
               use_pallas=False, tp_mesh=None, tp_reduce="exact",
               pallas_interpret=False)
    q_pos = jnp.zeros((1, 1), jnp.int32)
    out, _, _ = _layer(jnp.asarray(x[None, None, :]), params["layers"][0],
                       spec, cache.k[0], cache.v[0], q_pos, cfg)
    return np.asarray(out).reshape(-1)


def test_llama2_golden_block():
    """The reference's 4096 expected floats at its own 1e-5 tolerance
    (ref: llama2-tasks-test.cpp:588-607: one block, skipLastNTasks=3 skips
    final-norm + logits, so the residual stream is compared directly)."""
    from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec

    dim, hidden = 4096, 11008
    # vocab/seq_len shrunk: they only size the (unused) embedding/logits
    # tensors and the KV cache — the block math the golden pins sees neither
    spec = ModelSpec(arch=ArchType.LLAMA, dim=dim, hidden_dim=hidden,
                     n_layers=1, n_heads=32, n_kv_heads=32, vocab_size=8,
                     seq_len=16, hidden_act=HiddenAct.SILU,
                     rope_theta=10000.0)
    assert spec.head_size == 128 and spec.kv_dim == dim

    st = 800000010
    st, rms_att = _draw(st, dim, 120.0)
    st, rms_ffn = _draw(st, dim, 120.0)
    layer = {}
    for name, shape in (("wq", (dim, dim)), ("wk", (dim, dim)),
                        ("wv", (dim, dim)), ("wo", (dim, dim)),
                        ("w1", (hidden, dim)), ("w2", (dim, hidden)),
                        ("w3", (hidden, dim))):
        st, w = _draw(st, shape[0] * shape[1], 120.0)
        layer[f"layers.0.{name}"] = _host(name, w.reshape(shape))
    layer["layers.0.rms_att"] = _host("rms_att", rms_att)
    layer["layers.0.rms_ffn"] = _host("rms_ffn", rms_ffn)
    st, x = _draw(st, dim, 120.0)

    got = _run_block(spec, layer, x)
    want = np.load(os.path.join(DATA, "llama2_golden_block.npy"))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=0)


def test_grok1_golden_block():
    """The reference's three golden ranges at its own 3.5e-5 tolerance
    (ref: grok1-tasks-test.cpp:13-15,86-88: one MoE block, skipLastNTasks=4
    skips final-norm + the two finalize tasks)."""
    from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec

    dim, hidden, n_exp = 6144, 1024, 8
    spec = ModelSpec(arch=ArchType.GROK1, dim=dim, hidden_dim=hidden,
                     n_layers=1, n_heads=48, n_kv_heads=8, vocab_size=8,
                     seq_len=16, n_experts=n_exp, n_active_experts=2,
                     hidden_act=HiddenAct.GELU, rope_theta=10000.0)
    assert spec.head_size == 128 and spec.kv_dim == 1024

    st = 123456789
    layer = {}
    for name, shape in (("wq", (dim, dim)), ("wk", (spec.kv_dim, dim)),
                        ("wv", (spec.kv_dim, dim)), ("wo", (dim, dim))):
        st, w = _draw(st, shape[0] * shape[1], 100.0)
        layer[f"layers.0.{name}"] = _host(name, w.reshape(shape))
    st, router = _draw(st, n_exp * dim, 100.0)
    layer["layers.0.moe_router"] = _host("moe_router",
                                         router.reshape(n_exp, dim))
    for e in range(n_exp):
        for name, shape in (("up", (hidden, dim)), ("gate", (hidden, dim)),
                            ("down", (dim, hidden))):
            st, w = _draw(st, shape[0] * shape[1], 100.0)
            layer[f"layers.0.experts.{e}.{name}"] = _host(
                name, w.reshape(shape))
    for name in ("rms_att", "rms_ffn", "rms_moe", "rms_ffn2"):
        st, w = _draw(st, dim, 100.0)
        layer[f"layers.0.{name}"] = _host(name, w)

    # x is stored pre-divided by the f32 embedding scale, then the block's
    # first task multiplies it back (grokMulInput — both ops in f32)
    scale = np.float32(78.38367176906169)
    st, raw = native.rng_fill_f32(st, dim)
    x_stored = ((raw.astype(np.float64) / 100.0)
                / np.float64(scale)).astype(np.float32)
    x = (x_stored * scale).astype(np.float32)

    got = _run_block(spec, layer, x)
    for lo, want in ((0, [0.00940248929, 0.0191232786, 0.0147766126,
                          0.0102868658]),
                     (256, [0.0191071425, 0.0134582901, 0.0146755828,
                            0.019181719]),
                     (5012, [0.0126675405, 0.0169415697, 0.0183475353,
                             0.0182626117])):
        np.testing.assert_allclose(got[lo:lo + 4],
                                   np.asarray(want, np.float32),
                                   atol=3.5e-5, rtol=0)
