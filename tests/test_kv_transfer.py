"""Cross-replica KV block transfer (runtime/kv_transfer.py): cache fill
on miss, prefill/decode disaggregation, and the chaos bars.

The contract under test is the ISSUE 14 acceptance set:

  * greedy outputs are BIT-IDENTICAL with transfer on vs off: the
    shipped K/V *is* a sibling prefill's writes (same executable, same
    params), so a filled-and-seeded request must emit exactly the cold
    oracle's tokens — pinned over both transports (thread-tier local
    fill and the RMSG_BLOCK_* wire path);
  * every transfer failure — donor death mid-``RMSG_BLOCK_DATA`` (a
    REAL ``SIGKILL -9`` of a stalled donor worker process, plus the
    count-deterministic ``kvx_exit`` hard-exit), a client-side
    ``recv_stall`` past the per-transfer deadline, a ``frame_truncate``
    torn frame — degrades to a plain local re-prefill with ZERO
    unstreamed request failures and the same bit-identical output;
  * the measured block-frame wire ledger reconciles EXACTLY (drift 0.0)
    with the frame-size arithmetic (``netstats.estimate_block_transfer``
    / ``multihost.frame_bytes``);
  * donor-side eviction cannot strand the router fetching dead blocks:
    a ``RMSG_BLOCK_QUERY`` miss answer clears the stale shadow entry
    (the ISSUE 14 staleness regression);
  * ``--tier prefill|decode`` routes the prompt pass to the prefill
    worker, the decode worker admits already-seeded, and the mixed path
    serves when no prefill worker is routable.

Wire tests run REAL TCP against in-process ``ReplicaServer``s (connect-
mode ``RemoteReplicaHandle``s — every frame crosses a real socket, no
subprocess spawn cost); the donor-death chaos test spawns REAL worker
subprocesses like tests/test_replica_procs.py and runs in the CI chaos
job (the main matrix ignores this file).
"""

import threading
import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime import kv_transfer as kvx
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS
from distributed_llama_tpu.runtime.profiler import COMPILES
from distributed_llama_tpu.runtime.replica_worker import (
    REPLICA_PROTOCOL_VERSION, ReplicaServer)
from distributed_llama_tpu.runtime.resilience import EngineSupervisor
from distributed_llama_tpu.runtime.router import (RemoteReplicaHandle,
                                                  Router,
                                                  ShadowPrefixIndex)
from distributed_llama_tpu.runtime.stats import KVTransferStats
from distributed_llama_tpu.sampler import Sampler

SEQ = 64
BL = 8  # block length: prompts below are a few whole blocks + remainder
SPEC_FIELDS = dict(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                   n_kv_heads=2, vocab_size=128, seq_len=SEQ)
SEED, SCALE = 3, 0.05


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, hidden_act=HiddenAct.SILU,
                     **SPEC_FIELDS)
    host = random_tensors(spec, seed=SEED, scale=SCALE)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _factory(tiny, batch=2):
    spec, params = tiny

    def make():
        return Engine(spec, params, batch=batch,
                      compute_dtype=jnp.float32, cache_dtype=jnp.float32)

    return make


def _greedy():
    return Sampler(SPEC_FIELDS["vocab_size"], temperature=0.0, topp=0.9,
                   seed=1)


def _oracle(tiny, prompt, max_tokens):
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    return eng.generate(prompt, max_tokens, _greedy()).tokens


def _sup(tiny, *, blocks=16, transfer=True, key=None):
    return EngineSupervisor(_factory(tiny), prefix_blocks=blocks,
                            prefix_block_len=BL, kv_transfer=transfer,
                            stall_timeout=60.0, fault_key=key)


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, SPEC_FIELDS["vocab_size"], n).astype(
        np.int64).tolist()


class _Cluster:
    """Two in-process ReplicaServers behind a connect-mode Router: real
    TCP, real frames, zero subprocess spawns."""

    def __init__(self, tiny, *, tiers=("mixed", "mixed"), blocks=16,
                 io_timeout=30.0, policy="round_robin",
                 kv_transfer=True):
        self.servers = [
            ReplicaServer(
                (lambda k: (lambda: _sup(tiny, blocks=blocks,
                                         key=k)))(f"r{i}"),
                kv_transfer=kv_transfer, tier=tiers[i],
                io_timeout=io_timeout)
            for i in range(2)]
        self.ports = [s.start() for s in self.servers]
        self.handles = [
            RemoteReplicaHandle(i, address=("127.0.0.1", self.ports[i]),
                                block_len=BL, poll_interval=0.1)
            for i in range(2)]
        hs = self.handles
        self.router = Router(None, policy=policy,
                             handle_factories=[lambda: hs[0],
                                               lambda: hs[1]],
                             kv_transfer=kv_transfer,
                             fill_min_tokens=BL)

    def close(self):
        self.router.close()
        for s in self.servers:
            s.shutdown()


# -- thread-tier local fill -------------------------------------------------


def test_local_fill_parity_miss_and_zero_postwarmup_compiles(tiny):
    """The in-process transport: a warm donor's blocks import into a
    cold sibling, the seeded serve emits the cold oracle's exact tokens,
    a donor that cannot help answers a MISS (no import, no failure), and
    the whole exchange (export/import warmed by PrefixCache.warmup)
    mints ZERO post-warmup compile keys."""
    sup0, sup1 = _sup(tiny, key="r0"), _sup(tiny, key="r1")
    try:
        warm_baseline = COMPILES.after_warmup
        prompt = _prompt(3 * BL + 3, seed=0)
        oracle = _oracle(tiny, prompt, 8)
        got = list(sup0.submit(prompt, 8, _greedy()).tokens(timeout=60))
        assert got == oracle

        st = KVTransferStats(enabled=True)
        ans = kvx.local_fill(sup0, sup1, prompt, stats=st)
        assert ans == 3 * BL  # the donor's whole-block answer
        assert st.fills_ok == 1 and st.tokens_filled == 3 * BL
        assert st.blocks_filled == 3 and st.fill_fallbacks == 0

        got1 = list(sup1.submit(prompt, 8, _greedy()).tokens(timeout=60))
        assert got1 == oracle, "transfer-seeded output diverged"
        pcs = sup1.prefix_cache.stats
        assert pcs.hits == 1 and pcs.tokens_saved == 3 * BL

        # a prefix neither side caches: donor answers a miss, nothing
        # imports, nothing fails
        other = _prompt(2 * BL + 1, seed=9)
        ans2 = kvx.local_fill(sup0, sup1, other, stats=st)
        assert ans2 == 0 and st.fill_misses == 1 and st.fills_ok == 1

        # donor-side pins all released (eviction-safe): every node in
        # the donor tree is unreferenced again
        def all_unpinned(node):
            return node.refs == 0 and all(
                all_unpinned(c) for c in node.children.values())
        assert all(all_unpinned(c) for c in
                   sup0.prefix_cache._root.children.values())
        assert COMPILES.after_warmup == warm_baseline, \
            "transfer minted a post-warmup compile key"
    finally:
        sup0.close()
        sup1.close()


# -- the wire path ----------------------------------------------------------


def test_wire_fill_parity_ledger_reconciles_exactly(tiny):
    """Real frames end to end: round-robin lands the repeat request on
    the cold replica, which fetches the donor's blocks over RMSG_BLOCK_*
    and emits the oracle's exact tokens. The importer's dlwire ledger
    entry for BLOCK_DATA reconciles with the frame-size arithmetic at
    drift 0.0 (both via multihost.frame_bytes and via
    netstats.estimate_block_transfer's modeled_data_bytes), and the
    donor's tree holds no leaked pins."""
    from distributed_llama_tpu.parallel.multihost import frame_bytes
    from distributed_llama_tpu.runtime.netstats import (
        estimate_block_transfer, reconcile_wire)

    spec, _ = tiny
    c = _Cluster(tiny)
    try:
        prompt = _prompt(4 * BL + 1, seed=1)
        oracle = _oracle(tiny, prompt, 8)
        r0 = c.router.submit(prompt, 8, _greedy())
        assert list(r0.tokens(timeout=60)) == oracle
        r1 = c.router.submit(prompt, 8, _greedy())
        assert list(r1.tokens(timeout=60)) == oracle, \
            "wire-filled output diverged"
        assert r1.replica_id != r0.replica_id

        tgt = c.servers[r1.replica_id].kvx_stats
        don = c.servers[r0.replica_id].kvx_stats
        assert tgt.fills_ok == 1 and tgt.tokens_filled == 4 * BL
        assert don.queries_served == 1 and don.blocks_exported == 4

        per_block = kvx.block_payload_bytes(
            spec.n_layers, spec.n_kv_heads, BL, spec.head_size,
            jnp.float32)
        measured = tgt.wire.peer_bytes(r0.replica_id, "BLOCK_DATA", "rx")
        rec = reconcile_wire(measured, 4 * frame_bytes(1, per_block))
        assert rec["drift_frac"] == 0.0, rec
        est = estimate_block_transfer(spec, tokens=4 * BL, block_len=BL,
                                      cache_bytes=4)
        assert est["modeled_data_bytes"] == measured, (est, measured)
        # donor's pins all released after the connection closed
        pc0 = c.servers[r0.replica_id].sup.prefix_cache

        def all_unpinned(node):
            return node.refs == 0 and all(
                all_unpinned(ch) for ch in node.children.values())
        assert all(all_unpinned(ch)
                   for ch in pc0._root.children.values())

        # the router aggregate + /metrics family render the record
        summ = c.router.summary()
        agg = summ["kv_transfer"]
        assert agg["enabled"] and agg["fills_ok"] == 1, agg
        from distributed_llama_tpu.runtime.trace import render_prometheus
        text = render_prometheus(summ)
        assert "dllama_kv_transfer_fills_total 1" in text
        assert "dllama_replica_kv_transfer_blocks_exported_total" in text
    finally:
        c.close()


# -- chaos: faults + donor death at the transfer sites ----------------------
#
# These spawn REAL worker subprocesses (the test_replica_procs
# discipline): the donor's codec calls then live in ANOTHER process, so
# arming the global recv_stall/frame_truncate sites here counts ONLY the
# test-side transfer calls — deterministic `after=` placement.

_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "JAX_COMPILATION_CACHE_DIR": __import__("os").path.join(
        __import__("os").path.expanduser("~"), ".cache",
        "dllama_tpu_xla"),
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1.0",
}
_WORKER_CFG = {"test_spec": SPEC_FIELDS, "seed": SEED, "scale": SCALE,
               "compute_dtype": "f32", "batch": 2,
               "prefix_cache": True, "prefix_blocks": 16,
               "prefix_block_len": BL, "kv_transfer": True,
               "serve": {"stall_timeout": 60.0}}
_SPAWN_TIMEOUT = 120.0


def _worker_proc(rid, workdir, faults=""):
    from distributed_llama_tpu.runtime.replica_worker import WorkerProc

    return WorkerProc(rid, dict(_WORKER_CFG, fault_key=f"r{rid}"),
                      workdir=str(workdir), env=_WORKER_ENV,
                      faults=faults or None)


def _spawned_donor(workdir, faults=""):
    proc = _worker_proc(0, workdir, faults)
    proc.spawn()
    try:
        port = proc.wait_ready(timeout=_SPAWN_TIMEOUT)
    except BaseException:
        proc.stop(timeout=5.0)
        raise
    return proc, port


def test_client_codec_faults_degrade_to_reprefill(tiny, tmp_path):
    """``recv_stall``/``frame_truncate`` AT THE TRANSFER SITES: a stall
    past the per-transfer deadline and a torn QUERY frame both surface
    as a degraded fill (fallback counted, no exception), and the request
    still serves bit-identically via plain re-prefill — zero unstreamed
    failures."""
    from distributed_llama_tpu.runtime.replica_worker import WorkerClient

    proc, port = _spawned_donor(tmp_path)
    sup1 = _sup(tiny, key="r1")
    try:
        prompt = _prompt(3 * BL + 2, seed=2)
        oracle = _oracle(tiny, prompt, 8)
        wc = WorkerClient("127.0.0.1", port)
        warm = wc.submit(prompt, 8, _greedy())
        assert list(warm.tokens(timeout=60)) == oracle

        st = KVTransferStats(enabled=True)
        # transfer-side recv sequence (the ONLY codec recvs in this
        # process): HELLO_ACK(1), BLOCK_ACK(2), DATA(3) -> after=2
        # stalls the first BLOCK_DATA recv; the 1 s transfer deadline
        # fires and the fill degrades
        FAULTS.arm("recv_stall", after=2, times=1, ms=5000.0)
        t0 = time.perf_counter()
        ans = kvx.fill_from_wire(
            sup1._sched, prompt, "127.0.0.1", port, 3 * BL, stats=st,
            protocol_version=REPLICA_PROTOCOL_VERSION, io_timeout=1.0,
            deadline_s=1.0)
        FAULTS.clear()
        FAULTS.release()
        assert time.perf_counter() - t0 < 10.0, "deadline did not bound"
        assert st.fill_fallbacks == 1 and st.fills_ok == 0
        # the donor ANSWERED the query before the stall: the verdict is
        # its real match (shadow stays truthful), only the data was lost
        assert ans == 3 * BL

        # transfer-side send sequence: HELLO(1), QUERY(2) -> after=1
        # tears the QUERY mid-write; the donor sees a torn frame, the
        # client an EOF — no verdict, degrade
        FAULTS.arm("frame_truncate", after=1, times=1)
        ans2 = kvx.fill_from_wire(
            sup1._sched, prompt, "127.0.0.1", port, 3 * BL, stats=st,
            protocol_version=REPLICA_PROTOCOL_VERSION, io_timeout=2.0,
            deadline_s=2.0)
        FAULTS.clear()
        assert ans2 == -1, "a torn handshake must yield NO verdict"
        assert st.fill_fallbacks == 2

        # both failures degraded: the request itself serves cold,
        # bit-identically, with zero failures
        got = list(sup1.submit(prompt, 8, _greedy()).tokens(timeout=60))
        assert got == oracle
        assert sup1._sched.stats.requests_failed == 0
    finally:
        FAULTS.clear()
        FAULTS.release()
        sup1.close()
        proc.stop(timeout=5.0)


def test_donor_hard_exit_mid_block_data_degrades(tiny, tmp_path):
    """``kvx_exit`` lands an ``os._exit`` EXACTLY between the donor's
    first and second BLOCK_DATA frames (the count-deterministic
    SIGKILL/OOM shape): the importer sees a mid-transfer EOF, degrades
    to re-prefill, and the request's greedy output stays bit-identical
    — never a request failure."""
    from distributed_llama_tpu.runtime.replica_worker import WorkerClient

    proc, port = _spawned_donor(
        tmp_path, faults="kvx_exit:after=1;times=1;key=r0")
    sup1 = _sup(tiny, key="r1")
    try:
        prompt = _prompt(3 * BL + 2, seed=4)
        oracle = _oracle(tiny, prompt, 8)
        wc = WorkerClient("127.0.0.1", port)
        assert list(wc.submit(prompt, 8,
                              _greedy()).tokens(timeout=60)) == oracle

        st = KVTransferStats(enabled=True)
        ans = kvx.fill_from_wire(
            sup1._sched, prompt, "127.0.0.1", port, 3 * BL, stats=st,
            protocol_version=REPLICA_PROTOCOL_VERSION, io_timeout=5.0,
            deadline_s=5.0)
        # the donor died between DATA #1 and #2: partial data must be
        # discarded (a half path would still be correct, but the torn
        # stream yields no import), the fill degrades
        assert st.fills_ok == 0 and st.fill_fallbacks == 1
        assert ans in (-1, 3 * BL)  # EOF may land before or after ACK
        assert time.perf_counter() and proc.poll() is not None
        from distributed_llama_tpu.runtime.replica_worker import \
            classify_exit
        assert classify_exit(proc.poll()) == "fault_exit"

        got = list(sup1.submit(prompt, 8, _greedy()).tokens(timeout=60))
        assert got == oracle
        assert sup1._sched.stats.requests_failed == 0
    finally:
        sup1.close()
        proc.stop(timeout=5.0)


def test_sigkill_mid_transfer_holds_availability_and_parity(tiny,
                                                            tmp_path):
    """THE acceptance chaos bar: a REAL ``kill -9`` of the donor worker
    while a transfer is in flight (the donor is wedged inside its
    BLOCK_DATA loop by ``kvx_stall``, so the kill provably lands
    mid-transfer). The placed replica's fill degrades to a local
    re-prefill, the request completes with greedy tokens BIT-IDENTICAL
    to the oracle, zero unstreamed failures, the service stays ready
    throughout, and the dead donor is classified + respawned."""
    import os
    import signal

    procs = [_worker_proc(0, tmp_path,
                          faults="kvx_stall:key=r0;ms=60000;times=1"),
             _worker_proc(1, tmp_path)]
    handles = [None, None]

    def build(i):
        handles[i] = RemoteReplicaHandle(
            i, proc=procs[i], block_len=BL, poll_interval=0.1,
            spawn_timeout=_SPAWN_TIMEOUT, respawn_timeout=_SPAWN_TIMEOUT,
            spawn_backoff_base=0.05)

    threads = [threading.Thread(target=build, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(h is not None for h in handles), "worker spawn failed"
    hs = handles
    router = Router(None, policy="round_robin",
                    handle_factories=[lambda: hs[0], lambda: hs[1]],
                    kv_transfer=True, fill_min_tokens=BL)
    try:
        prompt = _prompt(4 * BL + 1, seed=5)
        oracle = _oracle(tiny, prompt, 8)
        r0 = router.submit(prompt, 8, _greedy())
        assert list(r0.tokens(timeout=60)) == oracle
        donor = hs[r0.replica_id]
        donor_pid = donor._proc.pid
        assert donor_pid

        # the fill for the NEXT request wedges inside the donor's
        # BLOCK_DATA loop (kvx_stall); this timer delivers the real -9
        # while it is wedged — provably mid-transfer
        killer = threading.Timer(
            0.7, lambda: os.kill(donor_pid, signal.SIGKILL))
        killer.start()
        t0 = time.perf_counter()
        r1 = router.submit(prompt, 8, _greedy())
        toks = list(r1.tokens(timeout=120))
        killer.join()
        assert toks == oracle, "post-kill output diverged"
        assert r1.replica_id != donor.id
        # the survivor stayed routable the whole time
        assert router.ready
        # the fill degraded, the request never failed
        survivor = hs[r1.replica_id]
        summ = survivor.summary()
        tgt_kvx = summ.get("kv_transfer") or {}
        assert tgt_kvx.get("fill_fallbacks", 0) >= 1, tgt_kvx
        assert summ.get("requests_failed", 0) == 0, summ
        # the dead donor is classified and respawned to routable
        end = time.perf_counter() + 180.0
        while time.perf_counter() < end:
            if donor.proc_stats.exit_classes.get("signal:SIGKILL"):
                break
            time.sleep(0.05)
        assert donor.proc_stats.exit_classes.get("signal:SIGKILL"), \
            donor.proc_stats.exit_classes
        while time.perf_counter() < end and not donor.ready:
            time.sleep(0.05)
        assert donor.ready, "donor did not respawn to routable"
        assert time.perf_counter() - t0 < 180.0
    finally:
        router.close()


# -- shadow-index staleness (the ISSUE 14 regression) -----------------------


def test_shadow_index_unit_truncate():
    sh = ShadowPrefixIndex(block_len=BL)
    toks = list(range(4 * BL + 1))
    sh.publish(toks)
    assert sh.match_len(toks) == 4 * BL
    assert sh.truncate(toks, 2 * BL) == 2  # two stale paths dropped
    assert sh.match_len(toks) == 2 * BL
    assert sh.truncate(toks, 2 * BL) == 0  # idempotent


def test_query_miss_clears_stale_shadow_entry(tiny):
    """Donor-side eviction of a transferred path must not leave the
    router fetching dead blocks: the donor's RMSG_BLOCK_QUERY miss
    answer (echoed on the ACCEPT frame) truncates the stale shadow
    entry, so the path stops attracting fetches — and the request that
    hit the miss still serves bit-identically via re-prefill."""
    from distributed_llama_tpu.runtime.replica_worker import WorkerClient

    c = _Cluster(tiny, blocks=4)  # tiny donor arena: 4 blocks total
    try:
        fam_a = _prompt(2 * BL + 1, seed=10)
        oracle_a = _oracle(tiny, fam_a, 6)
        # request A routes to r0 (round-robin first pick) and publishes
        # its 2 blocks there; the router's shadow records the path
        ra = c.router.submit(fam_a, 6, _greedy())
        assert list(ra.tokens(timeout=60)) == oracle_a
        donor = c.handles[ra.replica_id]
        assert donor.shadow.match_len(fam_a) == 2 * BL

        # evict A donor-side BEHIND the router's back: two more 2-block
        # families through a direct WorkerClient fill the 4-block pool
        # and LRU-evict A's path (the shadow still promises it)
        wc = WorkerClient("127.0.0.1", c.ports[donor.id])
        for s in (11, 12):
            fam = _prompt(2 * BL + 1, seed=s)
            rs = wc.submit(fam, 4, _greedy())
            for _ in rs.tokens(timeout=60):
                pass
        pc = c.servers[donor.id].sup.prefix_cache
        assert pc.match_len(fam_a) == 0, "eviction setup failed"
        assert donor.shadow.match_len(fam_a) == 2 * BL  # stale!

        # request A again: round-robin places it on the OTHER replica,
        # the fill targets the (stale) donor, the donor answers a MISS,
        # the shadow truncates, and the request re-prefills bit-exactly
        rb = c.router.submit(fam_a, 6, _greedy())
        assert list(rb.tokens(timeout=60)) == oracle_a
        assert rb.replica_id != donor.id
        tgt = c.servers[rb.replica_id].kvx_stats
        assert tgt.fills_requested == 1 and tgt.fills_ok == 0
        assert tgt.fill_misses == 1
        assert donor.shadow.match_len(fam_a) == 0, \
            "stale shadow entry survived the QUERY miss answer"
        assert c.router.kvx.shadow_truncates >= 1
    finally:
        c.close()


# -- prefill/decode disaggregation ------------------------------------------


def test_disaggregated_tiers_route_fill_and_fall_back(tiny):
    """--tier prefill|decode: the prompt runs on the prefill worker
    (max_tokens=0 pass, publishes blocks), the decode worker admits
    already-seeded via a fill from that donor, output is bit-identical
    to the unified oracle; prefill-tier replicas never serve requests;
    with the prefill worker drained the mixed path serves unchanged."""
    c = _Cluster(tiny, tiers=("prefill", "decode"))
    try:
        assert c.handles[0].tier == "prefill"
        assert c.handles[1].tier == "decode"
        prompt = _prompt(3 * BL + 3, seed=3)
        oracle = _oracle(tiny, prompt, 8)
        r = c.router.submit(prompt, 8, _greedy())
        assert list(r.tokens(timeout=60)) == oracle
        assert r.replica_id == 1, "prefill-tier replica served a request"
        assert c.router.kvx.prefill_passes == 1
        tgt = c.servers[1].kvx_stats
        assert tgt.fills_ok == 1 and tgt.tokens_filled == 3 * BL
        # the decode worker prefilled ONLY the suffix
        pcs = c.servers[1].sup.prefix_cache.stats
        assert pcs.tokens_saved == 3 * BL
        assert pcs.tokens_prefilled == len(prompt) - 3 * BL

        # no prefill worker routable -> unified mixed path, no failure
        c.handles[0].draining = True
        r2 = c.router.submit(prompt, 8, _greedy())
        assert list(r2.tokens(timeout=60)) == oracle
        assert c.router.kvx.prefill_pass_fallbacks == 1
    finally:
        c.close()


# -- /stats + CLI surface ---------------------------------------------------


def test_kv_transfer_block_present_in_every_tier(tiny):
    """The family must not vanish off a launch flag: a transfer-less
    supervisor summary gains an enabled=False block at the API layer
    (render path), and a router tier's aggregate block is real."""
    from distributed_llama_tpu.runtime.trace import render_prometheus

    off = KVTransferStats().summary()
    assert off["enabled"] is False
    text = render_prometheus({"kv_transfer": off})
    assert 'dllama_kv_transfer_info' in text
    assert 'enabled="False"' in text


def test_cli_dead_flag_validation(tiny, monkeypatch):
    """--kv-transfer/--tier dead-flag discipline at parse time (the
    api_server.serve validation block), in-process for speed."""
    from distributed_llama_tpu.apps import api_server
    from distributed_llama_tpu.apps.dllama import build_argparser

    def run(argv):
        args = build_argparser().parse_args(argv)
        with pytest.raises(SystemExit) as e:
            api_server.serve(args)
        return str(e.value)

    base = ["api", "--serve-batch", "2"]
    assert "--prefix-cache" in run(base + ["--kv-transfer",
                                           "--replicas", "2"])
    assert ">= 2 replicas" in run(base + ["--kv-transfer",
                                          "--prefix-cache"])
    # a ONE-replica process tier is still sibling-less (review-found:
    # process_tier truthiness must not stand in for a real fleet count)
    assert ">= 2 replicas" in run(base + ["--kv-transfer",
                                          "--prefix-cache",
                                          "--replica-procs", "1"])
    assert "--kv-transfer" in run(base + ["--prefix-cache",
                                          "--replicas", "2",
                                          "--tier", "prefill,decode"])
    assert "at least one decode" in run(
        base + ["--prefix-cache", "--replicas", "2", "--kv-transfer",
                "--tier", "prefill"])
    assert "2 roles for 3" in run(
        base + ["--prefix-cache", "--replicas", "3", "--kv-transfer",
                "--tier", "prefill,decode"])
    assert "prefill|decode|mixed" in run(
        base + ["--prefix-cache", "--replicas", "2", "--kv-transfer",
                "--tier", "prefill,bogus"])
    assert "--replica-hosts" in run(
        ["api", "--serve-batch", "2", "--prefix-cache", "--kv-transfer",
         "--replica-hosts", "h:1,h:2", "--tier", "prefill,decode"])
