"""On-device sampling (ops/device_sampler.py + Engine.generate_device).

The reference samples on the CPU every token (ref: src/tokenizer.cpp:
231-364); the device sampler reproduces the same xorshift* coin stream and
sampling semantics inside jit. Parity is asserted token-for-token against
the host Sampler (python backend, the correctness oracle) on fixed seeds.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.ops.device_sampler import (
    coin_f32, sample_token, state_from_seed, xorshift_step,
)
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler
from distributed_llama_tpu.utils.rng import xorshift_f32, xorshift_u32

from test_model_forward import make_spec, dense_weights


def test_device_xorshift_bit_parity():
    """1000 steps of the 32-bit-limb xorshift* match the host port exactly
    (both the u32 samples and the f32 coins)."""
    state = state_from_seed(987654321012345)
    py_state = 987654321012345
    for i in range(1000):
        state, s = xorshift_step(state)
        py_state, want = xorshift_u32(py_state)
        assert int(s) == want, i
    state = state_from_seed(7)
    py_state = 7
    for i in range(100):
        state, c = coin_f32(state)
        py_state, want = xorshift_f32(py_state)
        assert float(c) == want, i


def test_sample_token_greedy_is_argmax(rng):
    logits = jnp.asarray(rng.standard_normal(512).astype(np.float32))
    tok, _ = sample_token(logits, state_from_seed(1), 0.0, 0.9)
    assert int(tok) == int(np.argmax(np.asarray(logits)))


@pytest.mark.parametrize("topp", [0.0, 0.9, 0.5])
def test_sample_token_matches_host_sampler(rng, topp):
    """200 sequential draws (evolving rng state) equal the host Sampler's
    choices on the same logits — multinomial (topp outside (0,1)) and
    nucleus modes."""
    vocab = 300
    host = Sampler(vocab, temperature=0.8, topp=topp, seed=42,
                   backend="python")
    state = state_from_seed(42)
    for i in range(200):
        logits = rng.standard_normal(vocab).astype(np.float32) * 2.0
        want = host.sample(logits)
        tok, state = sample_token(jnp.asarray(logits), state, 0.8, topp)
        assert int(tok) == want, (i, topp)
        # states stay in lock-step too
        assert int(state[0]) == host.rng_state >> 32
        assert int(state[1]) == host.rng_state & 0xFFFFFFFF


def _engine(spec, host, **kw):
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    return Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32, **kw)


def test_generate_device_matches_host_generate():
    """Full on-device sampled generation reproduces the host loop's tokens
    (same seed/temperature/topp), greedy and sampled."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=32)
    host_w, _ = dense_weights(spec, seed=21)
    prompt = [1, 5, 9]

    for temp, topp, seed in ((0.0, 0.9, 3), (0.7, 0.9, 3), (0.9, 0.0, 11)):
        eng_h = _engine(spec, host_w)
        s = Sampler(spec.vocab_size, temperature=temp, topp=topp, seed=seed,
                    backend="python")
        want = eng_h.generate(prompt, 8, s).tokens

        eng_d = _engine(spec, host_w)
        got = eng_d.generate_device(prompt, 8, temperature=temp, topp=topp,
                                    seed=seed)
        assert got == want, (temp, topp, got, want)
        assert eng_d.pos == eng_h.pos


def test_generate_device_eos_truncation_and_continuation():
    """A stop token truncates the output and rewinds pos; a continued
    session from that point matches an unbroken host run (the overrun
    cache slots must be harmlessly overwritten)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=32)
    host_w, _ = dense_weights(spec, seed=22)
    prompt = [1, 5, 9]

    # find what greedy emits, then declare its 3rd token the "eos"
    probe = _engine(spec, host_w).generate_device(
        prompt, 6, temperature=0.0, topp=0.9, seed=1)
    eos = probe[2]

    eng = _engine(spec, host_w)
    out = eng.generate_device(prompt, 6, temperature=0.0, topp=0.9, seed=1,
                              eos_id=eos)
    assert out == probe[:3] and out[-1] == eos
    # host-parity pos: the last emitted token (eos) is never written
    assert eng.pos == len(prompt) + 2
    # continue past the rewind, re-feeding from the unwritten token on —
    # must match an unbroken run's suffix (the scan's overrun cache writes
    # beyond pos must be harmlessly overwritten)
    cont = eng.generate_device([probe[2], probe[3]], 2, temperature=0.0,
                               topp=0.9, seed=1)
    full = _engine(spec, host_w).generate_device(
        prompt + probe[:4], 2, temperature=0.0, topp=0.9, seed=1)
    assert cont == full, (cont, full)


def test_cli_device_sampling_matches_host(tmp_path, capsys):
    """--device-sampling produces the same transcript as the host loop for
    the same flags (greedy, fixed seed)."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.testing import write_fixture

    mpath, tpath = write_fixture(tmp_path, seed=23)
    base = ["generate", "--model", mpath, "--tokenizer", tpath,
            "--prompt", "ab", "--steps", "5", "--seed", "7",
            "--temperature", "0.7"]
    dllama.main(base)
    want = capsys.readouterr().out.splitlines()[-1]
    dllama.main(base + ["--device-sampling"])
    got = capsys.readouterr().out.splitlines()[-1]
    assert got == want
