"""On-device sampling (ops/device_sampler.py + Engine.generate_device).

The reference samples on the CPU every token (ref: src/tokenizer.cpp:
231-364); the device sampler reproduces the same xorshift* coin stream and
sampling semantics inside jit. Parity is asserted token-for-token against
the host Sampler (python backend, the correctness oracle) on fixed seeds.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.ops.device_sampler import (
    coin_f32, sample_token, state_from_seed, xorshift_step,
)
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler
from distributed_llama_tpu.utils.rng import xorshift_f32, xorshift_u32

from test_model_forward import make_spec, dense_weights


def test_device_xorshift_bit_parity():
    """1000 steps of the 32-bit-limb xorshift* match the host port exactly
    (both the u32 samples and the f32 coins)."""
    state = state_from_seed(987654321012345)
    py_state = 987654321012345
    for i in range(1000):
        state, s = xorshift_step(state)
        py_state, want = xorshift_u32(py_state)
        assert int(s) == want, i
    state = state_from_seed(7)
    py_state = 7
    for i in range(100):
        state, c = coin_f32(state)
        py_state, want = xorshift_f32(py_state)
        assert float(c) == want, i


def test_sample_token_greedy_is_argmax(rng):
    logits = jnp.asarray(rng.standard_normal(512).astype(np.float32))
    tok, _ = sample_token(logits, state_from_seed(1), 0.0, 0.9)
    assert int(tok) == int(np.argmax(np.asarray(logits)))


@pytest.mark.parametrize("topp", [0.0, 0.9, 0.5])
def test_sample_token_matches_host_sampler(rng, topp):
    """200 sequential draws (evolving rng state) equal the host Sampler's
    choices on the same logits — multinomial (topp outside (0,1)) and
    nucleus modes."""
    vocab = 300
    host = Sampler(vocab, temperature=0.8, topp=topp, seed=42,
                   backend="python")
    state = state_from_seed(42)
    for i in range(200):
        logits = rng.standard_normal(vocab).astype(np.float32) * 2.0
        want = host.sample(logits)
        tok, state = sample_token(jnp.asarray(logits), state, 0.8, topp)
        assert int(tok) == want, (i, topp)
        # states stay in lock-step too
        assert int(state[0]) == host.rng_state >> 32
        assert int(state[1]) == host.rng_state & 0xFFFFFFFF


@pytest.mark.parametrize("shape", ["peaked", "uniform", "mixed"])
def test_sample_token_topk_window_parity_large_vocab(rng, shape):
    """The k=512 top-k fast path (active only when vocab > 1024) at vocab
    4096 (ADVICE r4): its claim is bit-exact identity with the full-argsort
    path, so compare against a second device stream with the fast path
    forced off. "peaked" logits keep the nucleus inside the window (fast
    path taken), "uniform" logits spread the nucleus over ~3.7k tokens so
    cum(topv) never exceeds topp and the lax.cond runs the full sort, and
    "mixed" alternates — token streams and rng states must stay identical
    either way. (Host-Sampler parity at this vocab is only epsilon-exact:
    the documented f32-vs-f64 CDF deviation — see the peaked host check.)"""
    import jax

    vocab = 4096
    state_fast = state_from_seed(77)
    state_full = state_from_seed(77)
    host = Sampler(vocab, temperature=1.0, topp=0.9, seed=77,
                   backend="python")
    # jit once — un-jitted sample_token re-traces per draw (~2 s each)
    fast_fn = jax.jit(lambda l, s: sample_token(l, s, 1.0, 0.9))
    full_fn = jax.jit(
        lambda l, s: sample_token(l, s, 1.0, 0.9, _force_full=True))
    host_mismatch = 0
    for i in range(40):
        if shape == "peaked" or (shape == "mixed" and i % 2 == 0):
            logits = rng.standard_normal(vocab).astype(np.float32) * 4.0
        else:
            # near-uniform: top-512 cum ≈ 512/4096 = 0.125 < topp=0.9,
            # so the window guard must reject and run the full sort
            logits = rng.standard_normal(vocab).astype(np.float32) * 0.01
        x = jnp.asarray(logits)
        tok, state_fast = fast_fn(x, state_fast)
        ref, state_full = full_fn(x, state_full)
        assert int(tok) == int(ref), (shape, i)
        assert (np.asarray(state_fast) == np.asarray(state_full)).all()
        # host stays in rng lock-step; its token may differ only with the
        # ~1% per-draw f32-epsilon odds on near-uniform distributions
        want = host.sample(logits.copy())
        host_mismatch += int(tok) != want
        assert int(state_fast[0]) == host.rng_state >> 32
        assert int(state_fast[1]) == host.rng_state & 0xFFFFFFFF
    assert host_mismatch <= 3, host_mismatch


def test_sample_token_topk_window_boundary_fallback(rng):
    """A nucleus that needs MORE than the 512-entry window but where some
    window prefix does exceed topp is impossible (cumsum is monotone), but
    the n_cand < k disjunct matters: fewer than 512 cutoff-survivors with
    tiny cum must still use the window (truncate at n_cand) — parity with
    the host on a distribution engineered for exactly that."""
    vocab = 4096
    # ~100 tokens clearly above the cutoff, the rest far below: n_cand < k
    # while cum(top 100) ≈ 1 > topp — fast path, truncation at cum > topp
    import jax

    logits = np.full(vocab, -12.0, np.float32)
    hot = rng.choice(vocab, size=100, replace=False)
    logits[hot] = rng.standard_normal(100).astype(np.float32)
    host = Sampler(vocab, temperature=0.8, topp=0.95, seed=5,
                   backend="python")
    state = state_from_seed(5)
    fn = jax.jit(lambda l, s: sample_token(l, s, 0.8, 0.95))
    x = jnp.asarray(logits)
    for i in range(20):
        want = host.sample(logits.copy())
        tok, state = fn(x, state)
        assert int(tok) == want, i


def test_topp_empty_nucleus_edge_parity():
    """topp < 1/n with near-uniform probs leaves no cutoff candidate
    (ADVICE r2): host, device (and native, when built) must all fall back
    to the argmax instead of raising / silently returning the lowest-prob
    token."""
    n = 8
    logits = np.full(n, 1.0, np.float32)
    logits[5] = 1.0 + 1e-4  # a slight argmax so the fallback is observable
    host = Sampler(n, temperature=1.0, topp=0.05, seed=9, backend="python")
    want = host.sample(logits.copy())
    assert want == 5
    tok, _ = sample_token(jnp.asarray(logits), state_from_seed(9), 1.0, 0.05)
    assert int(tok) == want
    from distributed_llama_tpu import native
    if native.available():
        nat = Sampler(n, temperature=1.0, topp=0.05, seed=9,
                      backend="native")
        assert nat.sample(logits.copy()) == want


def _engine(spec, host, **kw):
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    return Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32, **kw)


def test_generate_device_matches_host_generate():
    """Full on-device sampled generation reproduces the host loop's tokens
    (same seed/temperature/topp), greedy and sampled."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=32)
    host_w, _ = dense_weights(spec, seed=21)
    prompt = [1, 5, 9]

    for temp, topp, seed in ((0.0, 0.9, 3), (0.7, 0.9, 3), (0.9, 0.0, 11)):
        eng_h = _engine(spec, host_w)
        s = Sampler(spec.vocab_size, temperature=temp, topp=topp, seed=seed,
                    backend="python")
        want = eng_h.generate(prompt, 8, s).tokens

        eng_d = _engine(spec, host_w)
        got = eng_d.generate_device(prompt, 8, temperature=temp, topp=topp,
                                    seed=seed)
        assert got == want, (temp, topp, got, want)
        assert eng_d.pos == eng_h.pos


def test_generate_device_eos_truncation_and_continuation():
    """A stop token truncates the output and rewinds pos; a continued
    session from that point matches an unbroken host run (the overrun
    cache slots must be harmlessly overwritten)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=32)
    host_w, _ = dense_weights(spec, seed=22)
    prompt = [1, 5, 9]

    # find what greedy emits, then declare its 3rd token the "eos"
    probe = _engine(spec, host_w).generate_device(
        prompt, 6, temperature=0.0, topp=0.9, seed=1)
    eos = probe[2]

    eng = _engine(spec, host_w)
    out = eng.generate_device(prompt, 6, temperature=0.0, topp=0.9, seed=1,
                              eos_id=eos)
    assert out == probe[:3] and out[-1] == eos
    # host-parity pos: the last emitted token (eos) is never written
    assert eng.pos == len(prompt) + 2
    # continue past the rewind, re-feeding from the unwritten token on —
    # must match an unbroken run's suffix (the scan's overrun cache writes
    # beyond pos must be harmlessly overwritten)
    cont = eng.generate_device([probe[2], probe[3]], 2, temperature=0.0,
                               topp=0.9, seed=1)
    full = _engine(spec, host_w).generate_device(
        prompt + probe[:4], 2, temperature=0.0, topp=0.9, seed=1)
    assert cont == full, (cont, full)


def test_generate_device_early_exit_step_count():
    """The device loop EXITS at eos instead of burning the whole budget:
    with budget 64 and the stop token arriving 3rd, the while loop runs
    exactly 3 device iterations (2 forwards) — not 64."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=128)
    host_w, _ = dense_weights(spec, seed=22)
    prompt = [1, 5, 9]
    probe = _engine(spec, host_w).generate_device(
        prompt, 6, temperature=0.0, topp=0.9, seed=1)
    eos = probe[2]

    eng = _engine(spec, host_w)
    out = eng.generate_device(prompt, 64, temperature=0.0, topp=0.9, seed=1,
                              eos_id=eos)
    assert out == probe[:3]
    assert eng.last_device_steps == 3
    assert eng.pos == len(prompt) + 2  # 2 forwards ran


@pytest.mark.parametrize("use_mesh", [False, True])
def test_generate_batch_device_matches_independent_runs(use_mesh):
    """Batched on-device sampling (VERDICT #5): dp=4 sampled generation
    matches 4 independent generate_device runs per-row — row i owns a
    device xorshift stream seeded seed + i."""
    from jax.sharding import Mesh

    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host_w, _ = dense_weights(spec, seed=31)
    prompts = [[1, 5, 9], [2, 7], [11, 3, 4, 8], [6]]

    kw = {}
    if use_mesh:
        import jax
        from distributed_llama_tpu.parallel.mesh import make_mesh
        kw["mesh"] = make_mesh(dp=4, tp=1)

    for temp, topp, seed in ((0.0, 0.9, 3), (0.7, 0.9, 5)):
        want = []
        for i, p in enumerate(prompts):
            eng1 = _engine(spec, host_w)
            want.append(eng1.generate_device(p, 8, temperature=temp,
                                             topp=topp, seed=seed + i))
        engb = _engine(spec, host_w, batch=4, **kw)
        got = engb.generate_batch_device(prompts, 8, temperature=temp,
                                         topp=topp, seed=seed)
        assert got == want, (temp, topp)


def test_generate_batch_device_same_prompt_distinct_samples():
    """The dp serving case the per-row streams exist for: identical
    prompts at temperature > 0 must NOT produce identical rows (one
    broadcast RNG state would duplicate every continuation)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host_w, _ = dense_weights(spec, seed=31)
    eng = _engine(spec, host_w, batch=4)
    outs = eng.generate_batch_device([[1, 5, 9]] * 4, 12, temperature=0.9,
                                     topp=0.9, seed=11)
    assert len({tuple(o) for o in outs}) > 1, outs


def test_generate_batch_device_eos_per_row():
    """Per-row stop tokens: each row truncates at its own eos (included),
    and the device loop exits once all rows stopped."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host_w, _ = dense_weights(spec, seed=32)
    prompts = [[1, 5, 9], [2, 7]]

    # find each row's greedy stream, declare row 0's 2nd token the eos
    probe = _engine(spec, host_w, batch=2).generate_batch_device(
        prompts, 6, temperature=0.0, topp=0.9, seed=1)
    eos = probe[0][1]

    eng = _engine(spec, host_w, batch=2)
    got = eng.generate_batch_device(prompts, 20, temperature=0.0, topp=0.9,
                                    seed=1, eos_id=eos)
    want = []
    for i, p in enumerate(prompts):
        want.append(_engine(spec, host_w).generate_device(
            p, 20, temperature=0.0, topp=0.9, seed=1 + i, eos_id=eos))
    assert got == want
    # the loop must exit early once both rows are done, not run 20 steps
    assert eng.last_device_steps <= max(len(r) for r in got) + 1


def test_cli_device_sampling_matches_host(tmp_path, capsys):
    """--device-sampling produces the same transcript as the host loop for
    the same flags (greedy, fixed seed)."""
    from distributed_llama_tpu.apps import dllama
    from distributed_llama_tpu.testing import write_fixture

    mpath, tpath = write_fixture(tmp_path, seed=23)
    base = ["generate", "--model", mpath, "--tokenizer", tpath,
            "--prompt", "ab", "--steps", "5", "--seed", "7",
            "--temperature", "0.7"]
    dllama.main(base)
    want = capsys.readouterr().out.splitlines()[-1]
    dllama.main(base + ["--device-sampling"])
    got = capsys.readouterr().out.splitlines()[-1]
    assert got == want
