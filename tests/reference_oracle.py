"""Numpy oracle reproducing the reference engine's per-token math.

Serves the role of the reference's golden-block tests
(ref: src/llama2-tasks-test.cpp:563-582, grok1-tasks-test.cpp:86-90): an
independent implementation, following the C++ op order (serial per-head
attention, exact rope formulas, f32 throughout), that the JAX forward is
checked against. Weights are dense f32 (nSlices=1 equivalent — with one
slice, the reference's sync tasks are no-ops).
"""

from __future__ import annotations

import numpy as np

from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec

GROK_INPUT_SCALE = 78.38367176906169
GROK_LOGIT_SCALE = 0.5773502691896257


def rms_norm(x, w):
    # ref: src/funcs.cpp:94-145
    inv = 1.0 / np.sqrt((x.astype(np.float32) ** 2).mean() + 1e-5)
    return w * (inv * x)


def softmax(x):
    # ref: src/funcs.cpp:63-92
    e = np.exp(x - x.max())
    return e / e.sum()


def act(x, hidden_act):
    if hidden_act == HiddenAct.SILU:
        return x / (1.0 + np.exp(-x))
    c = 0.044715
    s = 0.79788456080286535587989211986876
    return 0.5 * x * (1.0 + np.tanh(s * x * (1.0 + c * x * x)))


def rope_llama_inplace(v, pos, head_size, theta):
    # ref: src/transformer.cpp:98-135 — adjacent pairs, freq by (i % headSize)
    for i in range(0, v.shape[0], 2):
        head_dim = i % head_size
        freq = 1.0 / (theta ** (head_dim / head_size))
        val = pos * freq
        fcr, fci = np.cos(val), np.sin(val)
        v0, v1 = v[i], v[i + 1]
        v[i] = v0 * fcr - v1 * fci
        v[i + 1] = v0 * fci + v1 * fcr


def rope_falcon_inplace(v, pos, head_size, theta):
    # ref: src/transformer.cpp:137-159 — j pairs with j + hs/2 per head
    n_heads = v.shape[0] // head_size
    for h in range(n_heads):
        for j in range(head_size // 2):
            freq = 1.0 / (theta ** (2.0 * j / head_size))
            val = pos * freq
            fcr, fci = np.cos(val), np.sin(val)
            a = v[h * head_size + j]
            b = v[h * head_size + j + head_size // 2]
            v[h * head_size + j] = a * fcr - b * fci
            v[h * head_size + j + head_size // 2] = a * fci + b * fcr


class Oracle:
    def __init__(self, spec: ModelSpec, weights: dict[str, np.ndarray]):
        self.spec = spec
        self.w = weights
        s = spec
        self.k_cache = np.zeros((s.n_layers, s.seq_len, s.kv_dim), np.float32)
        self.v_cache = np.zeros((s.n_layers, s.seq_len, s.kv_dim), np.float32)

    def _attention(self, l: int, xb: np.ndarray, pos: int) -> np.ndarray:
        s = self.spec
        w = self.w
        p = f"layers.{l}."
        q = w[p + "wq"] @ xb
        k = w[p + "wk"] @ xb
        v = w[p + "wv"] @ xb
        rope = rope_llama_inplace if s.arch == ArchType.LLAMA else rope_falcon_inplace
        # note: falcon kv head size = kvDim/nKvHeads == headSize (ref: transformer.cpp:141)
        rope(q, pos, s.head_size, s.rope_theta)
        rope(k, pos, s.head_size, s.rope_theta)
        self.k_cache[l, pos] = k
        self.v_cache[l, pos] = v

        kv_mul = s.n_heads // s.n_kv_heads
        out = np.zeros(s.dim, np.float32)
        hs = s.head_size
        for h in range(s.n_heads):  # ref: src/llama2-tasks.cpp:54-94
            qh = q[h * hs:(h + 1) * hs]
            kvh = h // kv_mul
            scores = np.array([
                np.dot(qh, self.k_cache[l, t, kvh * hs:(kvh + 1) * hs]) / np.sqrt(hs)
                for t in range(pos + 1)
            ], np.float32)
            att = softmax(scores)
            acc = np.zeros(hs, np.float32)
            for t in range(pos + 1):
                acc += att[t] * self.v_cache[l, t, kvh * hs:(kvh + 1) * hs]
            out[h * hs:(h + 1) * hs] = acc
        return self.w[p + "wo"] @ out

    def _dense_ffn(self, l: int, xb: np.ndarray) -> np.ndarray:
        s, w = self.spec, self.w
        p = f"layers.{l}."
        gate = act(w[p + "w1"] @ xb, s.hidden_act)
        up = w[p + "w3"] @ xb
        return w[p + "w2"] @ (gate * up)

    def _moe_ffn(self, l: int, xb: np.ndarray) -> np.ndarray:
        # ref: src/grok1-tasks.cpp:56-227
        s, w = self.spec, self.w
        p = f"layers.{l}."
        probs = softmax(w[p + "moe_router"] @ xb)
        order = np.argsort(-probs, kind="stable")
        idx = order[: s.n_active_experts]
        wts = probs[idx] / probs[idx].sum()
        out = np.zeros(s.dim, np.float32)
        for ae, e in enumerate(idx):
            pe = p + f"experts.{e}."
            gate = act(w[pe + "gate"] @ xb, s.hidden_act)
            up = w[pe + "up"] @ xb
            out += wts[ae] * (w[pe + "down"] @ (gate * up))
        return out

    def step(self, token: int, pos: int) -> np.ndarray:
        s, w = self.spec, self.w
        x = w["tok_emb"][token].astype(np.float32).copy()
        if s.arch == ArchType.GROK1:
            x *= GROK_INPUT_SCALE
        for l in range(s.n_layers):
            p = f"layers.{l}."
            xb = rms_norm(x, w[p + "rms_att"])
            attn = self._attention(l, xb, pos)
            if s.arch == ArchType.GROK1:
                # ref: grok1-tasks.cpp:16-41 — norm before residual add
                x = x + rms_norm(attn, w[p + "rms_ffn"])
                xb = rms_norm(x, w[p + "rms_moe"])
                moe = self._moe_ffn(l, xb)
                moe = rms_norm(moe, w[p + "rms_ffn2"])
                x = x + moe
            elif s.arch == ArchType.MIXTRAL:
                x = x + attn
                xb = rms_norm(x, w[p + "rms_ffn"])
                x = x + self._moe_ffn(l, xb)
            else:
                x = x + attn
                xb = rms_norm(x, w[p + "rms_ffn"])
                x = x + self._dense_ffn(l, xb)
        x = rms_norm(x, w["rms_final"])
        logits = w["wcls"] @ x
        if s.arch == ArchType.GROK1:
            logits = logits * GROK_LOGIT_SCALE
        return logits
