"""Vocab sharding (ops/sharded_vocab.py, ISSUE-15): tp-split embedding +
logits head with sharded sampling.

The contract under test, against the replicated full-logit ORACLE:

  * forward logits are BIT-IDENTICAL sharded vs replicated (the masked
    local gather + all-reduce adds zeros + one real contribution —
    exact in any float dtype) across tp=2/4, prefill and decode;
  * the sharded argmax equals np.argmax including the deterministic
    lowest-index tie-break, and masks at the tokenizer vocab;
  * the merged per-shard top-k candidates provably contain the global
    top-k, and candidate top-p sampling matches the host Sampler
    token-for-token on the same coin stream whenever the exactness
    guard holds — with the guard FAILING OVER to the replicated row
    fetch on flat distributions (never a wrong distribution);
  * the slot scheduler serves greedy requests BIT-IDENTICALLY sharded
    vs replicated through every path — chunked prefill, plain decode,
    the seeded-prefix-cache path, and the speculative verify/accept
    path — with ZERO post-warmup compiles under a frozen ledger;
  * the HBM ledger's `vocab` category shows the freed bytes and
    `--serve-batch auto` / `--prefix-blocks auto` actually BANK them
    (larger resolved values, not just a smaller number in a report).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.parallel.mesh import make_mesh
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.profiler import COMPILES, hbm_ledger
from distributed_llama_tpu.runtime.sampling import (draw_coin,
                                                    sample_candidates)
from distributed_llama_tpu.sampler import Sampler

SEQ = 96


def _spec(vocab=288, layers=2, seq=SEQ):
    return ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=layers, n_heads=4, n_kv_heads=2,
                     vocab_size=vocab, seq_len=seq,
                     hidden_act=HiddenAct.SILU)


@pytest.fixture(scope="module")
def tiny():
    spec = _spec()
    host = random_tensors(spec, seed=11, scale=0.5)  # peaked logits —
    # the sampled tests need a nucleus narrower than the candidate set
    return spec, load_params(spec, host, mode="dense", dtype=jnp.float32)


def _engine(tiny, tp, shard, batch=1):
    spec, params = tiny
    mesh = make_mesh(tp=tp, dp=1)
    return Engine(spec, dict(params), mesh, batch=batch,
                  compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                  shard_vocab=shard)


def _prep(eng, logits, temps, n_vocab):
    view = eng.sample_view(logits, temps, n_vocab)
    assert view.sharded
    return view


# -- forward parity ----------------------------------------------------------


@pytest.mark.parametrize("tp", [2, 4])
def test_logits_bit_identical_sharded_vs_replicated(tiny, tp):
    """The tentpole invariant: the vocab-sharded embedding gather and
    head change NOTHING numerically — prefill and decode logits are
    bit-for-bit the replicated engine's."""
    prompt = [1, 5, 7, 9, 200, 31, 287, 2]
    on = _engine(tiny, tp, True)
    off = _engine(tiny, tp, False)
    assert on.shard_vocab and not off.shard_vocab
    a = on.fetch_logits(on.prefill(prompt))
    b = off.fetch_logits(off.prefill(prompt))
    assert np.array_equal(a, b)
    for tok in (3, 250):
        a = on.fetch_logits(on.step(np.asarray([[tok]], np.int32), on.pos))
        b = off.fetch_logits(off.step(np.asarray([[tok]], np.int32),
                                      off.pos))
        assert np.array_equal(a, b)


# -- sharded argmax ----------------------------------------------------------


@pytest.mark.parametrize("tp", [2, 4])
def test_sharded_argmax_parity_and_pinned_tiebreak(tiny, tp):
    """Device argmax == np.argmax over the tokenizer vocab, with the
    tie-break rule pinned EXPLICITLY: the lowest global index among
    max-attaining tokens wins — within a shard via the local argmax's
    first-max rule, across shards because lower shards hold lower ids."""
    spec, _ = tiny
    eng = _engine(tiny, tp, True)
    v = spec.vocab_size
    rng = np.random.default_rng(0)
    rows = []
    r = rng.standard_normal(v).astype(np.float32)
    rows.append(r)
    # exact tie ACROSS shards: same max value planted in shard 0 and the
    # last shard — index 7 (shard 0) must win
    t = rng.standard_normal(v).astype(np.float32)
    t[7] = t[v - 5] = np.float32(9.5)
    rows.append(t)
    # exact tie WITHIN one shard: first occurrence wins
    w = rng.standard_normal(v).astype(np.float32)
    w[40] = w[41] = np.float32(8.25)
    rows.append(w)
    # tokenizer-vocab mask: a huge logit beyond n_vocab is ignored
    n_vocab = v - 30
    m = rng.standard_normal(v).astype(np.float32)
    m[v - 2] = np.float32(99.0)
    rows.append(m)
    lg = jnp.asarray(np.stack(rows))
    # pad the batch? sample_view takes (B, V) of any B — fine as-is
    view = _prep(eng, lg, None, n_vocab)
    for i, row in enumerate(rows):
        assert view.argmax(i, n_vocab) == int(np.argmax(row[:n_vocab]))
    assert view.argmax(1, n_vocab) == 7      # cross-shard tie: lowest id
    assert view.argmax(2, n_vocab) == 40     # in-shard tie: first max


# -- candidate top-k ---------------------------------------------------------


def test_candidates_contain_global_topk(tiny):
    """The distribution-exactness precondition, proven directly: the
    merged k·S candidate set contains the global top-k (the global i-th
    largest, i <= k, is within the top-i <= top-k of its own shard)."""
    spec, _ = tiny
    eng = _engine(tiny, 4, True)
    v, k = spec.vocab_size, eng.vocab_topk
    rng = np.random.default_rng(1)
    lg = jnp.asarray(rng.standard_normal((3, v)).astype(np.float32))
    view = _prep(eng, lg, np.full((3,), 0.8, np.float32), v)
    for i in range(3):
        top = np.argsort(-np.asarray(lg[i]), kind="stable")[:k]
        assert set(top.tolist()) <= set(view.cand_id[i].tolist())


@pytest.mark.parametrize("tp", [2, 4])
def test_topp_candidate_sampling_matches_oracle(tiny, tp):
    """Peaked logits: the guard holds, and the candidate scheme draws
    the SAME token as the host Sampler on the SAME coin stream —
    token-for-token over many seeds (the probabilities are the same
    real quantity to f32 rounding; the nucleus set and order are the
    oracle's exactly)."""
    spec, _ = tiny
    v = spec.vocab_size
    eng = _engine(tiny, tp, True)
    # robustly peaked: ~12-token nucleus spread across both shards —
    # well inside the per-shard top-k, so the guard provably holds
    rng = np.random.default_rng(3)
    row = (rng.standard_normal(v) * 0.5).astype(np.float32)
    for j, gid in enumerate((3, 17, 150, 160, 201, 44, 260, 9, 99, 180)):
        row[gid] += np.float32(6.0 - 0.2 * j)
    lg_dev = jnp.asarray(row[None, :])
    view = _prep(eng, lg_dev, np.asarray([0.8], np.float32), v)
    agree = 0
    for seed in range(200):
        s_sh = Sampler(v, 0.8, 0.9, seed=seed, backend="python")
        s_or = Sampler(v, 0.8, 0.9, seed=seed, backend="python")
        t_sh = view.sample(s_sh, 0)
        t_or = s_or.sample(row)
        assert t_sh == t_or, (seed, t_sh, t_or)
        agree += 1
    assert agree == 200
    assert eng.vocab_sample_stats["fallback"] == 0  # guard held — the
    # fast path served every draw


def test_flat_distribution_falls_back_exactly(tiny):
    """FLAT logits (high temperature): the nucleus outgrows the
    candidates, the guard refuses, and the view serves the draw from
    the replicated row fetch — still the oracle's exact token on the
    same coin (sample_candidates itself returns None, never a wrong
    distribution)."""
    spec, _ = tiny
    v = spec.vocab_size
    eng = _engine(tiny, 2, True)
    rng = np.random.default_rng(5)
    flat = rng.standard_normal((1, v)).astype(np.float32) * 0.01
    lg = jnp.asarray(flat)
    view = _prep(eng, lg, np.asarray([5.0], np.float32), v)
    # the raw candidate scheme must refuse (guard fails on a ~full-vocab
    # nucleus at k*S << nucleus size)
    s_probe = Sampler(v, 5.0, 0.97, seed=1, backend="python")
    assert sample_candidates(s_probe, view.cand_p[0], view.cand_id[0],
                             view.guard[0], int(view.amax[0])) is None
    for seed in range(20):
        s_sh = Sampler(v, 5.0, 0.97, seed=seed, backend="python")
        s_or = Sampler(v, 5.0, 0.97, seed=seed, backend="python")
        assert view.sample(s_sh, 0) == s_or.sample(flat[0])
    assert eng.vocab_sample_stats["fallback"] >= 20


def test_pure_multinomial_and_foreign_vocab_fall_back_exactly(tiny):
    """topp >= 1 (full multinomial) and a sampler truncating at a
    DIFFERENT vocab both take the per-row oracle fallback — exact
    parity with the host Sampler on the full row, same coins."""
    spec, _ = tiny
    v = spec.vocab_size
    eng = _engine(tiny, 2, True)
    rng = np.random.default_rng(9)
    row = rng.standard_normal(v).astype(np.float32)
    lg = jnp.asarray(row[None, :])
    view = _prep(eng, lg, np.asarray([0.8], np.float32), v)
    s_sh = Sampler(v, 0.8, 1.0, seed=3, backend="python")   # topp >= 1
    s_or = Sampler(v, 0.8, 1.0, seed=3, backend="python")
    assert view.sample(s_sh, 0) == s_or.sample(row)
    s2_sh = Sampler(200, 0.8, 0.9, seed=4, backend="python")  # vocab 200
    s2_or = Sampler(200, 0.8, 0.9, seed=4, backend="python")
    assert view.sample(s2_sh, 0) == s2_or.sample(row)
    assert view.argmax(0, 200) == int(np.argmax(row[:200]))


def test_draw_coin_matches_sampler_stream(tiny):
    """draw_coin consumes exactly the sampler's next xorshift uniform —
    the candidate path's one coin is the oracle's one coin."""
    a = Sampler(288, 0.8, 0.9, seed=77, backend="python")
    b = Sampler(288, 0.8, 0.9, seed=77, backend="python")
    c1 = draw_coin(a)
    c2 = b._coin()
    assert c1 == c2 and a.rng_state == b.rng_state


# -- serving paths -----------------------------------------------------------


def _serve(tiny, shard, temps, *, draft=True, prefix=True, freeze=False):
    from distributed_llama_tpu.runtime.prefix_cache import PrefixCache
    from distributed_llama_tpu.runtime.scheduler import Scheduler

    spec, _ = tiny
    eng = _engine(tiny, 2, shard, batch=2)
    pc = PrefixCache(eng, num_blocks=16, block_len=8) if prefix else None
    draft_factory = None
    if draft:
        from distributed_llama_tpu.runtime.draft import build_draft

        draft_factory = lambda e: build_draft(e, "self:1")  # noqa: E731
    sched = Scheduler(eng, chunk=16, prefix_cache=pc,
                      draft_factory=draft_factory,
                      draft_len=4 if draft else 0,
                      draft_vocab=spec.vocab_size)
    sched.warmup()
    frozen_before = COMPILES.after_warmup
    if freeze:
        COMPILES.freeze = True
    try:
        sys_prefix = list(range(40, 72))  # shared prefix: seeds the
        # radix cache for later requests (the seeded-prefix-cache path)
        prompts = [sys_prefix + [5 + i, 9, 3 + i] for i in range(6)]
        reqs = []
        for i, p in enumerate(prompts):
            smp = Sampler(spec.vocab_size, temps[i % len(temps)], 0.9,
                          seed=1000 + i, backend="python")
            reqs.append(sched.submit(p, 10, smp))
        while sched.has_work():
            sched.step()
        outs = [list(r.tokens()) for r in reqs]
        frozen_delta = COMPILES.after_warmup - frozen_before
    finally:
        COMPILES.freeze = False
        sched.close()
    return outs, frozen_delta, dict(eng.vocab_sample_stats)


def test_scheduler_greedy_bit_identical_all_paths(tiny):
    """Greedy serving through the slot scheduler — chunked prefill,
    decode, the SEEDED-prefix-cache path (requests 2+ hit the shared
    prefix), and the speculative verify/accept path (self-draft armed)
    — emits BIT-IDENTICAL tokens sharded vs replicated, and the sharded
    run mints ZERO post-warmup compiles with the ledger FROZEN."""
    a, frozen, stats = _serve(tiny, True, [0.0], freeze=True)
    b, _, _ = _serve(tiny, False, [0.0])
    assert a == b
    assert frozen == 0
    assert stats.get("fallback", 0) == 0 and stats.get("sharded", 0) > 0


def test_scheduler_mixed_sampled_rows_deterministic(tiny):
    """Mixed greedy/sampled traffic: greedy rows stay bit-identical to
    the replicated engine; sampled rows are DETERMINISTIC across two
    sharded runs (fixed seeds — the candidate path consumes the same
    coins) and come from the candidate scheme, not the fallback."""
    a, frozen, stats = _serve(tiny, True, [0.0, 0.8], freeze=True)
    a2, _, _ = _serve(tiny, True, [0.0, 0.8])
    b, _, _ = _serve(tiny, False, [0.0, 0.8])
    assert a == a2                       # sampled determinism
    assert frozen == 0
    for i in range(0, 6, 2):
        assert a[i] == b[i]              # greedy rows: exact parity
    assert stats.get("sharded", 0) > 0


def test_generate_batch_stream_parity(tiny):
    """The batch-generate serving entry point: greedy batch rows are
    bit-identical sharded vs replicated (device argmax == np.argmax per
    row, same stop semantics)."""
    spec, _ = tiny
    prompts = [[1, 5, 9], [7, 2, 200, 31], [287, 3, 4]]

    def run(shard):
        eng = _engine(tiny, 2, shard, batch=3)
        smp = Sampler(spec.vocab_size, 0.0, 0.9, seed=5,
                      backend="python")
        return eng.generate_batch(prompts, 8, smp)

    assert run(True) == run(False)


def test_supervisor_tier_serves_on_tp_mesh(tiny):
    """The CLI-reachable path (PR-15 review finding): `dllama api
    --serve-batch N --tp T` builds the single-supervisor tier over the
    LAUNCHED mesh engine — build_front_door's engine factory must carry
    the mesh and the template's resolved shard_vocab decision through
    (rebuilds included), and the warmed sharded-sampling executables
    must serve greedy requests bit-identically to a replicated
    supervisor."""
    from distributed_llama_tpu.runtime.router import build_front_door

    spec, _ = tiny

    def run(shard):
        template = _engine(tiny, 2, shard, batch=1)
        sup = build_front_door(template, serve_batch=2, serve_chunk=16,
                               stall_timeout=60.0)
        try:
            eng = sup.engine
            assert eng.shard_vocab is shard  # the template's RESOLVED
            assert eng.mesh is template.mesh  # decision + mesh carried
            reqs = [sup.submit([1 + i, 5, 9], 8,
                               Sampler(spec.vocab_size, 0.0, 0.9,
                                       seed=50 + i, backend="python"))
                    for i in range(3)]
            return [list(r.tokens()) for r in reqs]
        finally:
            sup.close()

    assert run(True) == run(False)


# -- HBM ledger + auto-sizing ------------------------------------------------


def test_vocab_category_and_headroom_banked():
    """The freed bytes are REAL and BANKED: the ledger's `vocab`
    category shrinks under sharding (embedding per-chip = 1/tp), and
    `--serve-batch auto` / `--prefix-blocks auto` resolve to LARGER
    values for the sharded engine under the same byte budget."""
    from distributed_llama_tpu.runtime.profiler import resolve_auto_shape

    spec = _spec(vocab=2048, seq=64)
    host = random_tensors(spec, seed=2, scale=0.1)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    mesh = make_mesh(tp=2, dp=1)
    on = Engine(spec, dict(params), mesh, batch=1,
                compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                shard_vocab=True)
    off = Engine(spec, dict(params), mesh, batch=1,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 shard_vocab=False)
    led_on = hbm_ledger(on, device_stats=False)
    led_off = hbm_ledger(off, device_stats=False)
    emb = spec.vocab_size * spec.dim * 4
    # off: full embedding + the (already row-split) head's half;
    # on: both halved — the embedding shard is exactly 1/tp
    assert led_off["vocab_bytes"] == emb + emb // 2
    assert led_on["vocab_bytes"] == emb // 2 + emb // 2
    assert led_on["weights_bytes"] == led_off["weights_bytes"]

    # bank the freed bytes: same byte budget, larger resolved shapes.
    # {"bytes_limit": L} without in_use -> the ledger models in_use as
    # its accounted bytes, so the sharded engine's smaller footprint IS
    # the headroom difference
    budget = led_off["accounted_bytes"] + 4 * led_off["per_slot_bytes"]
    dec_on = resolve_auto_shape(on, serve_batch="auto",
                                prefix_blocks="auto", prefix_block_len=8,
                                device_stats={"bytes_limit": budget})
    dec_off = resolve_auto_shape(off, serve_batch="auto",
                                 prefix_blocks="auto", prefix_block_len=8,
                                 device_stats={"bytes_limit": budget})
    assert dec_on["serve_batch"] > dec_off["serve_batch"]
    assert dec_on["prefix_blocks"] > dec_off["prefix_blocks"]


def test_shard_vocab_refuses_indivisible_mesh():
    """Explicit shard_vocab=True with a mesh that cannot split the
    vocab is a clear construction error (the dead-flag discipline)."""
    spec = _spec(vocab=289)  # prime-ish: not divisible by 2
    host = random_tensors(spec, seed=2, scale=0.1)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    mesh = make_mesh(tp=2, dp=1)
    with pytest.raises(AssertionError, match="shard_vocab"):
        Engine(spec, params, mesh, compute_dtype=jnp.float32,
               cache_dtype=jnp.float32, shard_vocab=True)
    # auto on a tp-less mesh simply stays off (dp-only: nothing to
    # split over — the replicated oracle serves)
    spec2 = _spec()
    host2 = random_tensors(spec2, seed=2, scale=0.1)
    params2 = load_params(spec2, host2, mode="dense", dtype=jnp.float32)
    eng = Engine(spec2, params2, make_mesh(tp=1, dp=2), batch=2,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32)
    assert not eng.shard_vocab
