"""Continuous-batching scheduler parity (runtime/scheduler.py).

The contract under test: greedy continuous-batching output for N staggered
requests is TOKEN-IDENTICAL to N sequential Engine.generate runs — through
mid-decode joins, early finishes that hand a slot to a queued request, and
chunked prefill with padded tail chunks. f32 on the CPU mesh so the
batched scatter-write paths compare bit-exactly against the single-row
oracle (same discipline as tests/test_apps.py's batch fixtures).
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.scheduler import PromptTooLong, Scheduler
from distributed_llama_tpu.sampler import Sampler

SEQ = 64


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


def _oracle(spec, params, prompt, max_tokens, eos_id=None):
    """Sequential single-row reference: a fresh batch=1 Engine.generate."""
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    r = eng.generate(prompt, max_tokens,
                     Sampler(spec.vocab_size, temperature=0.0, topp=0.9,
                             seed=1), eos_id=eos_id)
    return r.tokens


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _drain(req):
    return list(req.tokens(timeout=5.0))


def _run_until_done(sched, reqs, limit=500):
    for _ in range(limit):
        if all(r.finished.is_set() for r in reqs):
            return
        sched.step()
    raise AssertionError("scheduler did not drain within the step limit")


def test_parity_staggered_joins_and_slot_reuse(tiny):
    """Three requests through a 2-slot scheduler: r1 joins mid-decode of
    r0, r2 queues until r1's early finish frees its slot — every output
    must equal the sequential oracle."""
    spec, params = tiny
    eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=4)

    p0 = [1, 9, 23, 54, 7, 88, 101, 5, 61, 17, 3]   # 3 padded chunks
    p1 = [2, 40, 77, 12, 9]
    p2 = [5, 66, 31, 90, 14, 8, 55]

    r0 = sched.submit(p0, 10, _greedy(spec))
    for _ in range(5):  # r0 prefills (3 chunks) and starts decoding
        sched.step()
    assert not r0.finished.is_set()

    r1 = sched.submit(p1, 4, _greedy(spec))   # joins mid-decode of r0
    r2 = sched.submit(p2, 6, _greedy(spec))   # queued: both slots busy
    _run_until_done(sched, [r0, r1, r2])

    assert _drain(r0) == _oracle(spec, params, p0, 10)
    assert _drain(r1) == _oracle(spec, params, p1, 4)
    assert _drain(r2) == _oracle(spec, params, p2, 6)
    assert r0.finish_reason == r1.finish_reason == r2.finish_reason == "length"
    # the batch never overflowed its slots and r2 really waited in queue
    assert max(sched.stats.occupancy) <= 2
    assert max(sched.stats.queue_depth) >= 1
    s = sched.stats.summary()
    assert s["requests_finished"] == 3
    assert s["tokens_out"] == 20
    assert s["ttft_p50_ms"] is not None and s["ttft_p50_ms"] >= 0


def test_parity_eos_early_finish(tiny):
    """A request whose greedy stream hits its stop token finishes early
    (stop token INCLUDED — Engine.generate parity) and frees the slot to
    a queued request whose output stays oracle-identical."""
    spec, params = tiny
    p0 = [1, 9, 23, 54, 7]
    p1 = [2, 40, 77, 12, 9, 31]
    base = _oracle(spec, params, p0, 8)
    eos = base[2]  # force an early stop three tokens in
    want0 = _oracle(spec, params, p0, 8, eos_id=eos)
    assert want0 == base[:3] and want0[-1] == eos

    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8)  # batch=1: p1 MUST wait for p0's slot
    r0 = sched.submit(p0, 8, _greedy(spec), eos_id=eos)
    r1 = sched.submit(p1, 5, _greedy(spec))
    _run_until_done(sched, [r0, r1])

    assert _drain(r0) == want0
    assert r0.finish_reason == "stop"
    assert _drain(r1) == _oracle(spec, params, p1, 5)
    assert max(sched.stats.occupancy) == 1


def test_prompt_too_long_and_empty_rejected(tiny):
    spec, params = tiny
    eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng)
    with pytest.raises(PromptTooLong):
        sched.submit(list(range(1, SEQ + 1)), 4, _greedy(spec))
    with pytest.raises(ValueError):
        sched.submit([], 4, _greedy(spec))
    assert not sched.has_work()


def test_budget_zero_prefills_and_emits_nothing(tiny):
    """max_tokens <= 0: prefill runs, nothing is emitted — the same
    hard-cap contract as Engine.generate."""
    spec, params = tiny
    eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=4)
    r = sched.submit([1, 9, 23], 0, _greedy(spec))
    _run_until_done(sched, [r])
    assert _drain(r) == []
    assert r.finish_reason == "length"


def test_threaded_loop_and_cancellation(tiny):
    """The background thread drains submissions; cancel() retires a
    request mid-stream and frees its slot to the next one."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8)
    sched.start()
    try:
        r0 = sched.submit([1, 9, 23, 54], 30, _greedy(spec))
        it = r0.tokens(timeout=60.0)
        got = [next(it), next(it)]
        r0.cancel()
        rest = list(it)
        assert got + rest == _oracle(spec, params, [1, 9, 23, 54], 30)[
            : len(got) + len(rest)]
        assert r0.finished.wait(60.0)
        assert r0.finish_reason == "cancelled"
        # the freed slot serves the next request with full parity
        r1 = sched.submit([2, 40, 77], 4, _greedy(spec))
        assert r1.finished.wait(60.0)
        assert _drain(r1) == _oracle(spec, params, [2, 40, 77], 4)
    finally:
        sched.close()


def test_exclusive_drains_then_lends_engine(tiny):
    """exclusive() finishes all in-flight work, then the borrower owns the
    engine (the legacy batch endpoint's path to the single live batched
    cache)."""
    spec, params = tiny
    eng = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8)
    r = sched.submit([1, 9, 23], 3, _greedy(spec))
    with sched.exclusive() as borrowed:
        assert borrowed is eng
        assert r.finished.is_set()
        borrowed.reset()  # all slots free: a reset cannot hurt anyone
    assert _drain(r) == _oracle(spec, params, [1, 9, 23], 3)
