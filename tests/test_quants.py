"""Quantization codec tests.

Mirrors the reference test strategy (ref: src/quants-test.cpp:7-52 checks the
Q80 round-trip error bound across several lengths) and adds Q40 round-trip,
byte-layout, and host/device codec equivalence checks the reference lacks.
"""

import numpy as np
import pytest

from distributed_llama_tpu.quants import (
    FloatType,
    batch_bytes,
    dequantize_q40,
    dequantize_q40_jax,
    dequantize_q80,
    dequantize_q80_jax,
    q40_arrays_to_bytes,
    q40_bytes_to_arrays,
    q80_arrays_to_bytes,
    q80_bytes_to_arrays,
    quantize_q40,
    quantize_q80,
    quantize_q80_jax,
    QuantizedTensor,
)
from distributed_llama_tpu.utils import XorshiftRng


def test_batch_bytes():
    # ref: src/quants.cpp:26-47 — 18 B and 34 B per 32-value block
    assert batch_bytes(FloatType.F32, 64, 3) == 64 * 3 * 4
    assert batch_bytes(FloatType.F16, 64, 3) == 64 * 3 * 2
    assert batch_bytes(FloatType.Q40, 64, 3) == 2 * 3 * 18
    assert batch_bytes(FloatType.Q80, 64, 3) == 2 * 3 * 34


@pytest.mark.parametrize("n", [1024, 768, 2752])
def test_q80_roundtrip_error(n):
    # reference checks an absolute error bound on values in [-1.2, 0.8)
    # (ref: src/quants-test.cpp:14-39); we assert the principled per-block
    # bound: half the int8 step plus the f16 scale-rounding contribution.
    rng = XorshiftRng(seed=100000 + n)
    x = rng.random_f32_array(n, scale=2.0, offset=-1.2)
    scales, q = quantize_q80(x)
    y = dequantize_q80(scales, q)
    step = np.abs(x.reshape(-1, 32)).max(axis=-1) / 127.0
    bound = step * 0.5 + step * 127 * 2.0**-11  # f16 has 10+1 mantissa bits
    err = np.abs((x - y).reshape(-1, 32))
    assert (err <= bound[:, None] + 1e-7).all()


@pytest.mark.parametrize("n", [32, 256, 4096])
def test_q40_roundtrip_error(n, rng):
    x = rng.standard_normal(n).astype(np.float32)
    scales, packed = quantize_q40(x)
    y = dequantize_q40(scales, packed)
    # 4-bit: scale = absmax/8; truncation gives 0.5*scale error but the
    # asymmetric +8.5/clamp-15 encode loses up to 1.5*scale at the extreme
    # opposite the max-magnitude value (converter/writer.py:37-38)
    blocks = x.reshape(-1, 32)
    bound = np.abs(blocks).max(axis=-1) * (1.5 / 8.0)
    err = np.abs((x - y).reshape(-1, 32))
    assert (err <= bound[:, None] + 1e-5).all()


def test_q40_bytes_layout(rng):
    """File bytes: f16 scale then 16 nibble bytes; lo nibble = element j,
    hi nibble = element j+16 (ref: src/quants.hpp:16-19, quants.cpp:166-179)."""
    x = rng.standard_normal(64).astype(np.float32)
    scales, packed = quantize_q40(x)
    buf = q40_arrays_to_bytes(scales, packed)
    assert len(buf) == batch_bytes(FloatType.Q40, 64, 1)
    s2, p2 = q40_bytes_to_arrays(buf, 64)
    assert np.array_equal(s2.view(np.uint16), scales.view(np.uint16))
    assert np.array_equal(p2, packed)
    # manual decode of block 0, element 0 and 16
    import struct

    d0 = np.frombuffer(buf[:2], dtype=np.float16)[0]
    b0 = buf[2]
    assert np.isclose(dequantize_q40(s2, p2)[0], ((b0 & 0xF) - 8) * np.float32(d0))
    assert np.isclose(dequantize_q40(s2, p2)[16], ((b0 >> 4) - 8) * np.float32(d0))


def test_q80_bytes_roundtrip(rng):
    x = rng.standard_normal(96).astype(np.float32)
    scales, q = quantize_q80(x)
    buf = q80_arrays_to_bytes(scales, q)
    assert len(buf) == batch_bytes(FloatType.Q80, 96, 1)
    s2, q2 = q80_bytes_to_arrays(buf, 96)
    assert np.array_equal(s2.view(np.uint16), scales.view(np.uint16))
    assert np.array_equal(q2, q)


def test_q40_jax_matches_numpy(rng):
    x = rng.standard_normal((4, 128)).astype(np.float32)
    scales, packed = quantize_q40(x)
    qt = QuantizedTensor.from_numpy(scales, packed)
    assert qt.shape == (4, 128)
    dev = np.asarray(dequantize_q40_jax(qt, dtype=np.float32))
    host = dequantize_q40(scales, packed)
    np.testing.assert_allclose(dev, host, rtol=0, atol=1e-6)


def test_q80_jax_roundtrip(rng):
    x = rng.standard_normal((2, 256)).astype(np.float32)
    q, scales = quantize_q80_jax(x)
    y = np.asarray(dequantize_q80_jax(q, scales))
    step = np.abs(x.reshape(-1, 32)).max(axis=-1) / 127.0
    err = np.abs((x - y).reshape(-1, 32))
    assert (err <= step[:, None] * (0.5 + 127 * 2.0**-11) + 1e-7).all()
    # device quantization matches host quantization up to rounding ties
    s_host, q_host = quantize_q80(x)
    diff = np.abs(np.asarray(q).reshape(q_host.shape).astype(np.int32) - q_host.astype(np.int32))
    assert diff.max() <= 1
    assert (diff > 0).mean() < 0.01


def test_xorshift_parity():
    """First few draws of the reference RNG for seed 123456789.

    Derived from the xorshift* recurrence the reference uses
    (ref: src/utils.cpp:53-64); fixed here as a regression anchor.
    """
    rng = XorshiftRng(123456789)
    vals = [rng.u32() for _ in range(4)]
    # recompute independently
    state = 123456789
    expect = []
    for _ in range(4):
        state ^= state >> 12
        state = (state ^ (state << 25)) & ((1 << 64) - 1)
        state ^= state >> 27
        expect.append(((state * 0x2545F4914F6CDD1D) & ((1 << 64) - 1)) >> 32)
    assert vals == expect
