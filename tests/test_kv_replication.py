"""tp > n_kv_heads via kv-head replication — the relaxed form of the
reference's hard `nSlices <= nKvHeads` constraint (ref:
src/transformer.cpp:254-257; SURVEY.md §7 step 4 planned the relaxation the
reference could not do). wk/wv expand to tp virtual heads
(models/params.kv_replication); the sharded engine must reproduce the
single-device tokens bit-for-bit on every execution path.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import (
    kv_replication, load_params, replicate_kv_heads,
)
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights

PROMPT = [1, 9, 4, 2]


def _gqa_spec(arch=ArchType.LLAMA):
    # 8 query heads sharing 2 kv heads: tp=4 and tp=8 both exceed kv heads
    return make_spec(arch, dim=256, n_heads=8, n_kv_heads=2, hidden_dim=512)


def _greedy(engine, n=5):
    s = Sampler(engine.spec.vocab_size, temperature=0.0, topp=0.9, seed=3)
    return engine.generate(PROMPT, n, s).tokens


@pytest.mark.parametrize("tp", [4, 8])
@pytest.mark.parametrize("mode", ["dense", "q40"])
def test_tp_beyond_kv_heads_matches_single(tp, mode):
    spec = _gqa_spec()
    host, _ = dense_weights(spec, seed=11)
    # separate loads: the tp=1 baseline engine fuses (and mutates) its pytree
    want = _greedy(Engine(spec, load_params(spec, host, mode=mode,
                                            dtype=jnp.float32),
                          compute_dtype=jnp.float32, cache_dtype=jnp.float32))

    params = load_params(spec, host, mode=mode, dtype=jnp.float32)
    eng = Engine(spec, params, make_mesh(tp=tp),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32)
    # engine computes with tp virtual kv heads; cache shards one per device
    assert eng.spec.n_kv_heads == tp
    assert eng.cache.k[0].shape[1] == tp
    assert eng.cache.k[0].sharding.shard_shape(eng.cache.k[0].shape)[1] == 1
    assert _greedy(eng) == want


def test_kv_replication_pallas_and_q80_paths():
    """The shard_map kernel path (interpret) and the q80-collective path
    agree with the single-device run under kv replication."""
    spec = _gqa_spec()
    host, _ = dense_weights(spec, seed=12)
    want = _greedy(Engine(spec, load_params(spec, host, mode="q40",
                                            dtype=jnp.float32),
                          compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                          use_pallas=False))
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)

    mesh = make_mesh(tp=4)
    got_pl = _greedy(Engine(spec, params, mesh, compute_dtype=jnp.float32,
                            cache_dtype=jnp.float32, use_pallas=True,
                            pallas_interpret=True))
    assert got_pl == want

    eng_q80 = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32, activation_q80=True,
                     q80_collectives=True)
    logits = eng_q80.step(np.asarray([PROMPT], np.int32), 0)
    assert np.isfinite(np.asarray(logits)).all()


def test_streamed_loader_replicates_host_side(tmp_path):
    """load_params_streamed places replicated wk/wv shards directly; the
    result must match the engine-side (device) replication path."""
    from distributed_llama_tpu.io.model_file import write_model
    from distributed_llama_tpu.models.loader import load_params_streamed
    from distributed_llama_tpu.quants.types import FloatType

    spec = _gqa_spec()
    host, _ = dense_weights(spec, seed=13)
    q40_spec = dataclasses.replace(spec, weights_float_type=FloatType.Q40)
    mpath = str(tmp_path / "m.m")
    write_model(mpath, q40_spec, {n: t.to_f32() for n, t in host.items()})

    mesh = make_mesh(tp=4)
    params_s, _ = load_params_streamed(q40_spec, mpath, mesh, mode="q40",
                                       dtype=jnp.float32)
    eng_s = Engine(spec, params_s, mesh, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32, use_pallas=False)
    wk = eng_s.params["layers"][0]["wk"]
    from distributed_llama_tpu.parallel.wrappers import WeightWrapper
    pk = (wk.w if isinstance(wk, WeightWrapper) else wk).packed
    assert pk.shape[0] == 4 * spec.head_size  # tp virtual heads worth of rows

    bulk = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng_b = Engine(spec, bulk, mesh, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32, use_pallas=False)
    assert _greedy(eng_s) == _greedy(eng_b)


def test_kv_replication_composes_with_sp():
    """tp=4 (over 2 kv heads) x sp=2: ring prefill + sp-sharded-cache decode
    with virtual kv heads must match the single-device tokens."""
    spec = _gqa_spec()
    host, _ = dense_weights(spec, seed=15)
    want = _greedy(Engine(spec, load_params(spec, host, mode="dense",
                                            dtype=jnp.float32),
                          compute_dtype=jnp.float32, cache_dtype=jnp.float32))
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    eng = Engine(spec, params, make_mesh(tp=4, sp=2),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32)
    assert eng.cache.k[0].shape[1] == 4  # virtual heads, sp-sharded seq dim
    assert _greedy(eng) == want


def test_kv_replication_composes_with_dp():
    """tp=4 x dp=2 batched generation under kv replication: each row matches
    the single-device greedy run."""
    spec = _gqa_spec()
    host, _ = dense_weights(spec, seed=16)
    want = _greedy(Engine(spec, load_params(spec, host, mode="q40",
                                            dtype=jnp.float32),
                          compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                          use_pallas=False), n=4)
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    eng = Engine(spec, params, make_mesh(tp=4, dp=2), batch=2,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    s = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=3)
    outs = eng.generate_batch([PROMPT, PROMPT], 4, s)
    assert outs[0] == want and outs[1] == want, (outs, want)


def test_kv_replication_validation():
    spec = _gqa_spec()
    assert kv_replication(spec, 4) == 2
    with pytest.raises(AssertionError):  # tp must be a multiple of kv heads
        kv_replication(spec, 3)
    with pytest.raises(AssertionError):  # tp cannot exceed query heads
        kv_replication(spec, 16)


def test_replicate_is_idempotent():
    spec = _gqa_spec()
    host, _ = dense_weights(spec, seed=14)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    once = replicate_kv_heads(params, spec, 4)
    wk1 = once["layers"][0]["wk"]
    twice = replicate_kv_heads(once, spec, 4)
    assert twice["layers"][0]["wk"] is wk1
