"""runtime/stats.py unit tests.

``percentile`` backs every reported p50/p99 in the serving stack (TTFT,
ITL, recovery and respawn latencies, the step timeline) and had no
direct tests; the edge cases here pin its nearest-rank semantics —
deliberately WITHOUT interpolation, so a reported percentile is always
an observed sample, never an invented midpoint. StepTimelineStats is
the flight recorder's per-composition histogram (runtime/trace.py).
"""

from distributed_llama_tpu.runtime.stats import (StepTimelineStats,
                                                 percentile)


# -- percentile -------------------------------------------------------------


def test_percentile_empty_is_none():
    assert percentile([], 50) is None
    assert percentile([], 0) is None
    assert percentile([], 100) is None


def test_percentile_single_element_answers_every_p():
    for p in (0, 1, 50, 99, 100):
        assert percentile([7.5], p) == 7.5


def test_percentile_p0_is_min_p100_is_max():
    xs = [5.0, 1.0, 9.0, 3.0]
    assert percentile(xs, 0) == 1.0
    assert percentile(xs, 100) == 9.0


def test_percentile_does_not_mutate_input():
    xs = [3.0, 1.0, 2.0]
    percentile(xs, 50)
    assert xs == [3.0, 1.0, 2.0]  # sorted() copy, not .sort()


def test_percentile_nearest_rank_no_interpolation():
    """p50 of two elements is an OBSERVED value (nearest-rank rounds to
    an index), never the 1.5 linear interpolation would invent."""
    assert percentile([1.0, 2.0], 50) in (1.0, 2.0)
    # ten elements 0..9: rank = round(p/100 * 9) — banker's rounding,
    # so p50 lands on index round(4.5) == 4
    xs = list(map(float, range(10)))
    assert percentile(xs, 50) == xs[round(0.5 * 9)] == 4.0
    assert percentile(xs, 99) == 9.0
    assert percentile(xs, 10) == xs[round(0.1 * 9)]


def test_percentile_out_of_range_p_clamps():
    xs = [1.0, 2.0, 3.0]
    assert percentile(xs, -10) == 1.0    # clamped to the min index
    assert percentile(xs, 250) == 3.0    # clamped to the max index


def test_percentile_unsorted_input_and_duplicates():
    xs = [9.0, 1.0, 9.0, 1.0, 5.0]
    assert percentile(xs, 0) == 1.0
    assert percentile(xs, 100) == 9.0
    assert percentile(xs, 50) == 5.0


# -- StepTimelineStats ------------------------------------------------------


def test_step_timeline_keys_and_summary():
    st = StepTimelineStats(window=16)
    for ms in (1.0, 2.0, 3.0):
        st.record(4, 1, 16, ms)
    st.record(2, 0, 0, 10.0)
    s = st.summary()
    assert set(s) == {(4, 1, 16), (2, 0, 0)}
    assert s[(4, 1, 16)]["n"] == 3
    assert s[(4, 1, 16)]["p50_ms"] == 2.0
    assert s[(4, 1, 16)]["mean_ms"] == 2.0
    assert s[(2, 0, 0)]["p99_ms"] == 10.0
    # busiest composition first
    assert list(s)[0] == (4, 1, 16)
    j = st.summary_json()
    assert j["dec4_pre1_c16"]["n"] == 3  # json-safe string keys


def test_step_timeline_window_bounds_samples():
    st = StepTimelineStats(window=8)
    for i in range(100):
        st.record(1, 0, 0, float(i))
    s = st.summary()[(1, 0, 0)]
    assert s["n"] == 8
    assert s["p50_ms"] >= 92.0  # only the newest window survives


def test_step_timeline_max_keys_bounds_compositions():
    st = StepTimelineStats(window=4, max_keys=3)
    for k in range(10):
        st.record(k, 0, 0, 1.0)
    assert len(st.summary()) == 3
    assert st.overflow == 7
    # an EXISTING key still records past the cap
    st.record(0, 0, 0, 2.0)
    assert st.summary()[(0, 0, 0)]["n"] == 2


def test_step_timeline_thread_safety_smoke():
    import threading

    st = StepTimelineStats(window=1024)
    errs = []

    def hammer(k):
        try:
            for i in range(500):
                st.record(k % 4, 0, 0, float(i))
                if i % 50 == 0:
                    st.summary()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=hammer, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs
    assert sum(v["n"] for v in st.summary().values()) <= 4 * 500


def test_percentile_matches_served_usage_shape():
    """The integration shape: percentile over a deque window exactly as
    ServeStats.summary does (list() of a deque of floats)."""
    from collections import deque

    win = deque(maxlen=4)
    for v in (10.0, 20.0, 30.0, 40.0, 50.0):
        win.append(v)
    assert percentile(list(win), 50) in (30.0, 40.0)
    assert percentile(list(win), 100) == 50.0
    # falsy inputs (None, ()) take the same no-data path as []
    assert percentile(None, 50) is None
