"""Two-process cluster chaos: bounded failure detection on the multihost
control plane (parallel/multihost.py) under socket-level fault injection
(runtime/faults.py conn_refused/recv_stall/frame_truncate/peer_close).

These are REAL two-OS-process clusters driven by the
parallel/cluster_harness.py subprocess CLI — but control-plane only (no
model, no mesh, no jax.distributed, no compiles), so the whole suite rides
the NON-SLOW tier and the CI `chaos` job. The contract under test is the
one the reference ships broken (SURVEY §5.3 — a dead worker hangs the
whole cluster forever):

  * a worker that DIES mid-phase is detected within --worker-timeout and
    produces a structured ClusterPeerLost diagnostic naming the node;
  * a worker that WEDGES (recv_stall: socket open, reader stopped — the
    shape no EOF will ever report) is detected by heartbeat silence;
  * a TORN frame (frame_truncate) is detected as a protocol loss;
  * a root killed with SIGKILL takes its workers down via bounded
    detection, not coordinator-teardown luck;
  * cluster formation retries refused connects with backoff and FAILS
    STRUCTURED at --connect-timeout, and a protocol-version mismatch is a
    symmetric formation error.

No assertion in this file ever waits on an unbounded recv: every
subprocess interaction carries a hard timeout well under the pytest
default, and the detection-latency assertions are the acceptance bars
(ISSUE 5) themselves.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HARNESS = "distributed_llama_tpu.parallel.cluster_harness"

# detection bounds used across the suite: tight enough that a regression
# to unbounded waits fails fast, loose enough for a loaded CI box
HB = "0.15"
TIMEOUT = 1.5      # --worker-timeout (seconds)
SLACK = 6.0        # subprocess/communicate margin over the bound
EXIT_PEER_LOST = 43
EXIT_FORMATION = 44


from distributed_llama_tpu.testing import free_port as _free_port


def _spawn(role: str, port: int, *extra, faults: str = ""):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # the harness never inits a backend, but
    env.pop("DLLAMA_FAULTS", None)  # never inherit ambient arming either
    if faults:
        env["DLLAMA_FAULTS"] = faults
    args = [sys.executable, "-m", HARNESS, role, "--port", str(port),
            "--heartbeat-interval", HB, "--worker-timeout", str(TIMEOUT),
            *extra]
    if role == "worker":
        args += ["--rank", "1"]
    return subprocess.Popen(args, cwd=REPO, env=env, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _events(out: str) -> list[dict]:
    return [json.loads(ln) for ln in out.splitlines()
            if ln.startswith("{")]


def _event(events: list[dict], name: str) -> dict:
    hits = [e for e in events if e["event"] == name]
    assert hits, (name, events)
    return hits[0]


def _wait_event(proc, name: str, timeout: float) -> tuple[dict, list[str]]:
    """Stream a harness process's stdout until the named event appears
    (bounded). Returns (event, lines_consumed) — the consumed lines must
    be recombined with communicate()'s remainder for full-event asserts."""
    import selectors

    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    lines: list[str] = []
    end = time.monotonic() + timeout
    try:
        while time.monotonic() < end:
            if not sel.select(timeout=0.2):
                continue
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            if line.startswith("{"):
                ev = json.loads(line)
                if ev["event"] == name:
                    return ev, lines
    finally:
        sel.close()
    proc.kill()
    raise AssertionError(
        f"event {name!r} never appeared within {timeout}s; got: {lines}")


def _finish(proc, timeout: float):
    """communicate() with a hard bound — a hung harness process is itself
    the regression this suite exists to catch."""
    try:
        return proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate(timeout=10)
        raise AssertionError(
            f"harness process hung past {timeout}s (the unbounded-wait "
            f"regression)\nstdout: {out}\nstderr: {err}")


def test_formation_and_clean_shutdown():
    """Happy path: HELLO handshake, heartbeats, phase ticks, SHUTDOWN —
    both sides exit 0 with structured event streams."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,idle:0.4")
    worker = _spawn("worker", port)
    w_out, w_err = _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert root.returncode == 0, (r_out, r_err)
    assert worker.returncode == 0, (w_out, w_err)
    r_ev, w_ev = _events(r_out), _events(w_out)
    assert _event(r_ev, "formed")["peers"] == [1]
    stats = _event(r_ev, "complete")["stats"]
    assert stats["pings_sent"] >= 1 and stats["pongs_received"] >= 1
    assert stats["peers_lost"] == []
    assert _event(w_ev, "shutdown")["stats"]["pongs_sent"] >= 1
    assert [e["phase"] for e in w_ev if e["event"] == "tick"] == [
        "formation", "idle"]


def test_worker_death_mid_prefill_detected():
    """A worker dying abruptly mid-phase is detected within
    --worker-timeout and the root's ClusterPeerLost names the node and
    the phase it died in."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,prefill:20")
    worker = _spawn("worker", port, "--die-after", "0.6")
    w_out, _ = _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert root.returncode == EXIT_PEER_LOST, (r_out, r_err)
    lost = _event(_events(r_out), "cluster_peer_lost")
    assert lost["node_id"] == 1
    assert lost["phase"] == "prefill"
    died = _event(_events(w_out), "dying")
    detect_s = lost["t_wall"] - died["t_wall"]
    # an abrupt process death closes the socket: detection is EOF-fast,
    # far inside the heartbeat bound
    assert 0 <= detect_s < TIMEOUT, (detect_s, lost)


def test_worker_stall_mid_decode_detected():
    """recv_stall wedges the worker's control-plane reader: the socket
    stays OPEN (no EOF will ever fire) but PONGs stop — only the
    heartbeat timeout can see it. Detection must land within
    --worker-timeout of the last frame; before this control plane
    existed, this exact shape hung the cluster forever (the reference's
    unbounded socket read)."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,decode:30")
    # after=2: let the HELLO_ACK recv and an early ping through, then
    # wedge every subsequent recv (times=0)
    worker = _spawn("worker", port, faults="recv_stall:after=2;times=0")
    try:
        r_out, r_err = _finish(root, TIMEOUT + 30)
        assert root.returncode == EXIT_PEER_LOST, (r_out, r_err)
        lost = _event(_events(r_out), "cluster_peer_lost")
        assert lost["node_id"] == 1
        assert lost["reason"] == "timeout"  # silence, not EOF
        assert lost["phase"] == "decode"
        # last_seen at detection ~= the timeout bound: the detector fired
        # as soon as the contract allows, not after some larger slop
        assert TIMEOUT <= lost["last_seen_s"] < TIMEOUT + 1.0, lost
    finally:
        worker.kill()  # the wedged reader never exits on its own
        worker.communicate(timeout=10)


def test_truncated_frame_detected():
    """frame_truncate tears the worker's next PONG mid-frame and closes
    the socket: the root must classify it as a protocol loss immediately
    (no waiting out the heartbeat bound)."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,run:20")
    # after=1: the HELLO send goes through, the first PONG tears
    worker = _spawn("worker", port, faults="frame_truncate:after=1;times=1")
    try:
        r_out, r_err = _finish(root, 30)
        assert root.returncode == EXIT_PEER_LOST, (r_out, r_err)
        lost = _event(_events(r_out), "cluster_peer_lost")
        assert lost["node_id"] == 1
        # a torn write surfaces as a mid-frame EOF/reset at the reader
        assert ("truncated" in lost["reason"] or lost["reason"]
                in ("eof", "reset")), lost
        assert lost["last_seen_s"] < TIMEOUT, lost  # no timeout wait
    finally:
        worker.kill()
        worker.communicate(timeout=10)


def test_root_sigkill_worker_exits():
    """SIGKILL the root mid-phase: every worker must take its own bounded
    diagnostic exit (EXIT_PEER_LOST, structured line naming node 0) —
    the pre-change behavior parked workers in an unbounded read until
    jax.distributed teardown happened to notice."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,decode:30")
    worker = _spawn("worker", port)
    _, pre_lines = _wait_event(worker, "formed", 60)  # cluster is up
    # wall clock ON PURPOSE: detect_s below subtracts the subprocess's
    # own t_wall event stamp — monotonic clocks do not transfer between
    # processes (the one legitimate cross-process exception to the
    # monotonic-interval rule, docs/observability.md)
    t_kill = time.time()
    root.send_signal(signal.SIGKILL)
    root.communicate(timeout=10)
    w_out, w_err = _finish(worker, TIMEOUT + SLACK)
    assert worker.returncode == EXIT_PEER_LOST, (w_out, w_err)
    lost = _event(_events("".join(pre_lines) + w_out), "cluster_peer_lost")
    assert lost["node_id"] == 0
    detect_s = lost["t_wall"] - t_kill
    assert 0 <= detect_s < TIMEOUT + 1.0, (detect_s, lost)


def test_connect_retry_backoff_then_success():
    """conn_refused fails the first two connect attempts deterministically;
    the worker's backoff loop must absorb them and still form."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,idle:0.3")
    worker = _spawn("worker", port, faults="conn_refused:times=2")
    w_out, w_err = _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert worker.returncode == 0, (w_out, w_err)
    assert root.returncode == 0, (r_out, r_err)
    assert _event(_events(w_out), "formed")["retries"] >= 2


def test_connect_timeout_is_bounded_and_structured():
    """No root at all: the worker must give up at --connect-timeout with a
    structured formation error (exit 44), never spin or hang."""
    port = _free_port()  # nothing listens here
    t0 = time.monotonic()
    worker = _spawn("worker", port, "--connect-timeout", "1.0")
    w_out, w_err = _finish(worker, 20)
    wall = time.monotonic() - t0
    assert worker.returncode == EXIT_FORMATION, (w_out, w_err)
    failed = _event(_events(w_out), "formation_failed")
    assert "--connect-timeout" in failed["error"]
    assert wall < 1.0 + SLACK, wall


def test_hello_version_mismatch_is_symmetric_error():
    """A worker speaking the wrong protocol version must produce a clear
    formation error on BOTH sides — never a half-formed cluster."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,idle:5",
                  "--connect-timeout", "5")
    worker = _spawn("worker", port, "--protocol-version", "99")
    w_out, w_err = _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert worker.returncode == EXIT_FORMATION, (w_out, w_err)
    assert root.returncode == EXIT_FORMATION, (r_out, r_err)
    for out in (w_out, r_out):
        failed = _event(_events(out), "formation_failed")
        assert "version" in failed["error"], failed


# -- dlwire: measured wire ledger + cross-node trace (ISSUE 12) ------------


def test_cross_node_trace_spans_link_under_one_id():
    """A traced two-process run: the root mints ONE trace id, phase
    frames carry it, the worker's cluster_tick spans ship back via
    MSG_TRACE and land on the root's timeline (origin=node1) under the
    SAME id as the root's own events — the cross-node acceptance bar."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,decode:0.4",
                  "--trace")
    worker = _spawn("worker", port, "--trace")
    w_out, w_err = _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert root.returncode == 0, (r_out, r_err)
    assert worker.returncode == 0, (w_out, w_err)
    r_ev = _events(r_out)
    tid = _event(r_ev, "complete")["tid"]
    assert tid > 0
    dump = _event(r_ev, "trace_dump")
    assert dump["tid"] == tid
    evs = dump["events"]
    assert all(e["tid"] == tid for e in evs if e.get("tid")), evs
    root_ticks = [e for e in evs if e["kind"] == "cluster_tick"
                  and "origin" not in e]
    worker_ticks = [e for e in evs if e["kind"] == "cluster_tick"
                    and e.get("origin") == "node1"]
    assert root_ticks and worker_ticks, evs
    assert {e["phase"] for e in worker_ticks} <= {e["phase"]
                                                  for e in root_ticks}
    # every dumped event is wall-stamped (the /admin/trace export shape)
    assert all("ts_wall" in e for e in evs), evs
    # the clean run has no casualty span
    assert not [e for e in evs if e["kind"] == "cluster_lost"], evs


def test_peer_close_death_yields_linked_casualty_span():
    """peer_close tears the worker down at a protocol send (its PONG):
    the root's bounded detection must emit a cluster_lost CASUALTY event
    linked under the session's trace id — on the same timeline as the
    worker's earlier shipped ticks — before its diagnostic exit. The
    cluster twin of a SIGKILLed replica's worker_exit span."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,decode:20",
                  "--trace")
    # after=2: HELLO + one frame pass, then the next send (a PONG) fires
    worker = _spawn("worker", port, "--trace",
                    faults="peer_close:after=2;times=1")
    try:
        r_out, r_err = _finish(root, 30)
        assert root.returncode == EXIT_PEER_LOST, (r_out, r_err)
        r_ev = _events(r_out)
        lost = _event(r_ev, "cluster_peer_lost")
        assert lost["node_id"] == 1
        dump = _event(r_ev, "trace_dump")
        tid = dump["tid"]
        assert tid > 0
        casualty = [e for e in dump["events"]
                    if e["kind"] == "cluster_lost"]
        assert casualty, dump["events"]
        assert casualty[0]["tid"] == tid
        assert casualty[0]["node"] == 1
        assert casualty[0]["reason"] == lost["reason"]
        # linked: the same id also carries the root's own protocol ticks
        assert [e for e in dump["events"]
                if e["kind"] == "cluster_tick" and e["tid"] == tid]
    finally:
        worker.kill()
        worker.communicate(timeout=10)


def test_wire_ledger_counts_match_frame_arithmetic_exactly():
    """The measured-bytes acceptance bar: after a clean harness run,
    every deterministic protocol frame's ledger count equals
    frame_bytes() arithmetic EXACTLY — on both ends of the star
    (root tx == worker rx for RUN/SHUTDOWN; PONG bytes likewise)."""
    from distributed_llama_tpu.parallel.multihost import (_HEADER_LEN,
                                                          frame_bytes)

    port = _free_port()
    phases = [("formation", 0.1), ("prefill", 0.3), ("decode", 0.3)]
    root = _spawn("root", port, "--phases",
                  ",".join(f"{n}:{s}" for n, s in phases))
    worker = _spawn("worker", port)
    w_out, w_err = _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert root.returncode == 0, (r_out, r_err)
    assert worker.returncode == 0, (w_out, w_err)
    root_wire = _event(_events(r_out), "complete")["stats"]["wire"]
    worker_wire = _event(_events(w_out), "shutdown")["stats"]["wire"]
    rtx = root_wire["peers"]["1"]["tx"]
    wrx = worker_wire["peers"]["0"]["rx"]

    run_expected = sum(frame_bytes(_HEADER_LEN, len(n.encode()))
                       for n, _ in phases)
    assert rtx["RUN"]["bytes"] == run_expected, (rtx, run_expected)
    assert wrx["RUN"]["bytes"] == run_expected
    assert rtx["RUN"]["frames"] == len(phases) == wrx["RUN"]["frames"]
    shut_expected = frame_bytes(_HEADER_LEN, 0)
    assert rtx["SHUTDOWN"]["bytes"] == shut_expected
    assert wrx["SHUTDOWN"]["bytes"] == shut_expected
    # heartbeat traffic: counts are timing-dependent but the SHAPE is
    # exact — every PING is frame_bytes(1, 0), every PONG frame_bytes(2,
    # 0) (seq + worker wall clock)
    ping = rtx["PING"]
    assert ping["bytes"] == ping["frames"] * frame_bytes(1, 0), ping
    pong = root_wire["peers"]["1"]["rx"]["PONG"]
    assert pong["bytes"] == pong["frames"] * frame_bytes(2, 0), pong
    # and both ends agree on the heartbeat bytes that actually crossed
    assert pong["bytes"] == worker_wire["peers"]["0"]["tx"]["PONG"]["bytes"]


def test_heartbeat_rtt_and_clock_offset_measured():
    """PING→PONG round trips land in the per-peer RTT histogram and the
    midpoint clock-offset estimate exists (≈0 between processes on one
    host — the bound here is loose on purpose, the ESTIMATE is what the
    MSG_TRACE rebase consumes)."""
    port = _free_port()
    root = _spawn("root", port, "--phases", "formation:0.1,idle:0.6")
    worker = _spawn("worker", port)
    _finish(worker, 30)
    r_out, r_err = _finish(root, 30)
    assert root.returncode == 0, (r_out, r_err)
    peer = _event(_events(r_out), "complete")["stats"]["wire"]["peers"]["1"]
    rtt = peer["rtt_ms"]
    assert rtt["n"] >= 1 and rtt["p50_ms"] >= 0, rtt
    assert rtt["p99_ms"] >= rtt["p50_ms"]
    assert len(rtt["recent"]) == rtt["n"] or len(rtt["recent"]) == 32
    assert abs(peer["clock_offset_ms"]) < 1000.0, peer
    assert peer["best_rtt_ms"] <= rtt["p99_ms"] + 1e-9


# -- in-process shape/codec tests (no subprocess) --------------------------


def _acct_recorder():
    calls = []
    return calls, lambda kind, n: calls.append((kind, n))


def test_torn_send_counts_partial_bytes_exactly_once():
    """frame_truncate writes half the frame then closes: the ledger hook
    must see exactly those partial bytes, once — and peer_close (closes
    without writing) must count zero. The PR-5 fault sites are the
    torn-frame truth the wire counters must survive."""
    from distributed_llama_tpu.parallel.multihost import (
        ClusterProtocolError, _send_frame, frame_bytes)
    from distributed_llama_tpu.runtime.faults import FAULTS

    a, b = socket.socketpair()
    calls, acct = _acct_recorder()
    try:
        FAULTS.arm("frame_truncate", times=1)
        buf_len = frame_bytes(3, 7)
        with pytest.raises(ClusterProtocolError, match="frame_truncate"):
            _send_frame(a, 1, [1, 2, 3], b"payload", timeout=5.0,
                        acct=acct)
        assert calls == [(1, max(1, buf_len // 2))], calls

        calls.clear()
        c, d = socket.socketpair()
        try:
            FAULTS.arm("peer_close", times=1)
            with pytest.raises(ClusterProtocolError, match="peer_close"):
                _send_frame(c, 1, [], b"x", timeout=5.0, acct=acct)
            assert calls == [], calls  # zero bytes crossed: no entry
        finally:
            d.close()
    finally:
        FAULTS.clear()
        a.close()
        b.close()


def test_torn_recv_counts_partial_bytes_exactly_once():
    """A frame torn mid-payload (EOF after the header): the receiving
    ledger counts the bytes that actually arrived, once, under the
    parsed kind — and a successful recv counts the exact frame size."""
    import struct

    from distributed_llama_tpu.parallel.multihost import (
        _FRAME_HDR, _FRAME_MAGIC, ClusterProtocolError, _recv_frame,
        _send_frame, frame_bytes)

    a, b = socket.socketpair()
    calls, acct = _acct_recorder()
    try:
        # clean frame: exact arithmetic
        _send_frame(a, 7, [1, -2], b"pay", timeout=5.0)
        _recv_frame(b, timeout=5.0, acct=acct)
        assert calls == [(7, frame_bytes(2, 3))], calls

        # torn frame: header + one of two ints, then EOF
        calls.clear()
        buf = _FRAME_HDR.pack(_FRAME_MAGIC, 9, 2, 0) + struct.pack("<q", 5)
        a.sendall(buf)
        a.close()
        with pytest.raises(ClusterProtocolError, match="truncated"):
            _recv_frame(b, timeout=5.0, acct=acct)
        assert calls == [(9, len(buf))], calls
    finally:
        b.close()


def test_recv_stall_fault_counts_nothing():
    """recv_stall wedges the reader BEFORE any bytes move: when the
    stall releases into a closed socket, the ledger must show zero for
    the attempt (nothing crossed the wire)."""
    from distributed_llama_tpu.parallel.multihost import _recv_frame
    from distributed_llama_tpu.runtime.faults import FAULTS

    a, b = socket.socketpair()
    calls, acct = _acct_recorder()
    try:
        FAULTS.arm("recv_stall", times=1, ms=50)
        a.close()  # EOF once the stall releases
        out = _recv_frame(b, timeout=5.0, acct=acct)
        assert out is None  # clean EOF at the frame boundary
        assert calls == [], calls
    finally:
        FAULTS.clear()
        b.close()


def test_wire_acct_disabled_path_is_allocation_free():
    """The cost bar (PR-8 discipline): a link's accounting closure with
    no stats object (the pre-formation / off-cluster shape) must be a
    no-op — no allocation over 10k calls — and the codec's acct=None
    default costs nothing."""
    import gc
    import sys as _sys

    from distributed_llama_tpu.parallel import multihost as mh

    link = mh.WorkerLink("127.0.0.1", 1, 1, 2)
    assert link.stats is None
    acct = link._mk_acct(0, "rx")
    acct(mh.MSG_PING, 24)  # warm the closure path
    gc.collect()
    before = _sys.getallocatedblocks()
    for _ in range(10_000):
        acct(mh.MSG_PING, 24)
    grew = _sys.getallocatedblocks() - before
    assert grew < 50, f"disabled wire acct allocated {grew} blocks"


def test_wire_ledger_enabled_cost_is_negligible():
    """Enabled-ledger cost bar: one account() call is bounded well under
    2% of even the tiny decode step (~5 ms on CPU-tiny; a control-plane
    frame is heartbeat-cadence anyway, never per-token). Measured
    loosely (CI boxes jitter): 10k accounts in well under a second."""
    from distributed_llama_tpu.runtime.stats import WireStats

    w = WireStats()
    t0 = time.perf_counter()
    for i in range(10_000):
        w.account(1, "PING", "tx", 24)
    per_call_us = (time.perf_counter() - t0) / 10_000 * 1e6
    # 100 µs/call would still be <2% of a decode step at heartbeat
    # cadence; typical is <2 µs — the bar catches accidental O(n) work
    assert per_call_us < 100, per_call_us
    s = w.summary()
    assert s["peers"]["1"]["tx"]["PING"] == {"frames": 10_000,
                                             "bytes": 240_000}


def test_wire_ledger_bounded_keys():
    """Label-cardinality bound: past max_keys distinct kinds per
    (peer, dir) the ledger counts overflow instead of growing."""
    from distributed_llama_tpu.runtime.stats import WireStats

    w = WireStats(max_keys=4)
    for i in range(10):
        w.account(1, f"K{i}", "tx", 8)
    s = w.summary()
    assert len(s["peers"]["1"]["tx"]) == 4
    assert s["key_overflow"] == 6


def test_reconcile_wire_drift_math_golden():
    """Pinned drift math (the 25% bar shared with dlprof's mirror):
    exact match -> 0.0/clean, 25% -> flagged (inclusive), modeled=0 ->
    no division, honest note."""
    from distributed_llama_tpu.runtime.netstats import (WIRE_DRIFT_FRAC,
                                                        reconcile_wire)

    r = reconcile_wire(400.0, 400.0)
    assert r["drift_frac"] == 0.0 and r["drift"] is False

    r = reconcile_wire(300.0, 400.0)  # exactly at the bar: flags
    assert r["drift_frac"] == 0.25 and r["drift"] is True
    assert "25%" in r["note"]

    r = reconcile_wire(390.0, 400.0)
    assert r["drift_frac"] == 0.025 and r["drift"] is False
    assert r["note"] is None

    r = reconcile_wire(100.0, 0.0)
    assert r["drift_frac"] is None and r["drift"] is False
    assert "no model" in r["note"]

    # the dlprof mirror cannot drift from the canonical threshold
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import dlprof
        assert dlprof.WIRE_DRIFT_FRAC == WIRE_DRIFT_FRAC
    finally:
        sys.path.pop(0)


# -- in-process shape/codec tests (no subprocess, pre-dlwire) --------------


def test_cluster_peer_lost_shape():
    from distributed_llama_tpu.parallel.multihost import ClusterPeerLost

    exc = ClusterPeerLost(3, 2.5, "decode", "timeout")
    assert exc.node_id == 3 and exc.phase == "decode"
    s = exc.summary()
    assert s == {"event": "cluster_peer_lost", "node_id": 3,
                 "last_seen_s": 2.5, "phase": "decode",
                 "reason": "timeout"}
    assert "node 3" in str(exc) and "decode" in str(exc)


def test_frame_codec_roundtrip_and_truncation():
    from distributed_llama_tpu.parallel.multihost import (
        _FRAME_HDR, _FRAME_MAGIC, ClusterProtocolError, _recv_frame,
        _send_frame)

    a, b = socket.socketpair()
    try:
        _send_frame(a, 7, [1, -2, 3], b"payload", timeout=5.0)
        kind, ints, payload = _recv_frame(b, timeout=5.0)
        assert (kind, ints, payload) == (7, [1, -2, 3], b"payload")

        # torn frame: half the bytes then EOF -> structured protocol error
        import struct
        buf = _FRAME_HDR.pack(_FRAME_MAGIC, 7, 1, 0) + struct.pack("<q", 9)
        a.sendall(buf[: len(buf) // 2])
        a.close()
        with pytest.raises(ClusterProtocolError, match="truncated"):
            _recv_frame(b, timeout=5.0)
    finally:
        b.close()


def test_frame_codec_rejects_garbage_magic():
    from distributed_llama_tpu.parallel.multihost import (
        ClusterProtocolError, _recv_frame)

    a, b = socket.socketpair()
    try:
        a.sendall(b"GET / HTTP/1.1\r\n")  # a port scanner / wrong service
        with pytest.raises(ClusterProtocolError, match="magic"):
            _recv_frame(b, timeout=5.0)
    finally:
        a.close()
        b.close()


def test_socket_fault_sites_registered():
    """The chaos sites exist in the registry and parse from DLLAMA_FAULTS
    (a typo'd site must fail loudly — faults.load_env contract)."""
    from distributed_llama_tpu.runtime.faults import SITES, FaultRegistry

    for site in ("conn_refused", "recv_stall", "frame_truncate",
                 "peer_close"):
        assert site in SITES
    reg = FaultRegistry()
    reg.load_env({"DLLAMA_FAULTS": "conn_refused:times=2,"
                                   "recv_stall:after=2;times=0"})
    assert reg.armed("conn_refused") and reg.armed("recv_stall")
    with pytest.raises(ConnectionRefusedError):
        reg.fire("conn_refused")
    # triggered() consumes counts deterministically
    reg.arm("peer_close", times=1)
    assert reg.triggered("peer_close") is True
    assert reg.triggered("peer_close") is False
    reg.clear()


def test_xfer_bench_header_carries_n_prompt():
    """ADVICE r5 high, protocol side: send_xfer_bench(n_prompt) must
    deliver n_prompt to the worker's RunMsg (max_tokens slot) so its
    measure_prefill_transfer_ms(n_prompt) runs the identical collective
    sequence as the root's (the collective half is pinned by the slow
    two-process test_multihost.py::test_two_process_benchmark_completes)."""
    import threading

    from distributed_llama_tpu.parallel import multihost as mh

    port = _free_port()
    root = mh.RootLink(2, "", port, heartbeat_interval=0.2,
                       worker_timeout=5.0, connect_timeout=5.0)
    worker = mh.WorkerLink("127.0.0.1", port, 1, 2, connect_timeout=5.0)
    t = threading.Thread(target=root.form)
    t.start()
    worker.form()
    t.join(timeout=10)
    old = mh.get_link()
    try:
        mh.set_link(root)
        mh.send_xfer_bench(37)
        mh.set_link(worker)
        msg = mh.recv_msg(timeout=10.0)
        assert msg.kind == mh.MSG_XFER_BENCH
        assert msg.max_tokens == 37
    finally:
        mh.set_link(old)
        root.close()
        worker.close()


def test_worker_recv_msg_wait_is_supervised():
    """recv_msg's queue wait wakes on root loss and raises the structured
    ClusterPeerLost — an idle worker can never block unboundedly."""
    import threading

    from distributed_llama_tpu.parallel import multihost as mh

    port = _free_port()
    root = mh.RootLink(2, "", port, heartbeat_interval=0.1,
                       worker_timeout=1.0, connect_timeout=5.0)
    worker = mh.WorkerLink("127.0.0.1", port, 1, 2, connect_timeout=5.0)
    t = threading.Thread(target=root.form)
    t.start()
    worker.form()
    t.join(timeout=10)
    try:
        t0 = time.monotonic()
        root.close()  # root goes away while the worker waits for a frame
        with pytest.raises(mh.ClusterPeerLost) as ei:
            worker.recv(timeout=30.0)
        assert ei.value.node_id == 0
        assert time.monotonic() - t0 < 5.0  # EOF-fast, nowhere near 30s
    finally:
        worker.close()
