"""dlrace (DLG3xx) lock-discipline lint tests.

Four kinds of coverage, all non-slow so `pytest -m "not slow"` enforces
the race gate exactly like CI:

* fixture corpus: one tripping + one clean file per rule under
  tests/fixtures/race_lint/, the tripping ones reconstructing the four
  historical host-side races (probe leak, deque-mutated-during-iteration,
  close/submit TOCTOU, unjoined `_rebuild` thread);
* convention tests: `_locked` suffix, `# dlrace: holds(...)`, inline
  `# dlrace: ignore[...]` suppression, scope membership;
* baseline hygiene: DLG108 stale-entry and DLG109 missing-justification
  detection, plus the live baseline's full justification coverage and the
  no-bare-suppression policy over the race scope;
* the JAX-free repo gate (L1 + dlrace + DLG206 against the committed
  baseline) and regression tests for live findings this lint got fixed.
"""

import pathlib
import threading
import time

from distributed_llama_tpu.analysis.findings import (load_baseline,
                                                     split_by_baseline,
                                                     unjustified_keys)
from distributed_llama_tpu.analysis.race_lint import (RACE_SCOPE,
                                                      in_race_scope,
                                                      race_lint_source)

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "race_lint"


def lint_fixture(name):
    return race_lint_source(f"tests/fixtures/race_lint/{name}",
                            (FIXTURES / name).read_text())


def rules_of(findings):
    return [f.rule for f in findings]


# -- fixture corpus: tripping + clean per rule ------------------------------


def test_dlg301_close_submit_toctou_trips():
    """Historical bug #3: close() flips the flag and drains lock-free
    while submit() appends after its lock-free check."""
    fs = lint_fixture("dlg301_bad.py")
    assert rules_of(fs) == ["DLG301"] * 3
    msgs = " ".join(f.message for f in fs)
    assert "Scheduler.submit" in msgs and "Scheduler.close" in msgs
    assert "`self._queue.append()`" in msgs
    assert "write to `self._closed`" in msgs


def test_dlg301_clean_lock_disciplined_scheduler():
    assert lint_fixture("dlg301_clean.py") == []


def test_dlg302_blocking_sleep_under_guard_trips():
    fs = lint_fixture("dlg302_bad.py")
    assert rules_of(fs) == ["DLG302"]
    assert "time.sleep" in fs[0].message and "_lock" in fs[0].message


def test_dlg302_clean_slow_work_outside_and_io_mutex_exempt():
    """The claim-then-work shape passes, and the dedicated send mutex
    (un-annotated by design) never counts as a held guard."""
    assert lint_fixture("dlg302_clean.py") == []


def test_dlg303_probe_leak_trips():
    """Historical bug #1: bare acquire stranded by a raising probe."""
    fs = lint_fixture("dlg303_bad.py")
    assert rules_of(fs) == ["DLG303"]
    assert "`_lock.acquire()`" in fs[0].message
    assert "try/finally" in fs[0].message


def test_dlg303_clean_try_finally_and_with():
    assert lint_fixture("dlg303_clean.py") == []


def test_dlg304_unjoined_rebuild_thread_trips():
    """Historical bug #4: close() joins the watchdog, forgets the
    in-flight rebuild thread."""
    fs = lint_fixture("dlg304_bad.py")
    assert rules_of(fs) == ["DLG304"]
    assert "`self._rebuild_thread`" in fs[0].message
    assert "close/shutdown" in fs[0].message


def test_dlg304_clean_snapshot_join_and_local_thread():
    assert lint_fixture("dlg304_clean.py") == []


def test_dlg305_deque_mutated_during_iteration_trips():
    """Historical bug #2: the stats scan iterating the live window while
    the step loop appends — all three iteration shapes fire."""
    fs = lint_fixture("dlg305_bad.py")
    assert rules_of(fs) == ["DLG305"] * 3
    fields = " ".join(f.message for f in fs)
    assert "`self._window`" in fields and "`self._by_key`" in fields


def test_dlg305_clean_snapshot_under_lock():
    assert lint_fixture("dlg305_clean.py") == []


def test_dlg306_wall_clock_intervals_trip():
    fs = lint_fixture("dlg306_bad.py")
    assert rules_of(fs) == ["DLG306"] * 3
    assert all("time.time()" in f.message for f in fs)


def test_dlg306_clean_monotonic_and_bare_timestamp():
    assert lint_fixture("dlg306_clean.py") == []


# -- conventions: holds(), _locked, suppression, scope ----------------------


def test_holds_annotation_and_locked_suffix_satisfy_the_guard():
    src = (
        "import threading\n"
        "from collections import deque\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._q = deque()  # dlrace: guarded-by(self._mu)\n"
        "    def _pump_locked(self):\n"
        "        self._q.append(1)\n"
        "    def _drain(self):  # dlrace: holds(self._mu)\n"
        "        self._q.popleft()\n"
        "    def broken(self):\n"
        "        self._q.append(2)\n"
    )
    fs = race_lint_source("x.py", src)
    assert rules_of(fs) == ["DLG301"]
    assert "S.broken" in fs[0].message


def test_dlrace_inline_suppression():
    src = (
        "import threading\n"
        "from collections import deque\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._q = deque()  # dlrace: guarded-by(self._mu)\n"
        "    def hot(self):\n"
        "        self._q.append(1)  # dlrace: ignore[DLG301]\n"
    )
    assert race_lint_source("x.py", src) == []
    # the suppression is rule-scoped: a different rule still fires
    narrowed = src.replace("ignore[DLG301]", "ignore[DLG305]")
    assert rules_of(race_lint_source("x.py", narrowed)) == ["DLG301"]


def test_nested_def_does_not_inherit_held_locks():
    src = (
        "import threading\n"
        "from collections import deque\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._q = deque()  # dlrace: guarded-by(self._mu)\n"
        "    def arm(self):\n"
        "        with self._mu:\n"
        "            def cb():\n"
        "                self._q.append(1)\n"
        "            return cb\n"
    )
    # cb runs later, on whatever thread fires it — the with-block's held
    # set must not leak into it
    assert rules_of(race_lint_source("x.py", src)) == ["DLG301"]


def test_race_scope_membership():
    assert in_race_scope("distributed_llama_tpu/runtime/scheduler.py")
    assert in_race_scope("distributed_llama_tpu/apps/api_server.py")
    assert in_race_scope("distributed_llama_tpu/parallel/multihost.py")
    assert not in_race_scope("distributed_llama_tpu/parallel/collectives.py")
    assert not in_race_scope("distributed_llama_tpu/model/llama.py")
    assert sorted(RACE_SCOPE) == ["apps/", "parallel/multihost.py",
                                  "runtime/"]


# -- baseline hygiene: DLG108 / DLG109 --------------------------------------


def test_dlg108_stale_allowlist_entry_reported():
    from distributed_llama_tpu.analysis.__main__ import hygiene_findings

    baseline = {"findings": ["DLG301|gone.py|msg"],
                "justifications": {"DLG301|gone.py|msg": "why"}}
    out = hygiene_findings([], baseline)
    assert rules_of(out) == ["DLG108"]
    assert "stale baseline" in out[0].message
    assert "DLG301|gone.py|msg" in out[0].message


def test_dlg109_unjustified_entry_reported():
    from distributed_llama_tpu.analysis.__main__ import hygiene_findings

    baseline = {"findings": ["DLG301|a.py|m"],
                "justifications": {"DLG301|a.py|m":
                                   "TODO: justify this entry"}}
    bad = hygiene_findings([], baseline)
    assert set(rules_of(bad)) == {"DLG108", "DLG109"}
    assert unjustified_keys(baseline) == ["DLG301|a.py|m"]


def test_live_baseline_every_entry_justified():
    """The acceptance bar: zero baseline entries without a one-line
    justification — an allowlisted race is a reviewed decision."""
    from distributed_llama_tpu.analysis.__main__ import DEFAULT_BASELINE

    baseline = load_baseline(DEFAULT_BASELINE)
    assert baseline["findings"], "baseline unexpectedly empty"
    assert unjustified_keys(baseline) == []


def test_no_bare_dlrace_suppressions_in_race_scope():
    """Policy: a suppression without a rule list silences EVERYTHING on
    the line — banned in the race scope (baseline with a justification
    instead)."""
    import re

    from distributed_llama_tpu.analysis.__main__ import PKG_DIR
    from distributed_llama_tpu.analysis.ast_lint import iter_package_files

    bare = re.compile(r"#\s*dl(?:grind|race):\s*ignore(?!\[)")
    offenders = []
    for rel in iter_package_files(PKG_DIR):
        if not in_race_scope(rel):
            continue
        src = (pathlib.Path(PKG_DIR) / rel).read_text()
        for i, line in enumerate(src.splitlines(), start=1):
            if bare.search(line):
                offenders.append(f"{rel}:{i}")
    assert not offenders, offenders


# -- the JAX-free repo gate + DLG206 ----------------------------------------


def test_race_gate_repo_is_clean_without_jax():
    """CI's lint-analysis job, pytest-collected: L1 + dlrace + the
    serving-path D2H audit against the committed baseline, no JAX import
    required (the jaxpr level has its own gate in test_analysis)."""
    from distributed_llama_tpu.analysis.__main__ import (DEFAULT_BASELINE,
                                                         gather_findings,
                                                         hygiene_findings)

    baseline = load_baseline(DEFAULT_BASELINE)
    findings, _ = gather_findings(baseline, no_jaxpr=True)
    new, _ = split_by_baseline(findings, baseline)
    new.extend(hygiene_findings(findings, baseline))
    assert not new, "\n".join(f"{f.anchor()}: {f.rule} {f.message}"
                              for f in new)


def test_dlg206_pins_the_host_sampling_transfers():
    """The per-token serving path reaches the four known host-sampling
    D2H sites (draft sampling + engine sampling/lookup) — and every one
    is a baselined, justified decision, not a silent cost."""
    from distributed_llama_tpu.analysis.__main__ import (DEFAULT_BASELINE,
                                                         PKG_DIR)
    from distributed_llama_tpu.analysis.serving_d2h import audit_serving_path

    fs = audit_serving_path(PKG_DIR, prefix="distributed_llama_tpu/")
    assert fs and all(f.rule == "DLG206" for f in fs)
    files = {f.file.rsplit("/", 1)[-1] for f in fs}
    assert {"draft.py", "engine.py"} <= files
    baseline = load_baseline(DEFAULT_BASELINE)
    keys = set(baseline["findings"])
    just = baseline.get("justifications", {})
    for f in fs:
        assert f.key() in keys, f"unbaselined serving-path D2H: {f.key()}"
        assert just.get(f.key()), f"no justification for {f.key()}"


# -- regression tests for live findings this lint got fixed -----------------


def test_remote_handle_close_joins_monitor_thread():
    """DLG304 live fix (router.py): RemoteReplicaHandle.close() must wait
    for the monitor thread instead of letting interpreter teardown race
    its health probes into a closed client."""
    from distributed_llama_tpu.runtime.router import RemoteReplicaHandle

    h = RemoteReplicaHandle.__new__(RemoteReplicaHandle)
    h._closed = False
    h.draining = False
    h._proc = None
    h._poll = 0.05

    class _Client:
        def close(self):
            pass

    h.client = _Client()
    gate = threading.Event()
    exited = threading.Event()

    def monitor():
        # parked mid-poll when close() runs — without the join, close()
        # returns while this thread is still alive
        gate.wait(timeout=0.3)
        assert h._closed
        exited.set()

    h._monitor_thread = threading.Thread(target=monitor, daemon=True)
    h._monitor_thread.start()
    t0 = time.perf_counter()
    h.close(timeout=5.0)
    assert exited.is_set(), "close() returned before the monitor exited"
    assert not h._monitor_thread.is_alive()
    assert time.perf_counter() - t0 < 5.0


def test_kv_transfer_summary_consistent_under_concurrent_appends():
    """DLG305 baselined decision (stats.py KVTransferStats.summary):
    list(deque) snapshots atomically under the GIL — hammer appends while
    summarizing and require no RuntimeError and sane aggregates."""
    from distributed_llama_tpu.runtime.stats import KVTransferStats

    st = KVTransferStats(enabled=True, tier="mixed")
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            st.note_transfer_ms(1.0)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            s = st.summary()
            assert isinstance(s, dict)
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert not t.is_alive()
