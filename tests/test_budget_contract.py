"""One budget contract across every generation path (VERDICT r4 #9).

max_tokens is a HARD cap on emitted tokens: max_tokens <= 0 prefills (the
cache advances — the API server's prefix reuse depends on that) but emits
nothing, on generate(), the lookup iterators, the batch paths, and the
on-device loops alike. Round 3 left generate() emitting one pre-budget-check
token; this pins the reconciled semantic.
"""

import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights

PROMPT = [1, 5, 9]


def _engine(spec, host, **kw):
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    return Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32, **kw)


def _spec(**kw):
    return make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=32, **kw)


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1,
                   backend="python")


def test_generate_budget_zero_emits_nothing_but_prefills():
    spec = _spec()
    host, _ = dense_weights(spec, seed=7)
    eng = _engine(spec, host)
    res = eng.generate(PROMPT, 0, _greedy(spec))
    assert res.tokens == []
    assert eng.pos == len(PROMPT)  # prefill advanced the cache
    # the advanced cache is live: continuing from here matches an unbroken
    # greedy run over the same positions
    cont = eng.generate([2], 3, _greedy(spec)).tokens
    full = _engine(spec, host).generate(PROMPT + [2], 3,
                                        _greedy(spec)).tokens
    assert cont == full


def test_all_paths_agree_at_budget_zero():
    spec = _spec()
    host, _ = dense_weights(spec, seed=7)

    eng = _engine(spec, host)
    assert eng.generate(PROMPT, 0, _greedy(spec)).tokens == []

    eng = _engine(spec, host)
    assert list(eng.generate_lookup_stream(PROMPT, 0, draft_len=4)) == []
    assert eng.pos == len(PROMPT)

    eng = _engine(spec, host)
    assert eng.generate_device(PROMPT, 0, temperature=0.0, topp=0.9,
                               seed=1) == []
    assert eng.pos == len(PROMPT)

    prompts = [PROMPT, [2, 7]]
    eng = _engine(spec, host, batch=2)
    steps = list(eng.generate_batch_stream(prompts, 0, _greedy(spec)))
    assert steps == []
    assert eng.pos == len(PROMPT)

    eng = _engine(spec, host, batch=2)
    assert eng.generate_batch_device(prompts, 0, temperature=0.0, topp=0.9,
                                     seed=1) == [[], []]


def test_generate_budget_is_exact_cap():
    """A positive budget emits exactly that many tokens (no +1 from the
    prefill-step sample) unless eos/context ends the run first."""
    spec = _spec()
    host, _ = dense_weights(spec, seed=7)
    for n in (1, 2, 5):
        eng = _engine(spec, host)
        assert len(eng.generate(PROMPT, n, _greedy(spec)).tokens) == n
