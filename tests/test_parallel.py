"""Tensor-parallel sharding tests on a virtual 8-device CPU mesh.

Closes the reference's testing gap — it has NO automated multi-node test
(SURVEY.md §4); slicing was only checked shard-by-shard in-process
(ref: src/transformer-test.cpp:21-72). Here the real SPMD program runs on 8
XLA devices and must match the single-device result.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.models.transformer import KVCache, forward
from distributed_llama_tpu.parallel import (
    make_mesh,
    param_pspecs,
    q80_psum,
    shard_params,
)
from distributed_llama_tpu.quants import QuantizedTensor
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights


def test_mesh_axes():
    mesh = make_mesh(tp=4, dp=2)
    assert mesh.shape == {"dp": 2, "sp": 1, "ep": 1, "pp": 1, "tp": 4}


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL])
@pytest.mark.parametrize("mode", ["dense", "q40"])
def test_tp_forward_matches_single_device(arch, mode):
    # q40 col-splits must keep whole 32-blocks per shard: dim >= 32*tp
    spec = make_spec(arch, dim=128, n_heads=8, n_kv_heads=4, hidden_dim=256)
    host, _ = dense_weights(spec, seed=5)
    params = load_params(spec, host, mode=mode, dtype=jnp.float32)

    tok = jnp.array([[7]], jnp.int32)
    ref_logits, _ = forward(params, spec, tok, jnp.int32(0), KVCache.create(spec, 1))

    mesh = make_mesh(tp=4, dp=1)
    engine = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    got = engine.step(np.array([[7]], np.int32), 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref_logits), rtol=0, atol=2e-4)


def test_tp_multi_step_decode_matches():
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=6)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)

    toks = [3, 9, 27, 81]
    # single device
    cache = KVCache.create(spec, 1)
    ref = []
    for i, t in enumerate(toks):
        lg, cache = forward(params, spec, jnp.array([[t]], jnp.int32), jnp.int32(i), cache)
        ref.append(np.asarray(lg))
    # 4-way TP (tp must divide n_kv_heads=4, the reference's nSlices rule)
    mesh = make_mesh(tp=4)
    engine = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    for i, t in enumerate(toks):
        got = engine.step(np.array([[t]], np.int32), i)
        np.testing.assert_allclose(np.asarray(got), ref[i], rtol=0, atol=5e-4)


def test_dp_tp_mesh_runs():
    """2-way data parallel x 4-way tensor parallel, batch=2."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=7)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    mesh = make_mesh(tp=4, dp=2)
    engine = Engine(spec, params, mesh, batch=2, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    logits = engine.step(np.array([[5], [11]], np.int32), 0)
    assert logits.shape == (2, spec.vocab_size)
    # row 0 must equal a single-device run of token 5
    ref, _ = forward(params, spec, jnp.array([[5]], jnp.int32), jnp.int32(0),
                     KVCache.create(spec, 1))
    np.testing.assert_allclose(np.asarray(logits)[0], np.asarray(ref)[0], rtol=0, atol=5e-4)


def test_param_pspecs_cover_all_leaves():
    spec = make_spec(ArchType.GROK1)
    host, _ = dense_weights(spec, seed=8)
    for mode in ("dense", "q40"):
        params = load_params(spec, host, mode=mode)
        specs = param_pspecs(params)
        assert set(specs) == set(params)

        def check(w, sp):
            if isinstance(w, QuantizedTensor):
                assert len(sp.packed) == w.packed.ndim
                assert len(sp.scales) == w.scales.ndim
            else:
                assert len(sp) == w.ndim

        for name, w in params.items():
            if name == "layers":
                for lw, lsp in zip(w, specs[name]):
                    assert set(lsp) == set(lw)
                    for k in lw:
                        check(lw[k], lsp[k])
            else:
                check(w, specs[name])


def test_q80_psum_matches_psum():
    """Quantized all-reduce ~ exact all-reduce (the reference's Q80 wire,
    ref: src/tasks.cpp:124-163)."""
    from distributed_llama_tpu.parallel.compat import shard_map

    mesh = make_mesh(tp=8)
    x = np.random.default_rng(0).standard_normal((8, 4, 64)).astype(np.float32)

    @jax.jit
    def exact(x):
        f = shard_map(lambda v: jax.lax.psum(v, "tp"), mesh=mesh,
                      in_specs=P("tp"), out_specs=P(), check_vma=False)
        return f(x)

    @jax.jit
    def quantized(x):
        f = shard_map(lambda v: q80_psum(v[0], "tp")[None], mesh=mesh,
                      in_specs=P("tp"), out_specs=P(), check_vma=False)
        return f(x)

    a = np.asarray(exact(x))
    b = np.asarray(quantized(x))
    # int8 blocks: small relative error on the reduced values
    assert np.abs(a - b).max() < 8 * np.abs(x).max() / 127 * 1.1


def test_q80_psum_2shot_matches_psum():
    """Two-shot quantized all-reduce ~ exact all-reduce; chunk-block-aligned
    path (the wire-efficient form of the reference's Q80 exchange)."""
    from distributed_llama_tpu.parallel.compat import shard_map

    from distributed_llama_tpu.parallel import q80_psum_2shot

    mesh = make_mesh(tp=8)
    # last dim 512 = 8 shards x 2 blocks: exercises the all_to_all path
    x = np.random.default_rng(1).standard_normal((8, 4, 512)).astype(np.float32)

    @jax.jit
    def exact(x):
        f = shard_map(lambda v: jax.lax.psum(v, "tp"), mesh=mesh,
                      in_specs=P("tp"), out_specs=P(), check_vma=False)
        return f(x)

    @jax.jit
    def quantized(x):
        f = shard_map(lambda v: q80_psum_2shot(v[0], "tp", 8)[None], mesh=mesh,
                      in_specs=P("tp"), out_specs=P(), check_vma=False)
        return f(x)

    a = np.asarray(exact(x))
    b = np.asarray(quantized(x))
    # double quantization (partials + reduced chunk): 2x the one-shot bound
    assert np.abs(a - b).max() < 2 * 8 * np.abs(x).max() / 127 * 1.1


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL])
@pytest.mark.parametrize("mode", ["dense", "q40"])
def test_tp_q80_collectives_match_exact(arch, mode):
    """q80-collective TP forward (shard_map + quantized all-reduce on wo/w2)
    ~ GSPMD-exact TP forward within block-quant tolerance (VERDICT r1 #2;
    ref wire compression: src/tasks.cpp:124-163)."""
    from distributed_llama_tpu.parallel.tp_q80 import TpColWeight

    spec = make_spec(arch, dim=256, n_heads=8, n_kv_heads=4, hidden_dim=512)
    host, _ = dense_weights(spec, seed=11)
    params = load_params(spec, host, mode=mode, dtype=jnp.float32)
    mesh = make_mesh(tp=4)

    exact = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32)
    q80 = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, q80_collectives=True)
    # col weights actually repacked into the shard_map stacked form
    assert isinstance(q80.params["layers"][0]["wo"], TpColWeight)
    if arch == ArchType.MIXTRAL:
        assert isinstance(q80.params["layers"][0]["moe_down"], TpColWeight)

    toks = [7, 3, 1]
    for i, t in enumerate(toks):
        a = np.asarray(exact.step(np.array([[t]], np.int32), i))
        b = np.asarray(q80.step(np.array([[t]], np.int32), i))
        # per-layer quantized exchange: error bounded by a few block-quant
        # steps on the residual stream; logits stay close
        np.testing.assert_allclose(b, a, rtol=0, atol=0.05)
        assert np.argmax(a) == np.argmax(b)


def test_repack_col_tp_roundtrip():
    """The stacked (tp, d, n/tp) shards hold exactly the logical column
    slices of the original weight, for dense and Q40 forms."""
    from distributed_llama_tpu.parallel.tp_q80 import repack_col_tp
    from distributed_llama_tpu.quants.jax_codec import dequantize_q40_jax
    from distributed_llama_tpu.quants.numpy_codec import quantize_q40

    rng = np.random.default_rng(3)
    w = rng.standard_normal((16, 256), dtype=np.float32) * 0.1
    tp = 4

    # dense
    stacked = repack_col_tp(jnp.asarray(w), tp).w
    for k in range(tp):
        np.testing.assert_array_equal(np.asarray(stacked[k]),
                                      w[:, k * 64:(k + 1) * 64])

    # q40: per-shard dequant == dequant-of-slice
    scales, packed = quantize_q40(w)
    qt = QuantizedTensor.from_numpy(scales, packed)
    full = np.asarray(dequantize_q40_jax(qt, dtype=jnp.float32))
    stacked_q = repack_col_tp(qt, tp).w
    for k in range(tp):
        shard = QuantizedTensor(stacked_q.packed[k], stacked_q.scales[k])
        np.testing.assert_allclose(
            np.asarray(dequantize_q40_jax(shard, dtype=jnp.float32)),
            full[:, k * 64:(k + 1) * 64], rtol=0, atol=1e-6)


def test_engine_generate_greedy():
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=9)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    mesh = make_mesh(tp=2)
    engine = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32, prefill_chunk=4)
    sampler = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    result = engine.generate([1, 5, 9], max_tokens=5, sampler=sampler)
    assert len(result.tokens) == 5
    # greedy is deterministic: same prompt, same continuation
    engine.reset()
    result2 = engine.generate([1, 5, 9], max_tokens=5, sampler=sampler)
    assert result.tokens == result2.tokens
    avg = result.stats.averages()
    assert avg.generation_ms > 0


def test_device_greedy_decode_matches_host_loop():
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=10)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)

    engine = Engine(spec, params, mesh=None, compute_dtype=jnp.float32,
                    cache_dtype=jnp.float32)
    toks_dev, _ = engine.decode_greedy_device(first_token=3, n_tokens=6)

    engine2 = Engine(spec, params, mesh=None, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
    sampler = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    res = engine2.generate([3], max_tokens=7, sampler=sampler)
    # device loop emits argmax AFTER consuming token i; host loop's first
    # output corresponds to the same position
    assert list(toks_dev.reshape(-1)[:6]) == res.tokens[:6]


def test_generate_batch_matches_independent_runs():
    """VERDICT r1 #4: batch=4 greedy generation over a dp mesh matches 4
    independent single-sequence runs token-for-token (ragged prompt lengths,
    per-row positions/eos)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=12)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    prompts = [[1, 5, 9], [2], [7, 3, 3, 3, 8], [4, 4]]

    greedy = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    refs = []
    for p in prompts:
        eng = Engine(spec, params, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
        refs.append(eng.generate(p, max_tokens=6, sampler=greedy).tokens)

    mesh = make_mesh(tp=2, dp=4)
    eng_b = Engine(spec, params, mesh, batch=4, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32)
    outs = eng_b.generate_batch(prompts, max_tokens=6, sampler=greedy)
    assert outs == refs


def test_generate_batch_sampled_reproducible_and_distinct():
    """Sampled batch generation (vectorized host sampler): a fixed seed
    reproduces exactly; identical prompts still diverge because the
    shared interleaved xorshift stream gives each row different coins."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=13)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    prompts = [[1, 5, 9]] * 3

    def run():
        s = Sampler(spec.vocab_size, temperature=0.9, topp=0.9, seed=5,
                    backend="python")
        eng = Engine(spec, params, batch=3, compute_dtype=jnp.float32,
                     cache_dtype=jnp.float32)
        return eng.generate_batch(prompts, max_tokens=8, sampler=s)

    a, b = run(), run()
    assert a == b  # deterministic for a fixed seed
    assert len({tuple(r) for r in a}) > 1  # interleaved stream: rows differ


def test_generate_batch_eos_stops_row():
    """A row sampling the stop token halts while other rows continue."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=13)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    prompts = [[1, 5], [2, 8]]

    greedy = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    ref0 = Engine(spec, params, compute_dtype=jnp.float32,
                  cache_dtype=jnp.float32).generate(
        prompts[0], max_tokens=8, sampler=greedy).tokens
    # use row 0's third greedy token as the "eos": row 0 must truncate there
    eos = ref0[2]

    eng_b = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32)
    outs = eng_b.generate_batch(prompts, max_tokens=8, sampler=greedy,
                                eos_id=eos)
    assert outs[0] == ref0[: ref0.index(eos) + 1]
    assert len(outs[1]) >= 1


def test_generate_batch_stops_at_context_limit():
    """Per-row overflow: a row at seq_len stops exactly where generate()
    would; no clamped rewrites leak extra tokens."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)  # seq 16
    host, _ = dense_weights(spec, seed=14)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    greedy = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)

    long_p = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]
    ref = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32).generate(
        long_p, max_tokens=10, sampler=greedy).tokens
    assert len(ref) == 1 + (spec.seq_len - len(long_p))  # context-limited

    eng_b = Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32)
    outs = eng_b.generate_batch([long_p, [1, 2]], max_tokens=10, sampler=greedy)
    assert outs[0] == ref
    assert len(outs[1]) == 10  # short row unaffected by the exhausted one


def test_generate_batch_stream_stop_flags_retire_rows():
    """generate_batch_stream: collecting the stream equals generate_batch
    (it IS generate_batch's engine), and a caller-set stop_flags[i]
    retires row i between steps — the API server's stop-sequence scan
    runs on decoded text the engine cannot see."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=14)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    prompts = [[1, 5, 9], [2, 7], [4]]

    def greedy():
        return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1,
                       backend="python")

    eng = Engine(spec, params, batch=3, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    want = eng.generate_batch(prompts, max_tokens=6, sampler=greedy())

    eng.reset()
    got = [[] for _ in prompts]
    for step in eng.generate_batch_stream(prompts, 6, greedy()):
        for i, t in enumerate(step):
            if t is not None:
                got[i].append(t)
    assert got == want

    # retire row 1 after its second token: rows 0/2 must be unaffected
    # (greedy rows are independent; the sampler draws no coins at temp 0)
    eng.reset()
    flags = np.zeros(3, bool)
    got2 = [[] for _ in prompts]
    for step in eng.generate_batch_stream(prompts, 6, greedy(),
                                          stop_flags=flags):
        for i, t in enumerate(step):
            if t is not None:
                got2[i].append(t)
        if len(got2[1]) >= 2:
            flags[1] = True
    assert got2[0] == want[0] and got2[2] == want[2]
    assert got2[1] == want[1][:2]


def test_force_mesh_kernels_one_device_parity():
    """The silicon-proof configuration (VERDICT r4 #1, bench._shardmap_row):
    a 1-device Mesh(('tp',)) engine with force_mesh_kernels=True routes
    every Q40 matmul through the shard_map Pallas wrappers (TpRowWeight at
    tp == 1) and must reproduce the direct-kernel engine's greedy stream
    exactly. Interpret mode here; the bench runs the same config on the
    real chip with Mosaic lowering."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=128, seq_len=64)
    host, _ = dense_weights(spec, seed=3)

    def greedy():
        return Sampler(spec.vocab_size, 0.0, 0.9, 1, backend="python")

    p1 = load_params(spec, host, mode="q40", dtype=jnp.float32)
    e1 = Engine(spec, p1, compute_dtype=jnp.float32,
                cache_dtype=jnp.float32, use_pallas=True,
                pallas_interpret=True)
    want = e1.generate([1, 5, 9], 8, greedy()).tokens

    mesh = make_mesh(tp=1, devices=jax.devices()[:1])
    p2 = load_params(spec, host, mode="q40", dtype=jnp.float32)
    e2 = Engine(spec, p2, mesh, compute_dtype=jnp.float32,
                cache_dtype=jnp.float32, use_pallas=True,
                pallas_interpret=True, force_mesh_kernels=True)
    from distributed_llama_tpu.parallel.tp_q80 import TpRowWeight
    assert any(isinstance(v, TpRowWeight)
               for v in e2.params["layers"][0].values())
    got = e2.generate([1, 5, 9], 8, greedy()).tokens
    assert got == want
