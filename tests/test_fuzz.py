"""Property/fuzz tests for the codec + tokenizer surfaces.

SURVEY.md §4 notes the reference has NO fuzzing at all; these close that
gap for the attack surfaces that parse externally-supplied bytes: the
Q40/Q80 block codecs (model files), the tokenizer (user text), and the
model-file header reader (arbitrary files must error, not crash or hang).
"""

import struct

import numpy as np
import pytest

from distributed_llama_tpu.quants.numpy_codec import (
    dequantize_q40, dequantize_q80, q40_bytes_to_arrays, q80_bytes_to_arrays,
    quantize_q40, quantize_q80,
)


def test_q40_roundtrip_properties(rng):
    """For arbitrary f32 rows: encode->decode error bounded by the block
    scale; all-zero blocks stay exactly zero; idempotent re-encode."""
    for _ in range(50):
        n = 32 * int(rng.integers(1, 9))
        x = (rng.standard_normal(n) * 10.0 ** int(rng.integers(-3, 3))).astype(np.float32)
        if rng.random() < 0.2:
            x[: 32 * int(rng.integers(0, n // 32 + 1))] = 0.0
        scales, packed = quantize_q40(x[None])
        y = dequantize_q40(scales, packed)[0]
        step = np.abs(scales.astype(np.float32))[0].repeat(32)
        assert np.all(np.abs(y - x) <= step * 1.01 + 1e-7)
        s2, p2 = quantize_q40(y[None])
        y2 = dequantize_q40(s2, p2)[0]
        assert np.all(np.abs(y2 - y) <= step * 1.01 + 1e-7)


def test_q40_decode_arbitrary_bytes(rng):
    """Any byte string of the right length decodes to finite floats (scales
    are f16: inf/nan bit patterns must not escape into weights... they CAN
    appear as f16 specials, so the decoder's contract is just: no crash,
    shape correct). Block stream parsing never reads out of bounds."""
    for _ in range(50):
        nb = int(rng.integers(1, 16))
        buf = rng.integers(0, 256, nb * 18, dtype=np.uint8).tobytes()
        scales, packed = q40_bytes_to_arrays(buf, nb * 32)
        assert scales.shape == (nb,) and packed.shape == (nb, 16)
        out = dequantize_q40(scales[None], packed[None])
        assert out.shape == (1, nb * 32)


def test_q80_roundtrip_and_arbitrary_bytes(rng):
    for _ in range(50):
        n = 32 * int(rng.integers(1, 9))
        x = (rng.standard_normal(n) * 10.0 ** int(rng.integers(-3, 3))).astype(np.float32)
        scales, q = quantize_q80(x[None])
        y = dequantize_q80(scales, q)[0]
        # 0.5*s rounding + 127 * f16-rounding of the scale itself (relative
        # 2^-11 for normals, absolute 2^-25 spacing for subnormal scales)
        s = np.abs(scales.astype(np.float32))[0].repeat(32)
        bound = 0.5 * s + 127 * np.maximum(s * 2.0 ** -11, 2.0 ** -25) + 1e-9
        assert np.all(np.abs(y - x) <= bound)
        buf = rng.integers(0, 256, (n // 32) * 34, dtype=np.uint8).tobytes()
        s2, q2 = q80_bytes_to_arrays(buf, n)
        assert s2.shape == (n // 32,) and q2.shape == (n // 32, 32)


def test_tokenizer_fuzz_roundtrip(tmp_path, rng):
    """Arbitrary unicode text encodes without error and decodes back to the
    same UTF-8 bytes (byte-fallback guarantees losslessness)."""
    from distributed_llama_tpu.testing import write_fixture
    from distributed_llama_tpu.tokenizer import Tokenizer

    _, tpath = write_fixture(tmp_path)
    tok = Tokenizer.from_file(tpath)
    for _ in range(30):
        cps = rng.integers(1, 0x10FFFF, int(rng.integers(1, 40)))
        text = "".join(chr(c) for c in cps
                       if not (0xD800 <= c <= 0xDFFF))  # skip surrogates
        ids = tok.encode(text, add_bos=False)
        got = b"".join(tok.decode_piece(ids[i - 1] if i else tok.bos_id, t)
                       for i, t in enumerate(ids))
        # leading-space strip applies only after BOS; compare raw bytes
        assert got == text.encode("utf-8"), (text, ids)


def test_model_file_reader_rejects_garbage(tmp_path, rng):
    """Arbitrary or truncated file bytes raise a clean error (the reference
    exits on bad magic; we must never hang or segfault)."""
    from distributed_llama_tpu.io.model_file import read_spec

    for i in range(20):
        path = str(tmp_path / f"junk{i}.m")
        n = int(rng.integers(0, 4096))
        with open(path, "wb") as f:
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
        with pytest.raises((ValueError, AssertionError, struct.error,
                            EOFError, OSError, KeyError)):
            read_spec(path)

    # a valid header magic followed by truncation must also error cleanly
    path = str(tmp_path / "trunc.m")
    with open(path, "wb") as f:
        f.write(struct.pack("<I", 0xA00ABCD))
    with pytest.raises((ValueError, AssertionError, struct.error,
                        EOFError, OSError, KeyError)):
        read_spec(path)


def test_fuzz_batch_lookup_parity(rng):
    """Property fuzz (round 5): random ragged prompt batches + random
    draft lengths — generate_batch_lookup must equal per-row single-engine
    greedy streams on every draw (accept/reject paths, eos-free)."""
    from distributed_llama_tpu.models import ArchType
    from distributed_llama_tpu.sampler import Sampler

    from test_model_forward import make_spec, dense_weights
    from test_speculative import _batch_engine, _engine

    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     vocab_size=96, seq_len=80)
    host, _ = dense_weights(spec, seed=57)

    for trial in range(4):
        b = int(rng.integers(2, 5))
        draft = int(rng.integers(1, 8))
        n = int(rng.integers(3, 14))
        prompts = [
            rng.integers(1, spec.vocab_size,
                         int(rng.integers(1, 9))).tolist()
            for _ in range(b)
        ]
        want = [
            _engine(spec, host).generate(
                p, n, Sampler(spec.vocab_size, 0.0, 0.9, 1,
                              backend="python")).tokens
            for p in prompts
        ]
        got = _batch_engine(spec, host, b).generate_batch_lookup(
            prompts, n, draft_len=draft)
        assert got == want, (trial, b, draft, n, prompts)
