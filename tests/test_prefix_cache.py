"""Radix prefix cache (runtime/prefix_cache.py): cross-request KV reuse.

The contracts under test: a prefix-cache HIT seeds a slot from arena
blocks and the greedy output stays TOKEN-IDENTICAL to a cold sequential
``Engine.generate`` run (seeded K/V is bitwise the K/V a cold prefill
would have written — exact-token-match at identical absolute positions,
same jitted programs); lookups return WHOLE blocks only and never cover
the entire prompt; eviction under a full pool can never free a block an
in-flight slot is pinned to; and a supervisor rebuild starts from an
EMPTY tree (the arena dies with the engine). f32 on CPU so the seeded
rows compare bit-exactly against the oracle (same discipline as
tests/test_scheduler.py).
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS
from distributed_llama_tpu.runtime.prefix_cache import PrefixCache
from distributed_llama_tpu.runtime.resilience import EngineSupervisor
from distributed_llama_tpu.runtime.scheduler import RequestError, Scheduler
from distributed_llama_tpu.sampler import Sampler

SEQ = 64
SYS = [7, 9, 23, 54, 11, 3, 88, 61]  # the shared "system prompt": 2 blocks of 4


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _oracle(spec, params, prompt, max_tokens):
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    return eng.generate(prompt, max_tokens,
                        Sampler(spec.vocab_size, temperature=0.0, topp=0.9,
                                seed=1)).tokens


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _sched(spec, params, *, batch=2, blocks=16, block_len=4, chunk=4):
    eng = Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    pc = PrefixCache(eng, num_blocks=blocks, block_len=block_len)
    return Scheduler(eng, chunk=chunk, prefix_cache=pc), pc


def _run(sched, req, limit=500):
    for _ in range(limit):
        if req.finished.is_set():
            return list(req.tokens(timeout=5.0))
        sched.step()
    raise AssertionError("scheduler did not finish the request")


def test_hit_parity_vs_cold_prefill(tiny):
    """A prefix-cache hit (seeded blocks + suffix prefill) must emit
    EXACTLY the cold run's greedy tokens — the seeded rows sit on the
    exact logits path of every subsequent forward, so token parity here
    is the end-to-end bit-exactness proof for the whole
    publish -> arena -> seed -> attend pipeline."""
    spec, params = tiny
    sched, pc = _sched(spec, params)
    pA = SYS + [101, 5, 17]
    pB = SYS + [40, 77]

    rA = sched.submit(pA, 6, _greedy(spec))
    assert _run(sched, rA) == _oracle(spec, params, pA, 6)
    assert pc.stats.hits == 0 and pc.stats.blocks_published >= 2

    rB = sched.submit(pB, 6, _greedy(spec))
    assert _run(sched, rB) == _oracle(spec, params, pB, 6)
    assert pc.stats.hits == 1
    assert pc.stats.tokens_saved == len(SYS)  # both shared blocks seeded
    s = sched.stats.summary()
    assert s["prefix_cache"]["hit_rate"] == 0.5
    assert s["prefix_cache"]["tokens_saved"] == len(SYS)


def test_partial_block_returns_whole_blocks_only(tiny):
    """A prefix sharing a non-block-aligned number of tokens matches only
    its WHOLE blocks (partial blocks are never indexed), and a prompt
    EQUAL to a cached prefix is capped at len - 1 so the finishing chunk
    still samples real logits."""
    spec, params = tiny
    sched, pc = _sched(spec, params)
    base = SYS + [33, 2]  # 10 tokens: 2 whole blocks + 2 remainder
    r0 = sched.submit(base, 3, _greedy(spec))
    _run(sched, r0)

    # shares 9 tokens with `base` -> only 2 whole blocks (8 tokens) seed
    p1 = base[:9] + [90, 14]
    r1 = sched.submit(p1, 4, _greedy(spec))
    assert _run(sched, r1) == _oracle(spec, params, p1, 4)
    assert pc.stats.tokens_saved == 8

    # the EXACT cached prompt (10 tokens): usable = (10 - 1) // 4 = 2
    # blocks again, never the full prompt — and parity still holds
    r2 = sched.submit(list(base), 4, _greedy(spec))
    assert _run(sched, r2) == _oracle(spec, params, base, 4)
    assert pc.stats.tokens_saved == 16
    assert pc.stats.hits == 2


def test_refcount_protected_eviction_under_full_pool(tiny):
    """With every pool block pinned by an in-flight slot, a publish that
    needs a block DROPS (publish_drops) instead of evicting — eviction
    must never free a block a live slot was seeded from. Once the pin is
    released, the same pressure evicts the LRU leaf."""
    spec, params = tiny
    sched, pc = _sched(spec, params, blocks=2)  # pool == the shared prefix
    p_shared = SYS + [101]
    other = [2, 40, 77, 12, 9, 31, 66, 90]      # a disjoint 2-block prompt

    r0 = sched.submit(p_shared, 1, _greedy(spec))
    _run(sched, r0)
    assert pc.stats.blocks_published == 2 and not pc._free

    # r1 seeds from both blocks and HOLDS them pinned while it decodes
    r1 = sched.submit(p_shared, 30, _greedy(spec))
    for _ in range(6):
        sched.step()
    assert not r1.finished.is_set() and pc.stats.hits == 1

    # r2 finishes while r1 is in flight; its publish finds the pool full
    # of PINNED blocks -> dropped, nothing evicted, r1's source survives
    r2 = sched.submit(other, 1, _greedy(spec))
    while not r2.finished.is_set():
        sched.step()
    assert pc.stats.publish_drops >= 1
    assert pc.stats.evictions == 0
    assert len(pc._walk(p_shared, 2)) == 2  # both blocks still indexed

    while not r1.finished.is_set():
        sched.step()
    assert _run(sched, r1) == _oracle(spec, params, p_shared, 30)

    # pins released: the same pressure now evicts the LRU leaf
    r3 = sched.submit(other, 1, _greedy(spec))
    _run(sched, r3)
    assert pc.stats.evictions >= 1


def test_publish_never_evicts_its_own_walk_path(tiny):
    """A publish whose allocation pressure lands on the pool it is
    standing on must DROP, not evict a walk-path node — evicting one
    would attach the next block under a detached parent, leaking an
    unreachable subtree (found by review). Scenario: the pool holds
    exactly prompt A's two blocks; a longer prompt EXTENDING A dedups
    through them and then needs a third — its only eviction candidate
    is A's leaf, the node the walk stands on."""
    spec, params = tiny
    sched, pc = _sched(spec, params, blocks=2)
    prompt_a = SYS                    # exactly 2 blocks of 4
    prompt_b = SYS + [5, 17, 40, 77]  # extends A by one more block
    r0 = sched.submit(prompt_a + [101], 1, _greedy(spec))
    _run(sched, r0)
    assert pc.stats.blocks_in_use == 2 and not pc._free

    rb = sched.submit(prompt_b + [33], 1, _greedy(spec))
    _run(sched, rb)
    # B's third block was dropped (the only candidate was its own walk
    # path); A's chain stayed reachable and nothing leaked
    assert len(pc._walk(prompt_b, 3)) == 2
    assert pc.stats.publish_drops >= 1 and pc.stats.evictions == 0
    assert pc.stats.blocks_in_use == 2 and not pc._free

    # with no walk in flight, unrelated pressure can still evict
    r2 = sched.submit([2, 6, 10, 14, 18, 22, 26, 30], 1, _greedy(spec))
    _run(sched, r2)
    assert pc.stats.evictions >= 1


def test_supervisor_rebuild_invalidates_tree(tiny):
    """Crash recovery (runtime/faults.py step_raise through the
    EngineSupervisor) must start the new generation from an EMPTY tree:
    the arena died with the engine, so nothing the old generation
    published may seed a rebuilt engine's slots — and requests after
    recovery still hit full greedy parity from the fresh cache."""
    spec, params = tiny

    def factory():
        return Engine(spec, params, batch=2, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32)

    sup = EngineSupervisor(factory, chunk=8, stall_timeout=60.0,
                           backoff_base=0.01, prefix_blocks=16,
                           prefix_block_len=4)
    try:
        prompt = SYS + [101, 5]
        r0 = sup.submit(prompt, 3, _greedy(spec))
        assert list(r0.tokens(timeout=30.0)) == _oracle(spec, params,
                                                        prompt, 3)
        pc_old = sup.prefix_cache
        assert pc_old.stats.blocks_published >= 2

        FAULTS.arm("step_raise")  # next step crashes mid-generation
        r1 = sup.submit(prompt, 8, _greedy(spec))
        with pytest.raises(RequestError):
            list(r1.tokens(timeout=30.0))

        end = __import__("time").perf_counter() + 30.0
        while (__import__("time").perf_counter() < end
               and sup.sup_stats.recoveries < 1):
            __import__("time").sleep(0.01)
        assert sup.sup_stats.recoveries == 1

        pc_new = sup.prefix_cache
        assert pc_new is not pc_old
        assert pc_old.stats.invalidations >= 1  # abort dropped the tree
        assert pc_new.stats.blocks_in_use == 0 and pc_new.stats.lookups == 0
        assert not pc_new._root.children

        # the rebuilt generation serves the same prompt from COLD (no
        # cross-generation seeding) and re-warms its own tree
        r2 = sup.submit(prompt, 3, _greedy(spec))
        assert list(r2.tokens(timeout=30.0)) == _oracle(spec, params,
                                                        prompt, 3)
        assert pc_new.stats.hits == 0 and pc_new.stats.blocks_published >= 2
    finally:
        sup.close()


def test_late_unpin_after_invalidate_cannot_double_allocate(tiny):
    """unpin() arriving AFTER invalidate() (a straggler path releasing a
    dead generation's pins) must not resurrect a detached node into the
    eviction heap: its block id is also on the rebuilt free list, and
    evicting it would hand the same arena block to two live nodes
    (found by review — depth >= 2 nodes keep their parent link, so the
    attachment check alone passes; the epoch stamp catches them)."""
    spec, params = tiny
    sched, pc = _sched(spec, params, blocks=2)
    prompt = SYS + [101]  # 2 blocks: a depth-2 chain
    r0 = sched.submit(prompt, 1, _greedy(spec))
    _run(sched, r0)
    n, ids, pins = pc.lookup_pin(prompt)
    assert n == len(SYS) and len(pins) == 2

    pc.invalidate()
    pc.unpin(pins)  # late release of pre-invalidate pins

    # drain the rebuilt free list, then demand one more block: the
    # detached depth-2 node must NOT be evictable (drop, not a second
    # hand-out of a block the free list already served)
    blocks = [pc._alloc() for _ in range(2)]
    assert sorted(blocks) == [0, 1]
    assert pc._alloc() is None
    assert pc.stats.evictions == 0 and pc.stats.blocks_in_use == 0


def test_cancel_and_deadline_release_pins(tiny):
    """Every slot-release path (cancel mid-decode, deadline expiry) must
    release its seed pins — a leaked pin would make its blocks
    permanently unevictable."""
    spec, params = tiny
    sched, pc = _sched(spec, params)
    r0 = sched.submit(SYS + [101], 1, _greedy(spec))
    _run(sched, r0)

    r1 = sched.submit(SYS + [40], 30, _greedy(spec))
    for _ in range(5):
        sched.step()
    assert pc.stats.hits == 1
    r1.cancel()
    sched.step()
    assert r1.finished.is_set() and r1.finish_reason == "cancelled"
    assert all(not s.pins for s in sched.slots)
    assert all(n.refs == 0 for n in pc._root.children.values())

    import time as _t
    r2 = sched.submit(SYS + [77], 30, _greedy(spec),
                      deadline=_t.perf_counter() + 0.15)
    for _ in range(5):
        sched.step()
    _t.sleep(0.2)
    sched.step()  # reaps the expired request
    assert r2.finished.is_set()
    assert all(not s.pins for s in sched.slots)
    assert all(n.refs == 0 for n in pc._root.children.values())


def test_eviction_heap_stays_bounded(tiny):
    """The lazy eviction heap must not grow one stale entry per request
    forever on a server whose pool never fills (eviction pops — the
    normal stale-entry drain — never run while the free list serves):
    pushes past the bound trigger compaction back to live candidates."""
    spec, params = tiny
    sched, pc = _sched(spec, params, blocks=2)
    r = sched.submit(SYS + [101], 1, _greedy(spec))
    _run(sched, r)
    for _ in range(200):  # steady-state churn: pin + unpin, no eviction
        _, _, pins = pc.lookup_pin(SYS + [40])
        pc.unpin(pins)
    assert len(pc._heap) <= max(4 * pc.num_blocks, 64) + 1


def test_warmup_on_full_pool_preserves_published_blocks(tiny):
    """Re-warming a long-lived scheduler whose pool is fully allocated
    must not clobber a live block's K/V (warmup's scratch publish only
    targets blocks still on the free list; with none free it is
    skipped) — a same-prefix request afterwards still seeds bit-exact."""
    spec, params = tiny
    sched, pc = _sched(spec, params, blocks=2)
    r0 = sched.submit(SYS + [101], 1, _greedy(spec))
    _run(sched, r0)
    assert not pc._free  # both blocks live
    sched.warmup()       # idle scheduler, full pool: publish skipped
    p = SYS + [40, 77]
    r1 = sched.submit(p, 4, _greedy(spec))
    assert _run(sched, r1) == _oracle(spec, params, p, 4)
    assert pc.stats.hits == 1


def test_warmup_is_state_neutral(tiny):
    """Scheduler.warmup with the prefix cache attached compiles the seed
    and publish executables without perturbing later outputs (the
    supervisor warms rebuilt engines this way before READY)."""
    spec, params = tiny
    sched, pc = _sched(spec, params)
    sched.warmup()
    assert pc.stats.blocks_in_use == 0  # nothing indexed by warmup
    p = SYS + [101, 5, 17]
    r = sched.submit(p, 6, _greedy(spec))
    assert _run(sched, r) == _oracle(spec, params, p, 6)
