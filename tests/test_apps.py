"""CLI + API server tests over a tiny end-to-end fixture model.

Exercises the app layer the reference never tested (SURVEY.md §4 notes the
absence of API-server tests): dllama generate/inference modes and the
OpenAI-compatible /v1/chat/completions route incl. SSE streaming
(ref: src/apps/dllama/dllama.cpp, src/apps/dllama-api/dllama-api.cpp).
"""

import http.client
import json
import threading

import numpy as np
import pytest

from distributed_llama_tpu.apps import dllama
from distributed_llama_tpu.apps.api_server import ApiState, make_handler
from distributed_llama_tpu.io import (
    TokenizerData, model_tensor_plan, write_model, write_tokenizer_file,
)
from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants import FloatType


def _fixture(tmp_path, rng, wt=FloatType.Q40):
    from distributed_llama_tpu.testing import write_fixture

    return write_fixture(tmp_path, rng=rng, weights_float_type=wt,
                         seq_len=192)


def test_cli_mesh_flags_end_to_end(tmp_path, rng, capsys):
    """--tp/--pp/--dp compose through the CLI on the virtual 8-device mesh:
    a dp-batched generation over tp-split weights in pp stages must produce
    the same tokens as the single-device run (greedy, fixed seed)."""
    mpath, tpath = _fixture(tmp_path, rng)
    # f32 buffers on both runs: the pp run force-disables q80, so the
    # baseline must not use it either or the comparison is approximate
    base_args = ["generate", "--model", mpath, "--tokenizer", tpath,
                 "--prompt", "ab", "--steps", "3", "--seed", "7",
                 "--temperature", "0", "--buffer-float-type", "f32"]
    dllama.main(base_args)
    want = capsys.readouterr().out
    dllama.main(base_args + ["--tp", "2", "--pp", "2", "--dp", "2"])
    got = capsys.readouterr().out
    # same generated text; the batched run reports its sequence count
    assert want.splitlines()[-1] in got


def test_cli_inference_mode(tmp_path, rng, capsys):
    mpath, tpath = _fixture(tmp_path, rng)
    dllama.main([
        "inference", "--model", mpath, "--tokenizer", tpath,
        "--prompt", "ab", "--steps", "4", "--seed", "7", "--temperature", "0",
    ])
    out = capsys.readouterr().out
    assert "Generated tokens:    4" in out
    assert "Avg generation time:" in out
    assert "🔶 G" in out  # per-token benchmark lines (ref: dllama.cpp:74-79)


def test_cli_worker_mode_rejected(tmp_path, rng):
    with pytest.raises(SystemExit):
        dllama.main(["worker", "--port", "9998"])


@pytest.fixture
def api_server(tmp_path, rng):
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny")
    from http.server import HTTPServer
    server = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address
    server.shutdown()


def test_api_models_route(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/v1/models")
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    assert body["data"][0]["id"] == "tiny"


def test_api_chat_completion(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=120)
    req = {"messages": [{"role": "user", "content": "ab"}],
           "max_tokens": 4, "temperature": 0}
    conn.request("POST", "/v1/chat/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    assert body["object"] == "chat.completion"
    choice = body["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert body["usage"]["completion_tokens"] <= 4
    assert body["usage"]["total_tokens"] == (
        body["usage"]["prompt_tokens"] + body["usage"]["completion_tokens"])


def test_api_chat_completion_streaming(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=120)
    req = {"messages": [{"role": "user", "content": "ab"}],
           "max_tokens": 3, "temperature": 0, "stream": True}
    conn.request("POST", "/v1/chat/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode()
    events = [line[len("data: "):] for line in raw.splitlines()
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert parsed[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    deltas = [p["choices"][0]["delta"].get("content", "") for p in parsed[:-1]]
    assert all(isinstance(d, str) for d in deltas)


def test_api_bad_json(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("POST", "/v1/chat/completions", "{not json",
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 400


def test_cli_profile_flag(tmp_path, rng, capsys):
    """--profile DIR writes a jax.profiler trace of the generation
    (net-new observability; the reference has no profiler hooks)."""
    import os

    mpath, tpath = _fixture(tmp_path, rng)
    pdir = str(tmp_path / "trace")
    dllama.main(["generate", "--model", mpath, "--tokenizer", tpath,
                 "--prompt", "ab", "--steps", "2", "--seed", "7",
                 "--temperature", "0", "--profile", pdir])
    out = capsys.readouterr().out
    assert "profiler trace written" in out
    found = [f for _, _, fs in os.walk(pdir) for f in fs]
    assert any(f.endswith((".pb", ".json.gz", ".xplane.pb")) for f in found), found
