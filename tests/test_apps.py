"""CLI + API server tests over a tiny end-to-end fixture model.

Exercises the app layer the reference never tested (SURVEY.md §4 notes the
absence of API-server tests): dllama generate/inference modes and the
OpenAI-compatible /v1/chat/completions route incl. SSE streaming
(ref: src/apps/dllama/dllama.cpp, src/apps/dllama-api/dllama-api.cpp).
"""

import http.client
import json
import threading

import numpy as np
import pytest

from distributed_llama_tpu.apps import dllama
from distributed_llama_tpu.apps.api_server import ApiState, make_handler
from distributed_llama_tpu.io import (
    TokenizerData, model_tensor_plan, write_model, write_tokenizer_file,
)
from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants import FloatType


def _fixture(tmp_path, rng, wt=FloatType.Q40):
    from distributed_llama_tpu.testing import write_fixture

    return write_fixture(tmp_path, rng=rng, weights_float_type=wt,
                         seq_len=192)


def test_cli_mesh_flags_end_to_end(tmp_path, rng, capsys):
    """--tp/--pp/--dp compose through the CLI on the virtual 8-device mesh:
    a dp-batched generation over tp-split weights in pp stages must produce
    the same tokens as the single-device run (greedy, fixed seed)."""
    mpath, tpath = _fixture(tmp_path, rng)
    # f32 buffers on both runs: the pp run force-disables q80, so the
    # baseline must not use it either or the comparison is approximate
    base_args = ["generate", "--model", mpath, "--tokenizer", tpath,
                 "--prompt", "ab", "--steps", "3", "--seed", "7",
                 "--temperature", "0", "--buffer-float-type", "f32"]
    dllama.main(base_args)
    want = capsys.readouterr().out
    dllama.main(base_args + ["--tp", "2", "--pp", "2", "--dp", "2"])
    got = capsys.readouterr().out
    # same generated text; the batched run reports its sequence count
    assert want.splitlines()[-1] in got


def test_cli_inference_mode(tmp_path, rng, capsys):
    mpath, tpath = _fixture(tmp_path, rng)
    dllama.main([
        "inference", "--model", mpath, "--tokenizer", tpath,
        "--prompt", "ab", "--steps", "4", "--seed", "7", "--temperature", "0",
    ])
    out = capsys.readouterr().out
    assert "Generated tokens:    4" in out
    assert "Avg generation time:" in out
    assert "🔶 G" in out  # per-token benchmark lines (ref: dllama.cpp:74-79)


def test_cli_inference_tp_trace_t_column(tmp_path, rng, capsys):
    """Benchmark mode on a multi-device mesh captures a trace for the
    per-step T column; on CPU the trace has no device plane, so the
    microbench fallback must keep the output intact (the TPU path is the
    same code with real per-step values — netstats.per_step_op_ms)."""
    mpath, tpath = _fixture(tmp_path, rng)
    dllama.main([
        "inference", "--model", mpath, "--tokenizer", tpath, "--tp", "2",
        "--prompt", "ab", "--steps", "3", "--seed", "7", "--temperature", "0",
    ])
    out = capsys.readouterr().out
    assert "🔶 G" in out and " T " in out
    assert "Avg transfer" in out


def test_per_step_op_ms_empty_trace(tmp_path):
    from distributed_llama_tpu.runtime.netstats import per_step_op_ms

    assert per_step_op_ms(str(tmp_path)) == []


def test_cli_worker_mode_rejected(tmp_path, rng):
    with pytest.raises(SystemExit):
        dllama.main(["worker", "--port", "9998"])


@pytest.fixture
def api_server(tmp_path, rng):
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny")
    from http.server import HTTPServer
    server = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address
    server.shutdown()


def test_api_models_route(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/v1/models")
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    assert body["data"][0]["id"] == "tiny"


def test_api_chat_completion(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=120)
    req = {"messages": [{"role": "user", "content": "ab"}],
           "max_tokens": 4, "temperature": 0}
    conn.request("POST", "/v1/chat/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    assert body["object"] == "chat.completion"
    choice = body["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert body["usage"]["completion_tokens"] <= 4
    assert body["usage"]["total_tokens"] == (
        body["usage"]["prompt_tokens"] + body["usage"]["completion_tokens"])


def test_api_chat_completion_streaming(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=120)
    req = {"messages": [{"role": "user", "content": "ab"}],
           "max_tokens": 3, "temperature": 0, "stream": True}
    conn.request("POST", "/v1/chat/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode()
    events = [line[len("data: "):] for line in raw.splitlines()
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert parsed[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    deltas = [p["choices"][0]["delta"].get("content", "") for p in parsed[:-1]]
    assert all(isinstance(d, str) for d in deltas)


def test_api_prefix_reuse_matches_stateless(tmp_path, rng):
    """Session/prefix reuse (VERDICT r2 #6): two chat requests sharing a
    system prompt — the second request must prefill only the suffix beyond
    the longest common token prefix, and its response must be byte-identical
    to a stateless (fresh-engine) handling of the same request."""
    from distributed_llama_tpu.apps.api_server import _completion_chunks

    mpath, tpath = _fixture(tmp_path, rng)

    def build_state():
        args = dllama.build_argparser().parse_args([
            "api", "--model", mpath, "--tokenizer", tpath,
            "--steps", "8", "--temperature", "0", "--seed", "3"])
        engine, tokenizer, sampler = dllama.build_engine(args)
        return ApiState(engine, tokenizer, sampler, model_name="tiny")

    def run(state, user):
        body = {"messages": [
            {"role": "system", "content": "abba"},
            {"role": "user", "content": user}],
            "max_tokens": 4, "temperature": 0}
        return list(_completion_chunks(state, body))

    # stateless oracle: fresh engine per request
    want_1 = run(build_state(), "ab")
    want_2 = run(build_state(), "ba")

    # shared-session path: one state across both requests; record how many
    # tokens each request actually prefilled
    state = build_state()
    prefills = []
    orig_prefill = state.engine.prefill

    def spy(suffix):
        prefills.append(len(suffix))
        return orig_prefill(suffix)

    state.engine.prefill = spy
    got_1 = run(state, "ab")
    full_len = prefills[0]
    got_2 = run(state, "ba")
    assert got_1 == want_1
    assert got_2 == want_2  # byte-identical responses
    # the shared system-prompt prefix was NOT re-prefilled
    assert len(prefills) == 2 and 0 < prefills[1] < full_len, prefills


def test_api_lookup_negative_temp_keeps_prefix_cache_aligned(tmp_path, rng):
    """ADVICE r4 (medium): with --lookup-decode on, a request carrying a
    NEGATIVE temperature falls through to the plain sampled loop; history
    bookkeeping must not double-append there, or cached_tokens drifts from
    the real K/V positions and every later prefix-reuse request decodes
    against wrong cache contents. Serve (negative-temp, then greedy) on one
    state and require the greedy follow-up byte-identical to stateless."""
    from distributed_llama_tpu.apps.api_server import _completion_chunks

    mpath, tpath = _fixture(tmp_path, rng)

    def build_state():
        args = dllama.build_argparser().parse_args([
            "api", "--model", mpath, "--tokenizer", tpath,
            "--steps", "8", "--temperature", "0", "--seed", "3",
            "--lookup-decode", "4"])
        engine, tokenizer, sampler = dllama.build_engine(args)
        return ApiState(engine, tokenizer, sampler, model_name="tiny",
                        lookup_decode=4)

    def run(state, user, temp):
        body = {"messages": [
            {"role": "system", "content": "abba"},
            {"role": "user", "content": user}],
            "max_tokens": 4, "temperature": temp}
        return list(_completion_chunks(state, body))

    want_2 = run(build_state(), "ba", 0)  # stateless oracle for request 2

    state = build_state()
    run(state, "ab", -1.0)  # negative temp: plain loop despite lookup on
    # the cache map must exactly mirror the engine's written K/V positions
    assert len(state.cached_tokens) == state.engine.pos
    got_2 = run(state, "ba", 0)
    assert got_2 == want_2


def test_api_session_survives_restart(tmp_path, rng):
    """API session persistence (VERDICT r3 weak #6): serve request A, save
    the session (the server's shutdown path), rebuild the server process
    state, load the session, then serve A + a follow-up — the follow-up
    must prefill ONLY the suffix beyond the restored prefix and its
    response must be byte-identical to the no-restart path."""
    from distributed_llama_tpu.apps.api_server import (
        _completion_chunks, build_chat_prompt, load_server_session,
        save_server_session)

    from distributed_llama_tpu.testing import write_fixture

    # the two-turn conversation runs ~272 prompt tokens — needs more
    # context than the shared 192-token fixture
    mpath, tpath = write_fixture(tmp_path, rng=rng, seq_len=384)
    spath = str(tmp_path / "api_session.npz")

    def build_state():
        args = dllama.build_argparser().parse_args([
            "api", "--model", mpath, "--tokenizer", tpath,
            "--steps", "8", "--temperature", "0", "--seed", "3"])
        engine, tokenizer, sampler = dllama.build_engine(args)
        return ApiState(engine, tokenizer, sampler, model_name="tiny")

    def body(messages):
        return {"messages": messages, "max_tokens": 4, "temperature": 0}

    msgs_a = [{"role": "system", "content": "abba"},
              {"role": "user", "content": "ab"}]
    # the follow-up extends the same conversation (assistant turn + new
    # user turn share the A prefix)
    msgs_b = msgs_a + [{"role": "assistant", "content": "x"},
                       {"role": "user", "content": "ba"}]

    # no-restart oracle: one state serves A then the follow-up
    ref = build_state()
    want_a = list(_completion_chunks(ref, body(msgs_a)))
    want_b = list(_completion_chunks(ref, body(msgs_b)))

    # restart path: serve A, save (shutdown), new process state, load
    s1 = build_state()
    got_a = list(_completion_chunks(s1, body(msgs_a)))
    assert got_a == want_a
    save_server_session(s1, spath)

    s2 = build_state()
    load_server_session(s2, spath)
    assert s2.engine.pos == s1.engine.pos
    prefills = []
    orig = s2.engine.prefill

    def spy(suffix):
        prefills.append(len(suffix))
        return orig(suffix)

    s2.engine.prefill = spy
    got_b = list(_completion_chunks(s2, body(msgs_b)))
    assert got_b == want_b  # byte-identical to the no-restart path
    # only the suffix beyond the restored prefix was prefilled
    n_full = len(s2.tokenizer.encode(build_chat_prompt(msgs_b)))
    assert len(prefills) == 1 and 0 < prefills[0] < n_full, (prefills, n_full)


def test_api_bad_json(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("POST", "/v1/chat/completions", "{not json",
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 400


def test_cli_profile_flag(tmp_path, rng, capsys):
    """--profile DIR writes a jax.profiler trace of the generation
    (net-new observability; the reference has no profiler hooks)."""
    import os

    mpath, tpath = _fixture(tmp_path, rng)
    pdir = str(tmp_path / "trace")
    dllama.main(["generate", "--model", mpath, "--tokenizer", tpath,
                 "--prompt", "ab", "--steps", "2", "--seed", "7",
                 "--temperature", "0", "--profile", pdir])
    out = capsys.readouterr().out
    assert "profiler trace written" in out
    found = [f for _, _, fs in os.walk(pdir) for f in fs]
    assert any(f.endswith((".pb", ".json.gz", ".xplane.pb")) for f in found), found


@pytest.fixture
def api_batch_server(tmp_path, rng):
    mpath, tpath = _fixture(tmp_path, rng)
    # f32: the batched step paths ("bpre"/"bvec") contain a bf16 dot
    # XLA's CPU thunks cannot execute (real target is TPU; the non-batch
    # API fixture keeps the bf16 default)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3",
        "--compute-dtype", "f32", "--cache-dtype", "f32"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny",
                     serve_batch=3)
    from http.server import HTTPServer
    server = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address, state
    server.shutdown()
    if state._scheduler is not None:
        # a leaked supervisor keeps its loop thread stepping forever —
        # later fault-injection tests would race it for armed faults
        state._scheduler.close()


def test_api_batch_completions_greedy_matches_singles(api_batch_server,
                                                      tmp_path, rng):
    """POST /v1/batch/completions: each row's greedy completion must be
    byte-identical to a fresh single-request server answering that prompt
    alone (ragged lengths — right-padded batch prefill per-row parity)."""
    (host, port), state = api_batch_server
    msgs = [[{"role": "user", "content": c}] for c in ("ab", "abab x", "b")]

    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"messages_list": msgs, "max_tokens": 5, "temperature": 0}
    conn.request("POST", "/v1/batch/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    assert body["object"] == "chat.completion"
    assert [c["index"] for c in body["choices"]] == [0, 1, 2]

    from distributed_llama_tpu.apps.api_server import _completion_chunks
    for i, m in enumerate(msgs):
        st = ApiState(state.engine, state.tokenizer, state.sampler)
        st.engine.reset()
        st.cached_tokens = []
        single = "".join(
            p for kind, p in _completion_chunks(
                st, {"messages": m, "max_tokens": 5, "temperature": 0})
            if kind == "piece")
        assert body["choices"][i]["message"]["content"] == single, i
    state.engine.reset()
    state.cached_tokens = []


def test_api_batch_completions_streaming_and_validation(api_batch_server):
    """SSE chunks carry per-row indices; oversized batches 400 cleanly."""
    (host, port), state = api_batch_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"messages_list": [[{"role": "user", "content": "ab"}]] * 2,
           "max_tokens": 3, "temperature": 0, "stream": True}
    conn.request("POST", "/v1/batch/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode()
    events = [line[len("data: "):] for line in raw.splitlines()
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert {p["choices"][0]["index"] for p in parsed} == {0, 1}
    finals = [p for p in parsed if p["choices"][0]["finish_reason"]]
    assert len(finals) == 2

    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"messages_list": [[{"role": "user", "content": "x"}]] * 4,
           "max_tokens": 2, "temperature": 0}
    conn.request("POST", "/v1/batch/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 400


def test_api_batch_speculative_matches_plain_batch(tmp_path, rng):
    """Batched speculation on the batch endpoint (round 5): with
    --lookup-decode on, a greedy batch request must return byte-identical
    choices to the plain batch path — sub-batch padding rows stay silent
    and per-row eos/stop handling is unchanged."""
    from distributed_llama_tpu.apps.api_server import (
        _batch_completion_chunks)

    mpath, tpath = _fixture(tmp_path, rng)

    def build_state(lookup):
        args = dllama.build_argparser().parse_args([
            "api", "--model", mpath, "--tokenizer", tpath,
            "--steps", "8", "--temperature", "0", "--seed", "3",
            "--compute-dtype", "f32", "--cache-dtype", "f32"])
        engine, tokenizer, sampler = dllama.build_engine(args)
        return ApiState(engine, tokenizer, sampler, model_name="tiny",
                        serve_batch=3, lookup_decode=lookup)

    # a 2-row request on a serve_batch=3 server: one padding row
    body = {"prompts": ["abab", "ba"], "max_tokens": 6, "temperature": 0}

    def collect(state):
        rows = {0: "", 1: ""}
        done = None
        for kind, payload in _batch_completion_chunks(state, dict(body)):
            if kind == "piece":
                i, piece = payload
                rows[i] += piece
            else:
                done = payload
        return rows, done

    # the lookup path bursts per row while the step loop interleaves, so
    # compare per-row text + the done envelope, not raw event order
    want_rows, want_done = collect(build_state(0))
    got_rows, got_done = collect(build_state(4))
    assert got_rows == want_rows
    assert got_done == want_done


def test_api_batch_max_tokens_zero_means_unlimited(api_batch_server):
    """ADVICE r4 (low): max_tokens: 0 on the batch endpoint must mean
    'generate to the context limit' like the single endpoint — not silently
    return one token per row."""
    (host, port), state = api_batch_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"prompts": ["ab", "ba"], "max_tokens": 0, "temperature": 0}
    conn.request("POST", "/v1/batch/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    # every row must run past a single token (to eos or the context limit)
    for c in body["choices"]:
        assert c["finish_reason"] in ("stop", "length")
    assert body["usage"]["completion_tokens"] > 2
    state.engine.reset()
    state.cached_tokens = []


def test_api_batch_endpoint_off_by_default(api_server):
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("POST", "/v1/batch/completions",
                 json.dumps({"prompts": ["x"]}),
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 404


def test_cli_dp_lookup_matches_plain(tmp_path, rng, capsys):
    """--dp + --lookup-decode (round 5, Engine.generate_batch_lookup):
    the replicated-prompt batch must stream row 0's EXACT greedy tokens,
    same as the plain --dp run and the single-sequence run."""
    from distributed_llama_tpu.testing import write_fixture

    mpath, tpath = write_fixture(tmp_path, seed=23)
    base = ["generate", "--model", mpath, "--tokenizer", tpath,
            "--prompt", "abab", "--steps", "6", "--seed", "7",
            "--temperature", "0", "--compute-dtype", "f32",
            "--cache-dtype", "f32"]

    def run(args):
        dllama.main(args)
        return [ln for ln in capsys.readouterr().out.splitlines()
                if ln.strip()][-1]

    single = run(list(base))
    plain = run(base + ["--dp", "2"])
    spec = run(base + ["--dp", "2", "--lookup-decode", "4"])
    assert plain == spec == single


@pytest.fixture
def sched_api_server(tmp_path, rng):
    """Threaded server with the continuous-batching scheduler on:
    /v1/completions and /v1/chat/completions enqueue onto the shared slot
    scheduler (f32 — the batched step paths contain bf16 dots XLA's CPU
    thunks cannot execute, same as the batch fixture)."""
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3",
        "--compute-dtype", "f32", "--cache-dtype", "f32"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny",
                     serve_batch=2, serve_chunk=16)
    from http.server import ThreadingHTTPServer
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address, state
    server.shutdown()
    if state._scheduler is not None:
        state._scheduler.close()


def _sse_events(raw: str) -> list:
    events = [line[len("data: "):] for line in raw.splitlines()
              if line.startswith("data: ")]
    assert events and events[-1] == "[DONE]"
    return [json.loads(e) for e in events[:-1]]


def test_api_threaded_concurrent_streaming_clients(sched_api_server):
    """Two concurrent streaming clients with different prompt lengths both
    complete through the shared scheduler, each with well-formed SSE."""
    (host, port), state = sched_api_server
    results = {}

    def client(key, content, n):
        conn = http.client.HTTPConnection(host, port, timeout=240)
        req = {"messages": [{"role": "user", "content": content}],
               "max_tokens": n, "temperature": 0, "stream": True}
        conn.request("POST", "/v1/chat/completions", json.dumps(req),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        results[key] = (resp.status, resp.getheader("Content-Type"),
                        resp.read().decode())

    threads = [threading.Thread(target=client, args=("a", "ab", 6)),
               threading.Thread(target=client,
                                args=("b", "abab baba abba x", 9))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
        assert not t.is_alive()

    for key in ("a", "b"):
        status, ctype, raw = results[key]
        assert status == 200
        assert ctype.startswith("text/event-stream")
        parsed = _sse_events(raw)
        # every chunk is a well-formed per-request envelope; exactly one
        # terminal chunk carries the finish_reason
        assert all(p["object"] == "chat.completion.chunk" for p in parsed)
        assert all(p["choices"][0]["index"] == 0 for p in parsed)
        finals = [p for p in parsed if p["choices"][0]["finish_reason"]]
        assert len(finals) == 1
        assert finals[0]["choices"][0]["finish_reason"] in ("stop", "length")
    assert len(state.scheduler().stats.requests) == 2


def test_api_sched_greedy_matches_legacy_single(sched_api_server, tmp_path,
                                                rng):
    """A greedy chat request served through the scheduler must be
    byte-identical to the legacy single-engine path answering it alone
    (continuous batching is a scheduling change, not a sampling one)."""
    from distributed_llama_tpu.apps.api_server import _completion_chunks

    (host, port), state = sched_api_server
    body = {"messages": [{"role": "user", "content": "abba"}],
            "max_tokens": 6, "temperature": 0}
    conn = http.client.HTTPConnection(host, port, timeout=240)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    got = json.loads(resp.read())["choices"][0]["message"]["content"]

    legacy = ApiState(state.engine, state.tokenizer, state.sampler)
    legacy.engine.reset()
    want = "".join(p for kind, p in _completion_chunks(legacy, dict(body))
                   if kind == "piece")
    assert got == want


def test_api_completions_route_scheduler(sched_api_server):
    """POST /v1/completions (raw prompt, no chat template) through the
    scheduler: valid text_completion envelope, consistent usage."""
    (host, port), state = sched_api_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"prompt": "ab", "max_tokens": 5, "temperature": 0}
    conn.request("POST", "/v1/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    body = json.loads(resp.read())
    assert body["object"] == "text_completion"
    choice = body["choices"][0]
    assert isinstance(choice["text"], str)
    assert choice["finish_reason"] in ("stop", "length")
    assert body["usage"]["completion_tokens"] <= 5
    assert body["usage"]["total_tokens"] == (
        body["usage"]["prompt_tokens"] + body["usage"]["completion_tokens"])


def test_api_completions_route_legacy(api_server):
    """The raw /v1/completions route also works without the scheduler
    (single engine behind the lock) — including SSE streaming."""
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"prompt": "ab", "max_tokens": 3, "temperature": 0,
           "stream": True}
    conn.request("POST", "/v1/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    parsed = _sse_events(resp.read().decode())
    assert all(p["object"] == "text_completion" for p in parsed)
    assert parsed[-1]["choices"][0]["finish_reason"] in ("stop", "length")


def test_api_sched_prompt_too_long_clean_400(sched_api_server):
    """A prompt larger than seq_len must return a clean 400 through the
    queued/threaded scheduler path (PromptTooLong from submit), and the
    server must keep serving afterwards."""
    (host, port), state = sched_api_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"messages": [{"role": "user", "content": "x" * 400}],
           "max_tokens": 2, "temperature": 0}
    conn.request("POST", "/v1/chat/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 400
    assert "tokens" in json.loads(resp.read())["error"]

    conn = http.client.HTTPConnection(host, port, timeout=240)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": "ab", "max_tokens": 2,
                             "temperature": 0}),
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 200


def test_api_stats_route(sched_api_server):
    """GET /stats exposes the scheduler's serving counters after a
    request has been served."""
    (host, port), state = sched_api_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": "ab", "max_tokens": 3,
                             "temperature": 0}),
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 200
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/stats")
    resp = conn.getresponse()
    assert resp.status == 200
    s = json.loads(resp.read())
    assert s["requests_finished"] >= 1
    assert s["tokens_out"] >= 1
    assert s["ttft_p50_ms"] is not None and s["ttft_p50_ms"] >= 0


@pytest.mark.parametrize("wt", [FloatType.F32, FloatType.Q80])
def test_cli_runs_f32_and_q80_weight_files(tmp_path, rng, capsys, wt):
    """The reference converts/serves q40, q80 AND f32 weight files
    (ref: converter/writer.py); q40 has dedicated kernels here, while q80/
    f32 run through the dense load path — pin that both actually DECODE
    (inference mode's stats line counts the generated tokens, so a load
    path that serves but silently emits nothing fails here)."""
    mpath, tpath = _fixture(tmp_path, rng, wt=wt)
    dllama.main(["inference", "--model", mpath, "--tokenizer", tpath,
                 "--prompt", "ab", "--steps", "4", "--seed", "7",
                 "--temperature", "0"])
    out = capsys.readouterr().out
    assert "Generated tokens:    4" in out, wt


# -- serving resilience at the HTTP layer (ISSUE 3) -------------------------


def test_api_healthz_readyz_routes(api_server):
    """Liveness and readiness on the legacy (scheduler-off) server:
    /healthz is the process-up probe, /readyz the routing signal."""
    host, port = api_server
    for path, key, want in (("/healthz", "status", "ok"),
                            ("/readyz", "status", "ready")):
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", path)
        resp = conn.getresponse()
        assert resp.status == 200, path
        assert json.loads(resp.read())[key] == want


def test_api_readyz_scheduler_states(sched_api_server):
    """/readyz with the supervisor: 'idle' before the first request builds
    it, 'ready' with the supervisor state once live."""
    (host, port), state = sched_api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/readyz")
    body = json.loads(conn.getresponse().read())
    assert body == {"status": "ready", "scheduler": "idle"}
    conn = http.client.HTTPConnection(host, port, timeout=240)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": "ab", "max_tokens": 2,
                             "temperature": 0}),
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 200
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/readyz")
    resp = conn.getresponse()
    assert resp.status == 200
    assert json.loads(resp.read())["state"] == "ready"
    # /stats now carries the resilience block too
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/stats")
    s = json.loads(conn.getresponse().read())
    assert s["state"] == "ready"
    assert s["resilience"]["recoveries"] == 0


def test_api_draining_rejects_posts_but_stays_alive(sched_api_server):
    """Graceful drain: POSTs 503 with Retry-After, /readyz goes unready,
    /healthz stays 200 (a liveness restart would cut the drain short)."""
    (host, port), state = sched_api_server
    state.draining = True
    try:
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "ab", "max_tokens": 2}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 503
        assert resp.getheader("Retry-After") is not None
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        assert resp.status == 503
        assert json.loads(resp.read())["status"] == "draining"
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["status"] == "draining"
    finally:
        state.draining = False


def test_api_sse_midstream_error_frame(sched_api_server):
    """ISSUE 3 satellite: an SSE client already streaming tokens when the
    step loop crashes must receive a structured error event and a
    terminated stream ([DONE]) — never a silent hang."""
    from distributed_llama_tpu.runtime.faults import FAULTS

    (host, port), state = sched_api_server
    try:
        # pace the step loop so the stream provably cannot COMPLETE before
        # the crash is armed below (warm caches make bare steps sub-ms)
        FAULTS.arm("slow_step", times=0, ms=25.0)
        conn = http.client.HTTPConnection(host, port, timeout=240)
        req = {"prompt": "abab", "max_tokens": 5000, "temperature": 0,
               "stream": True}
        conn.request("POST", "/v1/completions", json.dumps(req),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        # read until the first token chunk arrives — the stream is LIVE
        first = b""
        while not first.strip():
            first = resp.fp.readline()
        FAULTS.arm("step_raise")  # the next scheduler step crashes
        raw = first.decode() + resp.read().decode()
        events = [line[len("data: "):] for line in raw.splitlines()
                  if line.startswith("data: ")]
        assert events[-1] == "[DONE]"  # the stream TERMINATED cleanly
        parsed = [json.loads(e) for e in events[:-1]]
        errs = [p for p in parsed if "error" in p]
        assert len(errs) == 1, raw[-500:]
        assert errs[0]["error"]["code"] == "engine_error"
        assert "injected step_raise" in errs[0]["error"]["message"]
        finals = [p for p in parsed if p.get("choices")
                  and p["choices"][0]["finish_reason"]]
        assert finals and finals[-1]["choices"][0]["finish_reason"] == "error"
        # the supervisor recovers and the server keeps serving
        sup = state._scheduler
        deadline = 30.0
        import time as _time
        t0 = _time.perf_counter()
        while not sup.ready and _time.perf_counter() - t0 < deadline:
            _time.sleep(0.05)
        assert sup.ready, sup.state
        conn = http.client.HTTPConnection(host, port, timeout=240)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "ab", "max_tokens": 2,
                                 "temperature": 0}),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        assert sup.sup_stats.recoveries == 1
    finally:
        FAULTS.clear()


@pytest.fixture
def tight_queue_server(tmp_path, rng):
    """serve_batch=1 + queue_depth=1: one running slot, one queue seat —
    the third concurrent request must be REJECTED, not queued."""
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3",
        "--compute-dtype", "f32", "--cache-dtype", "f32"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny",
                     serve_batch=1, serve_chunk=16, queue_depth=1)
    from http.server import ThreadingHTTPServer
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address, state
    server.shutdown()
    if state._scheduler is not None:
        state._scheduler.close()


def test_api_queue_overflow_429_retry_after(tight_queue_server):
    """ISSUE 3: queue overflow returns a fast 429 + Retry-After instead of
    queueing unboundedly, and /readyz reports the saturated queue."""
    import time as _time

    from distributed_llama_tpu.runtime.faults import FAULTS

    (host, port), state = tight_queue_server
    results = {}

    def client(key, n):
        conn = http.client.HTTPConnection(host, port, timeout=240)
        req = {"prompt": "abab", "max_tokens": n, "temperature": 0,
               "stream": True}
        conn.request("POST", "/v1/completions", json.dumps(req),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        results[key] = (resp.status, resp.read().decode())

    try:
        FAULTS.arm("slow_step", times=0, ms=60.0)  # hold the slot busy
        a = threading.Thread(target=client, args=("a", 30), daemon=True)
        a.start()
        # wait until A occupies the slot
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < 30.0:
            sup = state._scheduler
            if sup is not None and any(
                    s.req is not None for s in sup._sched.slots):
                break
            _time.sleep(0.02)
        b = threading.Thread(target=client, args=("b", 2), daemon=True)
        b.start()  # takes the single queue seat
        t0 = _time.perf_counter()
        while len(state._scheduler._sched._queue) < 1:
            assert _time.perf_counter() - t0 < 30.0, "B never queued"
            _time.sleep(0.02)
        # C: queue full -> fast 429 with Retry-After
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "ab", "max_tokens": 2,
                                 "temperature": 0}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 429
        assert int(resp.getheader("Retry-After")) >= 1
        assert "queue full" in json.loads(resp.read())["error"]
        # readiness = engine healthy AND queue under bound
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/readyz")
        assert conn.getresponse().status == 503
        FAULTS.clear()  # let A and B finish normally
        a.join(timeout=240)
        b.join(timeout=240)
        assert not a.is_alive() and not b.is_alive()
        assert results["a"][0] == 200 and results["b"][0] == 200
        assert state._scheduler.stats.requests_rejected == 1
    finally:
        FAULTS.clear()


def test_api_batch_bad_temperature_is_400(api_batch_server):
    """A malformed request field on the batch endpoint is a deterministic
    client error: 400, never a retryable 503 'engine failure'."""
    (host, port), state = api_batch_server
    conn = http.client.HTTPConnection(host, port, timeout=240)
    req = {"prompts": ["ab"], "max_tokens": 2, "temperature": "hot"}
    conn.request("POST", "/v1/batch/completions", json.dumps(req),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 400
    assert resp.getheader("Retry-After") is None
    assert "ValueError" in json.loads(resp.read())["error"]


def test_api_batch_borrow_crash_triggers_recovery(api_batch_server):
    """A crash inside the exclusive borrow (the whole-batch generation
    itself) must reach the supervisor: recovery runs, the engine is
    rebuilt, and the endpoint serves again."""
    import time as _time

    from distributed_llama_tpu.apps.api_server import (
        _batch_completion_chunks)

    (host, port), state = api_batch_server
    sup = state.scheduler()

    def boom(*a, **k):
        raise RuntimeError("borrowed engine crashed")
        yield  # pragma: no cover — generator shape

    sup.engine.generate_batch_stream = boom
    body = {"prompts": ["ab", "ba"], "max_tokens": 3, "temperature": 0}
    with pytest.raises(RuntimeError, match="borrowed engine crashed"):
        list(_batch_completion_chunks(state, dict(body)))
    t0 = _time.perf_counter()
    while not sup.ready and _time.perf_counter() - t0 < 30.0:
        _time.sleep(0.05)
    assert sup.ready, sup.state
    assert sup.sup_stats.crashes == 1
    assert sup.sup_stats.recoveries == 1
    # the rebuilt engine serves the endpoint again, end to end
    conn = http.client.HTTPConnection(host, port, timeout=240)
    conn.request("POST", "/v1/batch/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    out = json.loads(resp.read())
    assert all(c["finish_reason"] in ("stop", "length")
               for c in out["choices"])


def test_session_pp_contract_rejected_at_parse():
    """VERDICT pp contract holes: --session with --pp > 1 (stage-stacked
    caches are not host-fetchable) and with --nnodes > 1 must be refused
    at PARSE time with a clear message — before any model load, cluster
    connect, or silent ignore."""
    with pytest.raises(SystemExit) as ei:
        dllama.main(["generate", "--model", "m", "--tokenizer", "t",
                     "--session", "s.bin", "--pp", "2"])
    assert "--session" in str(ei.value) and "--pp" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["chat", "--model", "m", "--tokenizer", "t",
                     "--session", "s.bin", "--pp", "4"])
    assert "--pp" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["generate", "--model", "m", "--tokenizer", "t",
                     "--session", "s.bin", "--nnodes", "2",
                     "--coordinator", "127.0.0.1:1"])
    assert "--nnodes" in str(ei.value)


def test_help_surfaces_q80_pp_exclusion():
    """The q80+pp collective exclusion must be discoverable from --help,
    not only from a runtime notice mid-run."""
    text = " ".join(dllama.build_argparser().format_help().split())
    # --buffer-float-type documents that q80 is ignored under --pp
    assert "q80 is ignored there" in text, text
    assert "quantized exchange cannot nest" in text.lower()
    # --pp documents both of its contract exclusions
    assert "--session is refused" in text
    # and the new cluster-resilience flags are documented
    for flag in ("--connect-timeout", "--heartbeat-interval",
                 "--worker-timeout"):
        assert flag in text, flag


def test_api_batch_lookup_streams_keepalives_before_completion(tmp_path,
                                                               rng,
                                                               monkeypatch):
    """ADVICE r5 low: the batch endpoint's greedy+lookup path buffers all
    rows (generate_batch_lookup) before the first data event — SSE
    keepalive comment frames must flow WHILE it collects, so bytes reach
    the client well before completion (no proxy/client idle timeout on
    long generations)."""
    import time as _time

    from distributed_llama_tpu.apps import api_server

    monkeypatch.setattr(api_server, "KEEPALIVE_SECS", 0.01)
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3",
        "--compute-dtype", "f32", "--cache-dtype", "f32"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny",
                     serve_batch=2, lookup_decode=4)
    from http.server import HTTPServer
    server = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    host, port = server.server_address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=240)
        req = {"prompts": ["abab", "ba"], "max_tokens": 6,
               "temperature": 0, "stream": True}
        conn.request("POST", "/v1/batch/completions", json.dumps(req),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        first_byte_at = None
        lines = []
        while True:
            line = resp.fp.readline()
            if first_byte_at is None and line:
                first_byte_at = _time.monotonic()
            lines.append(line.decode())
            if line.strip() == b"data: [DONE]":
                done_at = _time.monotonic()
                break
            assert line, lines  # EOF before [DONE] = broken stream
        # keepalive comments arrived, and BEFORE the first data event
        # (the collected path yields no piece until the whole batch is
        # done, so any earlier keepalive proves first-byte << completion)
        first_data = next(i for i, ln in enumerate(lines)
                          if ln.startswith("data: "))
        keepalives = [i for i, ln in enumerate(lines)
                      if ln.startswith(": keepalive")]
        assert keepalives, lines
        assert keepalives[0] < first_data, lines
        assert first_byte_at < done_at
        # the stream still ends with per-row finish chunks + [DONE]
        datas = [json.loads(ln[len("data: "):]) for ln in lines
                 if ln.startswith("data: ") and "[DONE]" not in ln]
        finals = [d for d in datas if d["choices"][0]["finish_reason"]]
        assert len(finals) == 2
    finally:
        server.shutdown()
        state.engine.reset()


def test_api_batch_lookup_stream_crash_yields_structured_error(tmp_path,
                                                               rng,
                                                               monkeypatch):
    """An engine crash surfacing BEHIND the keepalives (after the 200/SSE
    start) must follow the mid-stream error contract: an explicit
    {"error": ...} event then [DONE] — never a dropped connection."""
    import time as _time

    from distributed_llama_tpu.apps import api_server

    monkeypatch.setattr(api_server, "KEEPALIVE_SECS", 0.01)
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3",
        "--compute-dtype", "f32", "--cache-dtype", "f32"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny",
                     serve_batch=2, lookup_decode=4)
    sup = state.scheduler()  # build the supervisor, then wound its engine

    def boom(*a, **k):
        _time.sleep(0.05)  # long enough for a keepalive to have flowed
        raise RuntimeError("injected lookup crash")

    sup.engine.generate_batch_lookup = boom
    from http.server import HTTPServer
    server = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    host, port = server.server_address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=240)
        req = {"prompts": ["abab", "ba"], "max_tokens": 6,
               "temperature": 0, "stream": True}
        conn.request("POST", "/v1/batch/completions", json.dumps(req),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200  # SSE already started when it crashed
        raw = resp.read().decode()
        datas = [ln[len("data: "):] for ln in raw.splitlines()
                 if ln.startswith("data: ")]
        assert datas[-1] == "[DONE]", raw
        err_events = [json.loads(d) for d in datas[:-1]
                      if "error" in json.loads(d)]
        assert err_events and "injected lookup crash" in \
            err_events[0]["error"], raw
    finally:
        server.shutdown()
        if state._scheduler is not None:
            state._scheduler.close()


# -- multi-replica router tier at the HTTP layer (ISSUE 6) ------------------


def test_is_loopback_guard_shapes():
    """The /admin/* guard: the whole IPv4 loopback block, ::1, and the
    IPv6-mapped form pass; anything routable does not."""
    from distributed_llama_tpu.apps.api_server import _is_loopback

    for ok in ("127.0.0.1", "127.1.2.3", "::1", "::ffff:127.0.0.1"):
        assert _is_loopback(ok), ok
    for bad in ("10.0.0.1", "192.168.1.9", "0.0.0.0", "::ffff:10.0.0.1",
                "2001:db8::1", "128.0.0.1"):
        assert not _is_loopback(bad), bad


@pytest.fixture
def router_api_server(tmp_path, rng):
    """Threaded server with the 2-replica failover router in front of the
    continuous-batching scheduler (f32 for the same CPU-thunk reason as
    the other scheduler fixtures)."""
    mpath, tpath = _fixture(tmp_path, rng)
    args = dllama.build_argparser().parse_args([
        "api", "--model", mpath, "--tokenizer", tpath,
        "--steps", "8", "--temperature", "0", "--seed", "3",
        "--compute-dtype", "f32", "--cache-dtype", "f32"])
    engine, tokenizer, sampler = dllama.build_engine(args)
    state = ApiState(engine, tokenizer, sampler, model_name="tiny",
                     serve_batch=2, serve_chunk=16, replicas=2,
                     retry_budget=1)
    from http.server import ThreadingHTTPServer
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address, state
    server.shutdown()
    if state._scheduler is not None:
        state._scheduler.close()


def test_api_router_serves_and_reports_replicas(router_api_server):
    """The SAME handlers serve N replicas: a chat completion routes
    through the Router, /readyz carries per-replica states, and /stats
    aggregates counters with a `replicas` list + `router` block."""
    (host, port), state = router_api_server
    body = {"messages": [{"role": "user", "content": "ab"}],
            "max_tokens": 4, "temperature": 0}
    conn = http.client.HTTPConnection(host, port, timeout=240)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    out = json.loads(resp.read())
    assert out["choices"][0]["finish_reason"] in ("stop", "length")

    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/readyz")
    resp = conn.getresponse()
    assert resp.status == 200
    ready = json.loads(resp.read())
    assert ready["status"] == "ready"
    assert set(ready["replicas"]) == {"r0", "r1"}

    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/stats")
    s = json.loads(conn.getresponse().read())
    assert s["requests_finished"] >= 1
    assert s["router"]["replicas"] == 2
    assert s["router"]["routed"] >= 1
    assert len(s["replicas"]) == 2
    assert all("resilience" in r for r in s["replicas"])


def test_api_router_replica_failure_invisible_to_client(router_api_server):
    """Kill replica 0 mid-trace at the HTTP layer: the in-flight
    not-yet-streamed request retries on replica 1 and the client sees a
    clean 200 — byte-identical to the healthy answer — while /readyz
    stays 200 throughout."""
    from distributed_llama_tpu.runtime.faults import FAULTS

    (host, port), state = router_api_server
    body = {"messages": [{"role": "user", "content": "abba"}],
            "max_tokens": 5, "temperature": 0}

    def ask():
        conn = http.client.HTTPConnection(host, port, timeout=240)
        conn.request("POST", "/v1/chat/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    status, healthy = ask()  # also builds the router
    assert status == 200
    try:
        FAULTS.arm("replica_raise", key="r0", times=1)
        # the idle tie routes to r0 (its cache has no radix tree here, so
        # no cache bias): it dies pre-first-token, the router fails over
        status, failover = ask()
        assert status == 200
        assert failover["choices"][0]["message"]["content"] == \
            healthy["choices"][0]["message"]["content"]
        sup = state._scheduler
        assert sup.stats.retries >= 1 or FAULTS.fired("replica_raise") == 0
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/readyz")
        assert conn.getresponse().status == 200
    finally:
        FAULTS.clear()


def test_api_admin_reset_breaker_restores_broken_service(sched_api_server):
    """ISSUE 6 satellite: a BROKEN supervisor in api mode used to be an
    outage only a Python REPL could end — POST /admin/reset_breaker is
    the operator's HTTP half-open, and service resumes once the fault is
    gone."""
    import time as _time

    from distributed_llama_tpu.runtime.faults import FAULTS
    from distributed_llama_tpu.runtime.resilience import BROKEN, READY

    (host, port), state = sched_api_server

    def post(path, body):
        conn = http.client.HTTPConnection(host, port, timeout=240)
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    status, _ = post("/v1/completions", {"prompt": "ab", "max_tokens": 2,
                                         "temperature": 0})
    assert status == 200
    sup = state._scheduler
    try:
        FAULTS.arm("step_raise", times=0)  # every working step crashes
        t0 = _time.perf_counter()
        while sup.state != BROKEN and _time.perf_counter() - t0 < 60.0:
            try:
                post("/v1/completions", {"prompt": "ab", "max_tokens": 4,
                                         "temperature": 0})
            except Exception:  # noqa: BLE001 — a 503 path mid-recovery
                pass
            _time.sleep(0.05)
        assert sup.state == BROKEN, sup.state
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/readyz")
        assert conn.getresponse().status == 503
        FAULTS.clear()  # the fault is gone; the operator closes the circuit
        status, body = post("/admin/reset_breaker", {})
        assert status == 200 and body["status"] == "ok"
        t0 = _time.perf_counter()
        while sup.state != READY and _time.perf_counter() - t0 < 30.0:
            _time.sleep(0.05)
        status, _ = post("/v1/completions", {"prompt": "ab",
                                             "max_tokens": 2,
                                             "temperature": 0})
        assert status == 200
    finally:
        FAULTS.clear()


def test_api_admin_replica_ops_rolling_restart(router_api_server):
    """The rolling-restart recipe over HTTP: drain replica 0 (service
    stays ready on replica 1), restart it, repeat for replica 1 — the
    operator path docs/operations.md documents."""
    (host, port), state = router_api_server

    def post(path, body):
        conn = http.client.HTTPConnection(host, port, timeout=240)
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    status, _ = post("/v1/completions", {"prompt": "ab", "max_tokens": 2,
                                         "temperature": 0})
    assert status == 200  # router built
    for rid in (0, 1):
        status, body = post("/admin/drain_replica", {"replica": rid})
        assert status == 200 and body["status"] == "drained"
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        assert resp.status == 200  # the sibling keeps the service ready
        assert json.loads(resp.read())["replicas"][f"r{rid}"].endswith(
            "/draining")
        status, body = post("/admin/restart_replica", {"replica": rid})
        assert status == 200 and body["status"] == "restarted"
        status, _ = post("/v1/completions", {"prompt": "ab",
                                             "max_tokens": 2,
                                             "temperature": 0})
        assert status == 200
    assert state._scheduler.stats.restarts == 2
    # replica index validation is a clean 400
    status, body = post("/admin/restart_replica", {"replica": 9})
    assert status == 400 and "replica" in body["error"]


def test_api_admin_on_single_replica_and_legacy(api_server):
    """Admin endpoints never 404 by surprise: the legacy (no
    --serve-batch) server answers with a clear 404 + remedy; replica ops
    on a 1-replica server are a clean 400 (see the router fixture for the
    happy path)."""
    host, port = api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("POST", "/admin/reset_breaker", json.dumps({}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 404
    assert "--serve-batch" in json.loads(resp.read())["error"]


def test_admin_authorized_token_paths():
    """ISSUE 7 satellite (unit): loopback always passes; off-loopback
    needs an exact --admin-token bearer (constant-time compare) — no
    token configured means off-box is always refused, and a configured
    token never opens the door to a wrong or missing header."""
    from types import SimpleNamespace

    from distributed_llama_tpu.apps.api_server import _admin_authorized

    s = SimpleNamespace(admin_token="s3cret-tok")
    assert _admin_authorized(s, "127.0.0.1", None)          # loopback
    assert _admin_authorized(s, "::1", "Bearer wrong")      # still loopback
    assert _admin_authorized(s, "10.0.0.1", "Bearer s3cret-tok")
    assert not _admin_authorized(s, "10.0.0.1", None)
    assert not _admin_authorized(s, "10.0.0.1", "Bearer nope")
    assert not _admin_authorized(s, "10.0.0.1", "s3cret-tok")  # no scheme
    assert not _admin_authorized(s, "10.0.0.1", "bearer s3cret-tok")
    no_tok = SimpleNamespace(admin_token=None)
    assert not _admin_authorized(no_tok, "10.0.0.1", "Bearer s3cret-tok")
    assert _admin_authorized(no_tok, "127.0.0.1", None)


def test_api_admin_token_403_and_200_off_loopback(sched_api_server,
                                                  monkeypatch):
    """ISSUE 7 satellite (HTTP): with the caller simulated off-loopback,
    /admin/* is 403 without (or with a wrong) bearer and 200 with the
    configured --admin-token — the operator path for remote-replica
    deployments where loopback-only was an outage."""
    import distributed_llama_tpu.apps.api_server as api_mod

    (host, port), state = sched_api_server
    monkeypatch.setattr(api_mod, "_is_loopback", lambda addr: False)
    monkeypatch.setattr(state, "admin_token", "tok-123")

    def post(headers):
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/admin/reset_breaker", json.dumps({}),
                     {"Content-Type": "application/json", **headers})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    status, body = post({})
    assert status == 403 and "admin-token" in body["error"]
    status, _ = post({"Authorization": "Bearer wrong"})
    assert status == 403
    status, _ = post({"Authorization": "Bearer tok-123"})
    assert status == 200


def test_api_healthz_readyz_all_modes_never_404(api_server,
                                                sched_api_server,
                                                router_api_server):
    """ISSUE 6 satellite: a probe must never 404 depending on launch
    flags — /healthz and /readyz answer on the legacy single-engine
    server, the scheduler server, and the router server alike."""
    targets = [api_server, sched_api_server[0], router_api_server[0]]
    for host, port in targets:
        for path in ("/healthz", "/health", "/readyz"):
            conn = http.client.HTTPConnection(host, port, timeout=60)
            conn.request("GET", path)
            resp = conn.getresponse()
            assert resp.status in (200, 503), (host, port, path)
            assert resp.status != 404, (host, port, path)
            json.loads(resp.read())  # machine-readable either way


def test_replica_flags_rejected_without_serve_batch():
    """--replicas/--retry-budget/--route-policy are loud errors without
    --serve-batch (and retry/policy without --replicas), same dead-flag
    principle as the prefix-cache knobs — checked before any model
    load."""
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--replicas", "2"])
    assert "--serve-batch" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2", "--retry-budget", "3"])
    assert "--replicas" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2", "--route-policy",
                     "round_robin"])
    assert "--replicas" in str(ei.value)
    # an explicit 0 must hit the >= 1 error, not silently coerce to 1
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2", "--replicas", "0"])
    assert ">= 1" in str(ei.value)


def test_api_healthz_build_block_all_modes(api_server, sched_api_server,
                                           router_api_server):
    """ISSUE 10 satellite: /healthz carries the build-identity block —
    {version, jax, backend, mesh} — in every tier (never gated on a
    launch flag, the same rule as /metrics): version skew across a
    replica fleet must show on the probe everyone already scrapes."""
    import jax

    import distributed_llama_tpu as pkg

    targets = [api_server, sched_api_server[0], router_api_server[0]]
    for host, port in targets:
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200, (host, port)
        b = body["build"]
        assert b["version"] == pkg.__version__
        assert b["jax"] == jax.__version__
        assert b["backend"] == "cpu" and b["mesh"] == "single"


def test_api_metrics_build_info_series(sched_api_server):
    """dllama_build_info rides /metrics as the constant-1 info idiom."""
    (host, port), _state = sched_api_server
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    body = resp.read().decode()
    assert resp.status == 200
    line = next(ln for ln in body.splitlines()
                if ln.startswith("dllama_build_info{"))
    assert 'backend="cpu"' in line and 'mesh="single"' in line
    assert line.endswith(" 1")


def test_api_admin_profile_captures_and_validates(sched_api_server,
                                                  tmp_path, monkeypatch):
    """POST /admin/profile?ms=N: loopback 200 with the trace dir in the
    body (the capture ran synchronously), garbage ms a clean 400 —
    and off-loopback it is guarded exactly like every /admin/* verb."""
    import distributed_llama_tpu.apps.api_server as api_mod

    (host, port), state = sched_api_server
    monkeypatch.setattr(state, "profile_dir", str(tmp_path / "prof"))

    def post(path, headers=None):
        conn = http.client.HTTPConnection(host, port, timeout=120)
        conn.request("POST", path, json.dumps({}),
                     {"Content-Type": "application/json", **(headers or {})})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    status, body = post("/admin/profile?ms=20")
    assert status == 200, body
    assert body["status"] == "ok" and body["ms"] == 20.0
    assert body["dir"].startswith(str(tmp_path / "prof"))
    import os
    assert os.path.isdir(body["dir"])

    for bad in ("ms=zz", "ms=-5", "ms=0", "ms=900000"):
        status, body = post(f"/admin/profile?{bad}")
        assert status == 400, (bad, body)

    # off-loopback: same guard as every admin verb (the chaos job pins
    # the process-tier variant in tests/test_replica_procs.py)
    monkeypatch.setattr(api_mod, "_is_loopback", lambda addr: False)
    status, body = post("/admin/profile?ms=10")
    assert status == 403 and "admin" in body["error"]
    monkeypatch.setattr(state, "admin_token", "tok-9")
    status, _ = post("/admin/profile?ms=10",
                     {"Authorization": "Bearer tok-9"})
    assert status == 200


def test_profiler_flags_rejected_without_serve_batch():
    """--freeze-compiles/--profile-sample hang off the slot scheduler
    (warmup arms the sentinel; the sampler hooks steps) — dead flags
    without --serve-batch, same principle as the router/trace knobs."""
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--freeze-compiles"])
    assert "--serve-batch" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--profile-sample", "8"])
    assert "--serve-batch" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        dllama.main(["api", "--model", "m", "--tokenizer", "t",
                     "--serve-batch", "2", "--profile-sample", "0"])
    assert ">= 1" in str(ei.value)
