"""Multi-host cluster tests: a REAL two-process jax.distributed run.

The reference's only multi-node testing was a manual screen-session script
(ref: examples/n-workers.sh; SURVEY.md §4 notes the gap). Here the root +
worker protocol (parallel/multihost.py, apps/dllama.py cmd_worker) runs as
two actual OS processes, 1 virtual CPU device each, forming one global
2-device tp mesh over the jax.distributed coordinator — and the cluster's
greedy transcript must equal a single-process run of the same model.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from distributed_llama_tpu.io import (
    TokenizerData, model_tensor_plan, write_model, write_tokenizer_file,
)
from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants import FloatType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# pins the CPU platform before any backend init (a sitecustomize hook may
# otherwise pin a TPU plugin) and runs the real CLI main
WRAPPER = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
           "import sys; from distributed_llama_tpu.apps.dllama import main; "
           "main(sys.argv[1:])")


def _fixture(tmp_path):
    spec = ModelSpec(
        arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2, n_heads=4,
        n_kv_heads=2, vocab_size=288, seq_len=96, hidden_act=HiddenAct.SILU,
        weights_float_type=FloatType.Q40)
    rng = np.random.default_rng(77)
    tensors = {name: rng.standard_normal(shape).astype(np.float32) * 0.05
               for name, shape, _ in model_tensor_plan(spec)}
    mpath = str(tmp_path / "model.m")
    write_model(mpath, spec, tensors)
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{b:02X}>".encode() for b in range(256)]
    while len(vocab) < spec.vocab_size:
        vocab.append(f"<fill{len(vocab)}>".encode())
    tpath = str(tmp_path / "tok.t")
    write_tokenizer_file(tpath, TokenizerData(
        vocab=vocab, scores=[0.0] * len(vocab), bos_id=1, eos_id=2))
    return mpath, tpath


def _run(cli_args, n_local_devices=1, timeout=600):
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local_devices}")
    env.pop("JAX_PLATFORMS", None)  # the wrapper pins cpu via jax.config
    return subprocess.Popen(
        [sys.executable, "-c", WRAPPER, *cli_args],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True), timeout


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _gen_line(out: str) -> str:
    """The generated-text line: last non-empty stdout line."""
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert lines, out
    return lines[-1]


def test_two_process_cluster_matches_single(tmp_path):
    mpath, tpath = _fixture(tmp_path)
    base = ["--model", mpath, "--tokenizer", tpath, "--prompt", "ab",
            "--steps", "6", "--seed", "7", "--temperature", "0",
            "--buffer-float-type", "f32"]

    # single-process reference transcript (1 virtual device, no mesh)
    p, t = _run(["generate", *base])
    out_single, err = p.communicate(timeout=t)
    assert p.returncode == 0, err

    # two-process cluster: rank 0 root (generate) + rank 1 worker, 1 device
    # each -> a global 2-device tp mesh over the coordinator
    port = _free_port()
    cluster = ["--nnodes", "2", "--coordinator", f"127.0.0.1:{port}"]
    root, t = _run(["generate", *base, *cluster, "--node-rank", "0"])
    worker, _ = _run(["worker", "--model", mpath, "--tokenizer", tpath,
                      "--temperature", "0", "--buffer-float-type", "f32",
                      *cluster, "--node-rank", "1"])
    out_root, err_root = root.communicate(timeout=t)
    out_worker, err_worker = worker.communicate(timeout=t)
    assert root.returncode == 0, (out_root, err_root)
    assert worker.returncode == 0, (out_worker, err_worker)

    assert _gen_line(out_root) == _gen_line(out_single), (
        out_root, out_single)
    assert "worker rank 1 of 2 ready" in out_worker
    assert "root shut down" in out_worker


def test_worker_mode_requires_cluster_flags():
    from distributed_llama_tpu.apps import dllama

    with pytest.raises(SystemExit):
        dllama.main(["worker", "--port", "9998"])
    with pytest.raises(SystemExit):  # nnodes without coordinator
        dllama.main(["generate", "--nnodes", "2"])
    with pytest.raises(SystemExit):  # non-root rank must be a worker
        dllama.main(["generate", "--nnodes", "2", "--node-rank", "1",
                     "--coordinator", "127.0.0.1:1"])
    with pytest.raises(SystemExit):  # root rank cannot be a worker
        dllama.main(["worker", "--nnodes", "2", "--node-rank", "0",
                     "--coordinator", "127.0.0.1:1"])


def test_single_process_protocol_helpers():
    """is_multihost/fetch_logits degrade to no-ops off-cluster."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel.multihost import is_multihost
    from distributed_llama_tpu.parallel.mesh import make_mesh

    assert not is_multihost(None)
    assert not is_multihost(make_mesh(tp=2, devices=jax.devices()[:2]))
