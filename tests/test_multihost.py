"""Multi-host cluster tests: a REAL two-process jax.distributed run.

The reference's only multi-node testing was a manual screen-session script
(ref: examples/n-workers.sh; SURVEY.md §4 notes the gap). Here the root +
worker protocol (parallel/multihost.py, apps/dllama.py cmd_worker) runs as
two actual OS processes, 1 virtual CPU device each, forming one global
2-device tp mesh over the jax.distributed coordinator — and the cluster's
greedy transcript must equal a single-process run of the same model.
"""

import os
import subprocess
import sys

import pytest

from distributed_llama_tpu.testing import write_fixture

# compile-heavy SPMD meshes / subprocess clusters: the slow tier (pytest.ini)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# pins the CPU platform before any backend init (a sitecustomize hook may
# otherwise pin a TPU plugin) and runs the real CLI main
WRAPPER = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
           "import sys; from distributed_llama_tpu.apps.dllama import main; "
           "main(sys.argv[1:])")


def _fixture(tmp_path):
    return write_fixture(tmp_path, seed=77)


def _run(cli_args, n_local_devices=1, timeout=600):
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local_devices}")
    env.pop("JAX_PLATFORMS", None)  # the wrapper pins cpu via jax.config
    return subprocess.Popen(
        [sys.executable, "-c", WRAPPER, *cli_args],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True), timeout


def _free_port() -> int:
    from distributed_llama_tpu.testing import free_port

    return free_port()


def _gen_line(out: str) -> str:
    """The generated-text line: last non-empty stdout line."""
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert lines, out
    return lines[-1]


def test_two_process_cluster_matches_single(tmp_path):
    mpath, tpath = _fixture(tmp_path)
    base = ["--model", mpath, "--tokenizer", tpath, "--prompt", "ab",
            "--steps", "6", "--seed", "7", "--temperature", "0",
            "--buffer-float-type", "f32"]

    # single-process reference transcript (1 virtual device, no mesh)
    p, t = _run(["generate", *base])
    out_single, err = p.communicate(timeout=t)
    assert p.returncode == 0, err

    # two-process cluster: rank 0 root (generate) + rank 1 worker, 1 device
    # each -> a global 2-device tp mesh over the coordinator
    port = _free_port()
    cluster = ["--nnodes", "2", "--coordinator", f"127.0.0.1:{port}"]
    root, t = _run(["generate", *base, *cluster, "--node-rank", "0"])
    worker, _ = _run(["worker", "--model", mpath, "--tokenizer", tpath,
                      "--temperature", "0", "--buffer-float-type", "f32",
                      *cluster, "--node-rank", "1"])
    out_root, err_root = root.communicate(timeout=t)
    out_worker, err_worker = worker.communicate(timeout=t)
    assert root.returncode == 0, (out_root, err_root)
    assert worker.returncode == 0, (out_worker, err_worker)

    assert _gen_line(out_root) == _gen_line(out_single), (
        out_root, out_single)
    assert "worker rank 1 of 2 ready" in out_worker
    assert "root shut down" in out_worker


def test_two_process_cluster_push_weights_fileless_worker(tmp_path):
    """Root-push weight distribution (VERDICT r4 #8): the worker starts
    with NO model file — rank 0 broadcasts the spec + every tensor's raw
    bytes (parallel/multihost.bcast_spec / bcast_model_tensors, the
    reference's per-worker TCP weight push, transformer.cpp:562-591) and
    the cluster transcript must still equal the single-process run."""
    mpath, tpath = _fixture(tmp_path)
    base = ["--model", mpath, "--tokenizer", tpath, "--prompt", "ab",
            "--steps", "6", "--seed", "7", "--temperature", "0",
            "--buffer-float-type", "f32"]

    p, t = _run(["generate", *base])
    out_single, err = p.communicate(timeout=t)
    assert p.returncode == 0, err

    port = _free_port()
    cluster = ["--nnodes", "2", "--coordinator", f"127.0.0.1:{port}",
               "--push-weights"]
    root, t = _run(["generate", *base, *cluster, "--node-rank", "0"])
    # the worker gets NO --model flag at all — spec and weights arrive
    # over the broadcast protocol
    worker, _ = _run(["worker", "--tokenizer", tpath,
                      "--temperature", "0", "--buffer-float-type", "f32",
                      *cluster, "--node-rank", "1"])
    out_root, err_root = root.communicate(timeout=t)
    out_worker, err_worker = worker.communicate(timeout=t)
    assert root.returncode == 0, (out_root, err_root)
    assert worker.returncode == 0, (out_worker, err_worker)
    assert _gen_line(out_root) == _gen_line(out_single), (
        out_root, out_single)
    assert "<pushed>" in out_worker  # the worker really had no file
    assert "root shut down" in out_worker


def test_two_process_cluster_lookup_decode(tmp_path):
    """--lookup-decode over a 2-process cluster: drafts are mined from the
    replicated token stream, so both processes compute the same verify
    widths in lock-step and the transcript matches the single-process
    speculative run (the worker replays via the MSG_RUN lookup field)."""
    mpath, tpath = _fixture(tmp_path)
    base = ["--model", mpath, "--tokenizer", tpath, "--prompt", "abab",
            "--steps", "8", "--seed", "7", "--temperature", "0",
            "--buffer-float-type", "f32", "--lookup-decode", "5"]

    p, t = _run(["generate", *base])
    out_single, err = p.communicate(timeout=t)
    assert p.returncode == 0, err

    port = _free_port()
    cluster = ["--nnodes", "2", "--coordinator", f"127.0.0.1:{port}"]
    root, t = _run(["generate", *base, *cluster, "--node-rank", "0"])
    # --lookup-decode is part of the cluster config fingerprint (API mode
    # needs flag parity), so the worker passes it too; the RUN header's
    # draft length is still what the replay uses
    worker, _ = _run(["worker", "--model", mpath, "--tokenizer", tpath,
                      "--temperature", "0", "--buffer-float-type", "f32",
                      "--lookup-decode", "5",
                      *cluster, "--node-rank", "1"])
    out_root, err_root = root.communicate(timeout=t)
    out_worker, err_worker = worker.communicate(timeout=t)
    assert root.returncode == 0, (out_root, err_root)
    assert worker.returncode == 0, (out_worker, err_worker)
    assert _gen_line(out_root) == _gen_line(out_single), (
        out_root, out_single)


def _post_completion(port: int, body: dict, deadline: float = 240.0) -> dict:
    """POST /v1/chat/completions, retrying until the server accepts."""
    import http.client
    import json
    import time

    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
            conn.request("POST", "/v1/chat/completions", json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = json.loads(resp.read())
            conn.close()
            return data
        except (ConnectionRefusedError, OSError) as e:
            last = e
            time.sleep(1.0)
    raise TimeoutError(f"server never came up: {last}")


def _stop(proc) -> tuple[str, str]:
    """Terminate a server/worker subprocess, escalating to SIGKILL (the api
    root blocks in serve_forever; workers may be blocked in a collective).
    Drains and returns (stdout, stderr) so failures carry diagnostics and
    the pipes can't fill up or leak."""
    proc.terminate()
    try:
        return proc.communicate(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        return proc.communicate(timeout=10)


@pytest.mark.parametrize("lookup", [0, 5])
def test_two_process_cluster_api_mode(tmp_path, lookup):
    """api mode over a 2-process cluster: the worker replays each request
    from its broadcast JSON body; the completion must equal the
    single-process server's. lookup=5 exercises speculative replay — both
    processes must carry the same --lookup-decode (it is in the cluster
    config fingerprint) and mine identical drafts from the replayed
    request, keeping the verify widths in lock-step."""
    mpath, tpath = _fixture(tmp_path)
    body = {"messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 5, "temperature": 0}
    lk = ["--lookup-decode", str(lookup)] if lookup else []

    def run_api(extra, http_port):
        # f32 buffers: default q80 would give the tp=2 cluster lossy
        # quantized reduces vs the single run's exact ones (same pinning as
        # test_two_process_cluster_matches_single)
        return _run(["api", "--model", mpath, "--tokenizer", tpath,
                     "--temperature", "0", "--seed", "11",
                     "--buffer-float-type", "f32", *lk,
                     "--port", str(http_port), "--host", "127.0.0.1", *extra])

    # single-process reference completion
    port1 = _free_port()
    single, _ = run_api([], port1)
    try:
        want = _post_completion(port1, body)
    finally:
        _, err = _stop(single)
        print("single server stderr:", err[-2000:])  # shown on failure

    # two-process cluster (root api + worker)
    port2, cport = _free_port(), _free_port()
    cluster = ["--nnodes", "2", "--coordinator", f"127.0.0.1:{cport}"]
    root, _ = run_api([*cluster, "--node-rank", "0"], port2)
    worker, _ = _run(["worker", "--model", mpath, "--tokenizer", tpath,
                      "--temperature", "0", "--seed", "11",
                      "--buffer-float-type", "f32", *lk,
                      *cluster, "--node-rank", "1"])
    try:
        got = _post_completion(port2, body)
        # same completion text and token accounting as the single server
        assert (got["choices"][0]["message"]["content"]
                == want["choices"][0]["message"]["content"]), (got, want)
        assert got["usage"] == want["usage"], (got, want)
    finally:
        # the api server runs until killed; the worker exits via coordinator
        # teardown when the root dies (or the SIGKILL escalation)
        _, r_err = _stop(root)
        _, w_err = _stop(worker)
        print("root stderr:", r_err[-2000:])    # shown on failure
        print("worker stderr:", w_err[-2000:])


def test_two_process_benchmark_completes(tmp_path):
    """ADVICE r5 HIGH regression: `inference` (--benchmark) over a
    2-process cluster must COMPLETE. The root's _print_benchmark runs
    measure_transfer_ms AND measure_prefill_transfer_ms(n_prompt) —
    real collectives over the global mesh — so the MSG_XFER_BENCH header
    now carries n_prompt and workers run the IDENTICAL sequence; before
    the fix the root's prefill microbench had no worker counterpart and
    the cluster deadlocked here (this test timed out)."""
    mpath, tpath = _fixture(tmp_path)
    base = ["--model", mpath, "--tokenizer", tpath, "--prompt", "ab",
            "--steps", "4", "--seed", "7", "--temperature", "0",
            "--buffer-float-type", "f32"]
    port = _free_port()
    cluster = ["--nnodes", "2", "--coordinator", f"127.0.0.1:{port}"]
    root, t = _run(["inference", *base, *cluster, "--node-rank", "0"])
    worker, _ = _run(["worker", "--model", mpath, "--tokenizer", tpath,
                      "--temperature", "0", "--buffer-float-type", "f32",
                      *cluster, "--node-rank", "1"])
    out_root, err_root = root.communicate(timeout=t)
    out_worker, err_worker = worker.communicate(timeout=t)
    assert root.returncode == 0, (out_root, err_root)
    assert worker.returncode == 0, (out_worker, err_worker)
    # the benchmark epilogue only prints after BOTH microbenches complete
    assert "Avg tokens / second:" in out_root, out_root
    assert "Avg transfer" in out_root, out_root
    assert "root shut down" in out_worker


def test_worker_mode_requires_cluster_flags():
    from distributed_llama_tpu.apps import dllama

    with pytest.raises(SystemExit):
        dllama.main(["worker", "--port", "9998"])
    with pytest.raises(SystemExit):  # nnodes without coordinator
        dllama.main(["generate", "--nnodes", "2"])
    with pytest.raises(SystemExit):  # non-root rank must be a worker
        dllama.main(["generate", "--nnodes", "2", "--node-rank", "1",
                     "--coordinator", "127.0.0.1:1"])
    with pytest.raises(SystemExit):  # root rank cannot be a worker
        dllama.main(["worker", "--nnodes", "2", "--node-rank", "0",
                     "--coordinator", "127.0.0.1:1"])


def test_single_process_protocol_helpers():
    """is_multihost/fetch_logits degrade to no-ops off-cluster."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel.multihost import is_multihost
    from distributed_llama_tpu.parallel.mesh import make_mesh

    assert not is_multihost(None)
    assert not is_multihost(make_mesh(tp=2, devices=jax.devices()[:2]))
