"""Fused Q40 Pallas kernel vs the XLA dequant oracle (interpret mode on the
CPU mesh; the compiled path runs on real TPU via bench/engine opt-in).

The kernel is the TPU-native analogue of the reference's Q40xQ80 SIMD matmul
(ref: src/funcs.cpp:286-385); correctness target is the dequantize-then-dot
semantics of the reference decoder (ref: src/quants.cpp:166-179).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_llama_tpu.ops.pallas_q40 import q40_matmul, supports_pallas, _tile_d
from distributed_llama_tpu.quants.jax_codec import QuantizedTensor, dequantize_q40_jax
from distributed_llama_tpu.quants.numpy_codec import quantize_q40


def _qt(rng, d, n, scale=0.1):
    w = rng.standard_normal((d, n), dtype=np.float32) * scale
    scales, packed = quantize_q40(w)
    return QuantizedTensor.from_numpy(scales, packed)


@pytest.mark.parametrize("d,n,t", [
    (256, 1024, 1),    # gemv, aligned
    (256, 1024, 4),    # small batch
    (704, 128 * 32, 2),  # d not 128-aligned -> whole-d tile
    (128, 704, 1),     # n/32 not lane-aligned -> full-m block padding
])
def test_kernel_matches_dequant_oracle(rng, d, n, t):
    qt = _qt(rng, d, n)
    x = jnp.asarray(rng.standard_normal((t, n), dtype=np.float32))
    ref = jnp.einsum("tn,dn->td", x, dequantize_q40_jax(qt, dtype=jnp.float32))
    got = q40_matmul(x, qt, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=1e-4)


def test_leading_dims_flattened(rng):
    qt = _qt(rng, 128, 256)
    x = jnp.asarray(rng.standard_normal((2, 3, 256), dtype=np.float32))
    got = q40_matmul(x, qt, interpret=True)
    assert got.shape == (2, 3, 128)
    ref = jnp.einsum("btn,dn->btd", x, dequantize_q40_jax(qt, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("e", [0, 2, 7])
def test_expert_kernel_matches_sliced_oracle(rng, e):
    """The expert-indexed kernel (traced index into the (E, d, m) stack) must
    match slicing the expert out first then running the plain kernel path."""
    from distributed_llama_tpu.ops.pallas_q40 import q40_expert_matmul

    n_e, d, n = 8, 256, 1024
    qts = [_qt(rng, d, n) for _ in range(n_e)]
    stack = QuantizedTensor(jnp.stack([q.packed for q in qts]),
                            jnp.stack([q.scales for q in qts]))
    x = jnp.asarray(rng.standard_normal((1, n), dtype=np.float32))
    ref = jnp.einsum("tn,dn->td", x,
                     dequantize_q40_jax(qts[e], dtype=jnp.float32))
    got = q40_expert_matmul(x, stack, jnp.int32(e), interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=1e-4)


def test_fused_expert_matmul_dispatch(rng):
    """ops/matmul.fused_expert_matmul: eligible only for single-shard Q40
    stacks under use_pallas; returns the same result as gather-then-matmul."""
    from distributed_llama_tpu.ops.matmul import fused_expert_matmul

    n_e, d, n = 4, 128, 256
    qts = [_qt(rng, d, n) for _ in range(n_e)]
    stack = QuantizedTensor(jnp.stack([q.packed for q in qts]),
                            jnp.stack([q.scales for q in qts]))
    x = jnp.asarray(rng.standard_normal((1, 1, n), dtype=np.float32))
    got = fused_expert_matmul(x, stack, jnp.int32(3),
                              compute_dtype=jnp.float32, use_pallas=True,
                              pallas_interpret=True)
    assert got is not None and got.shape == (1, 1, d)
    ref = jnp.einsum("btn,dn->btd", x,
                     dequantize_q40_jax(qts[3], dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=1e-4)
    # ineligible: pallas off, mesh path, dense leaf, 2D (un-stacked) weight
    assert fused_expert_matmul(x, stack, 0, compute_dtype=jnp.float32) is None
    assert fused_expert_matmul(x, stack, 0, compute_dtype=jnp.float32,
                               use_pallas=True, tp_mesh=object()) is None
    assert fused_expert_matmul(x, jnp.zeros((4, d, n)), 0,
                               compute_dtype=jnp.float32,
                               use_pallas=True) is None
    assert fused_expert_matmul(x, qts[0], 0, compute_dtype=jnp.float32,
                               use_pallas=True) is None


def test_supports_and_tiles():
    assert _tile_d(4096, 2048) == 1024
    assert _tile_d(4096, 5504) == 256     # w2: bigger m, smaller tile
    assert _tile_d(11008, 2048) == 256    # 11008 has no 512/1024 divisor
    assert _tile_d(704, 2048) == 704      # whole-dim fallback
    assert _tile_d(32000, 2048) == 256
    rng = np.random.default_rng(0)
    qt = _qt(rng, 128, 256)
    assert supports_pallas(qt)
    stacked = QuantizedTensor(qt.packed[None], qt.scales[None])  # (L, d, 16, nb)
    assert not supports_pallas(stacked)  # leading dims must be sliced first


@pytest.mark.parametrize("d", [256, 1024])
def test_subtiled_bf16_prefill_matches_whole_tile(rng, d, monkeypatch):
    """The mxu_bf16 unpack/MXU interleave (t>=16, bf16 out, td=256 sub-tiled
    8-way) must be a pure regrouping of output writes: each output element
    still sees one full-N contraction, so forcing n_sub=1 on the same kernel
    must reproduce the sub-tiled output to within 1 bf16 ulp (XLA's dot
    blocks its f32 accumulation differently per output shape, so bitwise
    equality is not guaranteed — but the math is the same contraction).
    (A bf16-dequant einsum oracle is deliberately not the reference here:
    the kernel's -8-offset fold amplifies bf16 rounding vs naively-rounded
    (nib-8)*s weights — see the module docstring.)"""
    from distributed_llama_tpu.ops import pallas_q40 as q

    n, t = 1024, 32
    qt = _qt(rng, d, n)
    td = _tile_d(d, qt.packed.shape[1])
    assert q._n_sub(td, qt.packed.shape[1], True) == (8 if td == 256 else 1)
    x = jnp.asarray(rng.standard_normal((t, n), dtype=np.float32))
    got = q40_matmul(x, qt, out_dtype=jnp.bfloat16, interpret=True)
    assert got.dtype == jnp.bfloat16

    monkeypatch.setattr(q, "_n_sub", lambda td_, m_, mxu: 1)
    q40_matmul.clear_cache()
    whole = q40_matmul(x, qt, out_dtype=jnp.bfloat16, interpret=True)
    q40_matmul.clear_cache()  # drop the patched-trace cache entry
    g, w = np.asarray(got, dtype=np.float32), np.asarray(whole, dtype=np.float32)
    np.testing.assert_allclose(g, w, rtol=2 ** -7, atol=2 ** -7 * np.abs(w).max())

    # loose sanity vs the exact f32 oracle (bf16 feeds: ~1% relative)
    ref = jnp.einsum("tn,dn->td", x, dequantize_q40_jax(qt, dtype=jnp.float32))
    scale = float(np.abs(np.asarray(ref)).max())
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), np.asarray(ref),
        atol=0.03 * scale, rtol=0.03)


def test_subtiled_expert_kernel_matches_whole_tile(rng, monkeypatch):
    """The expert kernel's leading-dim ref slicing (packed_ref[0, sl, :])
    must survive sub-tiling: a t>=16 bf16 expert matmul at a td=256 tile
    runs n_sub=8, and forcing n_sub=1 must agree to 1 bf16 ulp (MoE
    prefill's hot path — decode t=1 never sub-tiles)."""
    from distributed_llama_tpu.ops import pallas_q40 as q
    from distributed_llama_tpu.ops.pallas_q40 import q40_expert_matmul

    n_e, d, n, t, e = 4, 256, 1024, 32, 2
    qts = [_qt(rng, d, n) for _ in range(n_e)]
    stack = QuantizedTensor(jnp.stack([qq.packed for qq in qts]),
                            jnp.stack([qq.scales for qq in qts]))
    assert q._n_sub(_tile_d(d, stack.packed.shape[2]),
                    stack.packed.shape[2], True) == 8
    x = jnp.asarray(rng.standard_normal((t, n), dtype=np.float32))
    got = q40_expert_matmul(x, stack, jnp.int32(e),
                            out_dtype=jnp.bfloat16, interpret=True)
    assert got.dtype == jnp.bfloat16

    monkeypatch.setattr(q, "_n_sub", lambda td_, m_, mxu: 1)
    q40_expert_matmul.clear_cache()
    whole = q40_expert_matmul(x, stack, jnp.int32(e),
                              out_dtype=jnp.bfloat16, interpret=True)
    q40_expert_matmul.clear_cache()
    g = np.asarray(got, dtype=np.float32)
    w = np.asarray(whole, dtype=np.float32)
    np.testing.assert_allclose(g, w, rtol=2 ** -7, atol=2 ** -7 * np.abs(w).max())

    # and the sub-tiled output still tracks the selected expert's oracle
    ref = np.asarray(jnp.einsum("tn,dn->td", x,
                                dequantize_q40_jax(qts[e], dtype=jnp.float32)))
    scale = float(np.abs(ref).max())
    np.testing.assert_allclose(g, ref, atol=0.03 * scale, rtol=0.03)
