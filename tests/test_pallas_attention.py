"""Flash attention kernel (decode + chunked prefill) vs the XLA
decode_attention oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.ops.attention import decode_attention
from distributed_llama_tpu.ops.pallas_attention import (
    flash_attention, flash_decode_attention, flash_supported)


@pytest.mark.parametrize("b,h,kvh,s,pos", [
    (1, 8, 8, 256, 255),    # full cache, MHA
    (1, 8, 2, 256, 255),    # GQA group 4
    (1, 8, 8, 256, 0),      # only position 0 visible
    (2, 8, 4, 512, 100),    # batch, partial cache, multiple s-blocks
    (1, 4, 4, 384, 300),    # s = 384 -> 128-wide blocks
])
def test_flash_decode_matches_oracle(b, h, kvh, s, pos):
    hs = 128
    rng = np.random.default_rng(pos + s + h)
    q = jnp.asarray(rng.standard_normal((b, 1, h, hs)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    q_pos = jnp.full((b, 1), pos, jnp.int32)

    want = decode_attention(q, k, v, q_pos)
    got = flash_decode_attention(q, k, v, q_pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("b,h,kvh,s,t,pos0", [
    (1, 8, 8, 256, 16, 0),     # prefill chunk from 0, MHA
    (1, 8, 2, 256, 16, 100),   # GQA group 4, mid-session chunk
    (2, 8, 4, 512, 32, 37),    # batch, multiple s-blocks
    (1, 4, 4, 384, 8, 300),    # 128-wide blocks, chunk near the cache edge
])
def test_flash_prefill_matches_oracle(b, h, kvh, s, t, pos0):
    """T>1 chunks: per-row causal limits must match the dense masked path.
    The cache is pre-filled at the chunk's positions (the engine writes K/V
    before attending — models/transformer._attention_block)."""
    hs = 128
    rng = np.random.default_rng(pos0 + s + h + t)
    q = jnp.asarray(rng.standard_normal((b, t, h, hs)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    q_pos = pos0 + jnp.arange(t, dtype=jnp.int32)[None, :]
    q_pos = jnp.broadcast_to(q_pos, (b, t))

    want = decode_attention(q, k, v, q_pos)
    got = flash_attention(q, k, v, q_pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-2, rtol=2e-2)


def test_flash_prefill_per_row_pos0():
    """Batched generation decodes with per-row positions; the kernel reads
    each panel's own pos_ref[b]."""
    b, t, h, kvh, s, hs = 3, 1, 4, 4, 256, 128
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((b, t, h, hs)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    q_pos = jnp.asarray([[3], [100], [255]], jnp.int32)

    want = decode_attention(q, k, v, q_pos)
    got = flash_attention(q, k, v, q_pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-2, rtol=2e-2)


def test_flash_supported_bounds():
    assert flash_supported(1, 32, 8)        # decode always
    assert flash_supported(256, 32, 32)     # 7B chunk: 256 rows
    assert flash_supported(256, 32, 8)      # 8B chunk: 1024 rows
    assert not flash_supported(512, 32, 8)  # 2048 rows > VMEM budget


def test_flash_decode_bf16():
    b, h, kvh, s, hs = 1, 8, 8, 256, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, 1, h, hs)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.bfloat16)
    q_pos = jnp.full((b, 1), s - 1, jnp.int32)

    want = decode_attention(q, k, v, q_pos)
    got = flash_decode_attention(q, k, v, q_pos, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=5e-2, rtol=5e-2)
