"""Flash-decode attention kernel vs the XLA decode_attention oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.ops.attention import decode_attention
from distributed_llama_tpu.ops.pallas_attention import flash_decode_attention


@pytest.mark.parametrize("b,h,kvh,s,pos", [
    (1, 8, 8, 256, 255),    # full cache, MHA
    (1, 8, 2, 256, 255),    # GQA group 4
    (1, 8, 8, 256, 0),      # only position 0 visible
    (2, 8, 4, 512, 100),    # batch, partial cache, multiple s-blocks
    (1, 4, 4, 384, 300),    # s = 384 -> 128-wide blocks
])
def test_flash_decode_matches_oracle(b, h, kvh, s, pos):
    hs = 128
    rng = np.random.default_rng(pos + s + h)
    q = jnp.asarray(rng.standard_normal((b, 1, h, hs)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.float32)
    q_pos = jnp.full((b, 1), pos, jnp.int32)

    want = decode_attention(q, k, v, q_pos)
    got = flash_decode_attention(q, k, v, q_pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-2, rtol=2e-2)


def test_flash_decode_bf16():
    b, h, kvh, s, hs = 1, 8, 8, 256, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, 1, h, hs)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, hs)), jnp.bfloat16)
    q_pos = jnp.full((b, 1), s - 1, jnp.int32)

    want = decode_attention(q, k, v, q_pos)
    got = flash_decode_attention(q, k, v, q_pos, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=5e-2, rtol=5e-2)
