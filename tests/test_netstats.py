"""Collective-bytes observability (runtime/netstats.py — VERDICT r1 #7).

Checks the modeled wire bytes against the reference's published per-token
transfer table (ref README.md:96-110: Llama 3 8B, F32 2048 kB vs Q80 544 kB
at 2 devices — the ~4x quantized-wire claim)."""

import numpy as np

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.runtime.netstats import (
    estimate_decode_wire,
    measure_allreduce_ms,
)

LLAMA3_8B = ModelSpec(
    arch=ArchType.LLAMA, dim=4096, hidden_dim=14336, n_layers=32,
    n_heads=32, n_kv_heads=8, vocab_size=128256, seq_len=8192,
    hidden_act=HiddenAct.SILU)


def test_wire_estimate_q80_ratio_matches_reference_claim():
    """q80 vs f32 bytes ratio reproduces the reference's ~3.8x wire cut
    (2048 kB -> 544 kB, ref README.md:98-108) on the per-layer reductions."""
    mesh = make_mesh(tp=2)
    f32 = estimate_decode_wire(LLAMA3_8B, mesh, q80=False)
    q80 = estimate_decode_wire(LLAMA3_8B, mesh, q80=True)
    ratio = f32.breakdown["tp_partial_sums"] / q80.breakdown["tp_partial_sums"]
    assert abs(ratio - 4 / 1.0625) < 0.01  # 3.7647x

    # magnitude sanity vs the reference's 2-device table: same order as its
    # 2048 kB (f32) / 544 kB (q80); our all-reduce design halves the star
    # topology's 2 broadcasts + 2 gathers, so expect roughly half
    assert 512 <= f32.sent_kb_per_token <= 2048
    assert 136 <= q80.sent_kb_per_token <= 700


def test_wire_estimate_components():
    mesh = make_mesh(tp=4, sp=2)
    est = estimate_decode_wire(LLAMA3_8B, mesh, q80=False)
    assert set(est.breakdown) == {"tp_partial_sums", "tp_logits_gather",
                                  "sp_attn_merge"}
    assert est.sent_kb_per_token > 0
    # single-device: nothing moves
    assert estimate_decode_wire(LLAMA3_8B, None).sent_kb_per_token == 0
    assert estimate_decode_wire(
        LLAMA3_8B, make_mesh(tp=1, dp=8)).sent_kb_per_token == 0


def test_measured_allreduce_runs():
    mesh = make_mesh(tp=4)
    ms = measure_allreduce_ms(mesh, 4096, iters=4)
    assert ms > 0
    assert measure_allreduce_ms(make_mesh(tp=1, dp=8), 4096) == 0.0


def test_engine_wire_surface():
    import jax.numpy as jnp

    from distributed_llama_tpu.models.params import load_params, random_tensors
    from distributed_llama_tpu.runtime import Engine
    from test_model_forward import make_spec, dense_weights

    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=2)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    eng = Engine(spec, params, make_mesh(tp=2), compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    est = eng.wire_estimate()
    assert est.sent_kb_per_token > 0
    assert eng.measure_transfer_ms() > 0
