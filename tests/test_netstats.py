"""Collective-bytes observability (runtime/netstats.py — VERDICT r1 #7).

Checks the modeled wire bytes against the reference's published per-token
transfer table (ref README.md:96-110: Llama 3 8B, F32 2048 kB vs Q80 544 kB
at 2 devices — the ~4x quantized-wire claim)."""

import numpy as np

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.runtime.netstats import (
    estimate_decode_wire,
    measure_allreduce_ms,
)

LLAMA3_8B = ModelSpec(
    arch=ArchType.LLAMA, dim=4096, hidden_dim=14336, n_layers=32,
    n_heads=32, n_kv_heads=8, vocab_size=128256, seq_len=8192,
    hidden_act=HiddenAct.SILU)


def test_wire_estimate_q80_ratio_matches_reference_claim():
    """q80 vs f32 bytes ratio reproduces the reference's ~3.8x wire cut
    (2048 kB -> 544 kB, ref README.md:98-108) on the per-layer reductions."""
    mesh = make_mesh(tp=2)
    f32 = estimate_decode_wire(LLAMA3_8B, mesh, q80=False)
    q80 = estimate_decode_wire(LLAMA3_8B, mesh, q80=True)
    ratio = f32.breakdown["tp_partial_sums"] / q80.breakdown["tp_partial_sums"]
    assert abs(ratio - 4 / 1.0625) < 0.01  # 3.7647x

    # magnitude sanity vs the reference's 2-device table: same order as its
    # 2048 kB (f32) / 544 kB (q80); our all-reduce design halves the star
    # topology's 2 broadcasts + 2 gathers, so expect roughly half
    assert 512 <= f32.sent_kb_per_token <= 2048
    assert 136 <= q80.sent_kb_per_token <= 700


def test_wire_estimate_components():
    mesh = make_mesh(tp=4, sp=2)
    est = estimate_decode_wire(LLAMA3_8B, mesh, q80=False)
    assert set(est.breakdown) == {"tp_partial_sums", "tp_logits_gather",
                                  "sp_attn_merge"}
    assert est.sent_kb_per_token > 0
    # single-device: nothing moves
    assert estimate_decode_wire(LLAMA3_8B, None).sent_kb_per_token == 0
    assert estimate_decode_wire(
        LLAMA3_8B, make_mesh(tp=1, dp=8)).sent_kb_per_token == 0


def test_reconcile_wire_golden_on_synthetic_ledger():
    """Measured-vs-modeled reconciliation (dlwire), pinned on a synthetic
    wire ledger: the measured control-plane bytes of a known frame
    sequence against frame-size arithmetic (exact -> drift 0.0), a
    doctored model (flagged at the 25% bar, inclusive), and the modeled
    q80 decode wire as the data-plane example."""
    from distributed_llama_tpu.parallel.multihost import (_HEADER_LEN,
                                                          frame_bytes)
    from distributed_llama_tpu.runtime.netstats import reconcile_wire
    from distributed_llama_tpu.runtime.stats import WireStats

    # synthetic ledger: 3 RUN frames with 4/0/9-byte payloads + 5 PINGs
    w = WireStats()
    for n_pay in (4, 0, 9):
        w.account(1, "RUN", "tx", frame_bytes(_HEADER_LEN, n_pay))
    for _ in range(5):
        w.account(1, "PING", "tx", frame_bytes(1, 0))
    measured = w.peer_bytes(1, "RUN", "tx")
    modeled = sum(frame_bytes(_HEADER_LEN, n) for n in (4, 0, 9))
    r = reconcile_wire(measured, modeled)
    assert r["drift_frac"] == 0.0 and r["drift"] is False and \
        r["note"] is None, r
    assert r["measured"] == r["modeled"] == measured

    # drift math pinned: 0.25 is INCLUSIVE (the flag bar), just under is
    # clean, and the asymmetric direction measures against the MODEL
    assert reconcile_wire(75.0, 100.0)["drift"] is True
    assert reconcile_wire(75.0, 100.0)["drift_frac"] == 0.25
    assert reconcile_wire(124.9, 100.0)["drift_frac"] == 0.249
    assert reconcile_wire(124.9, 100.0)["drift"] is False
    assert reconcile_wire(200.0, 100.0)["drift_frac"] == 1.0

    # data-plane shape: the modeled q80 decode wire reconciles with
    # itself (the silicon MULTICHIP rows will feed the measured side)
    mesh = make_mesh(tp=2)
    kb = estimate_decode_wire(LLAMA3_8B, mesh, q80=True).sent_kb_per_token
    r = reconcile_wire(kb, kb, unit="kb/token")
    assert r["drift"] is False and r["unit"] == "kb/token"


def test_measured_allreduce_runs():
    mesh = make_mesh(tp=4)
    ms = measure_allreduce_ms(mesh, 4096, iters=4)
    assert ms > 0
    assert measure_allreduce_ms(make_mesh(tp=1, dp=8), 4096) == 0.0


def test_engine_wire_surface():
    import jax.numpy as jnp

    from distributed_llama_tpu.models.params import load_params, random_tensors
    from distributed_llama_tpu.runtime import Engine
    from test_model_forward import make_spec, dense_weights

    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4)
    host, _ = dense_weights(spec, seed=2)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    eng = Engine(spec, params, make_mesh(tp=2), compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    est = eng.wire_estimate()
    assert est.sent_kb_per_token > 0
    assert eng.measure_transfer_ms() > 0


def test_measured_ppermute_runs():
    from distributed_llama_tpu.runtime.netstats import measure_ppermute_ms

    ms = measure_ppermute_ms(make_mesh(pp=4), 4096, iters=4)
    assert ms > 0
    assert measure_ppermute_ms(make_mesh(tp=8), 4096) == 0.0


def test_engine_prefill_transfer_models_gpipe_schedule(monkeypatch):
    """VERDICT r4 #9: the prefill T estimate follows the schedule forward()
    picks — GPipe segments are costed as (M + pp - 2) microbatch ppermute
    hops + one output psum, short segments as pp whole-activation psums."""
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel.pp import gpipe_microbatches
    from distributed_llama_tpu.models.params import load_params
    from distributed_llama_tpu.runtime import Engine
    from test_model_forward import make_spec, dense_weights

    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     n_layers=4, seq_len=256)
    host, _ = dense_weights(spec, seed=2)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    eng = Engine(spec, params, make_mesh(pp=2, tp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32)
    assert eng.pp_gpipe

    calls = []
    from distributed_llama_tpu.runtime import netstats

    monkeypatch.setattr(netstats, "measure_allreduce_ms",
                        lambda mesh, n, iters=16, axes=("tp",):
                        calls.append(("psum", n, axes)) or 1.0)
    monkeypatch.setattr(netstats, "measure_ppermute_ms",
                        lambda mesh, n, iters=16, axis="pp":
                        calls.append(("hop", n, axis)) or 0.5)

    t = 128  # gpipe engages: M microbatches of t/M tokens
    m = gpipe_microbatches(t, 2)
    assert m > 1
    total = eng.measure_prefill_transfer_ms(t)
    hops = [c for c in calls if c[0] == "hop"]
    psums = [c for c in calls if c[0] == "psum"]
    assert len(hops) == 1 and hops[0][1] == (t // m) * spec.dim
    assert len(psums) == 1 and psums[0][1] == t * spec.dim
    assert total == (m + 2 - 2) * 0.5 + 1.0

    calls.clear()
    short = eng.measure_prefill_transfer_ms(8)  # all-stages: pp psums
    assert [c[0] for c in calls] == ["psum"]
    assert short == 2 * 1.0
