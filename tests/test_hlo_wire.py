"""The wire model vs the compiled HLO (VERDICT r2 #9).

`netstats.estimate_decode_wire` is a hand model of which collectives the
sharding design makes GSPMD/shard_map emit. These tests lower a real decode
step for the tp / sp / ep modes, count the collective ops in the optimized
HLO, and assert the model's per-layer reduce counts match — so a sharding
change that adds an unmodeled collective fails a test instead of silently
skewing the S/T columns (the reference's byte counters are ground truth by
construction, ref: src/socket.cpp:266-271; a model needs this check).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_llama_tpu.models.transformer import KVCache, forward
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.parallel.sharding import cache_pspec, shard_params
from distributed_llama_tpu.runtime.netstats import estimate_decode_wire

from conftest import forward_entry_inputs


def _collective_counts(hlo: str) -> dict:
    """Occurrences of each collective op kind in optimized HLO text."""
    out = {}
    for kind in ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                 "collective-permute"):
        # op applications only: "kind(" or "kind-start(" — not fusion names
        out[kind] = len(re.findall(rf"= \S+ {kind}(?:-start)?\(", hlo))
    return out


def _lowered_decode_hlo(spec, params, mesh, **fwd_kw) -> str:
    cache = KVCache.create(spec, batch=1, seq_len=spec.seq_len,
                           dtype=jnp.float32)
    cache = jax.device_put(cache, NamedSharding(
        mesh, cache_pspec(sp=mesh.shape.get("sp", 1) > 1)))
    tok = jnp.zeros((1, 1), jnp.int32)

    def step(params, tok, cache):
        logits, cache = forward(params, spec, tok, jnp.int32(3), cache,
                                compute_dtype=jnp.float32, **fwd_kw)
        return logits, cache

    fn = jax.jit(step, out_shardings=(NamedSharding(mesh, P()), None))
    return fn.lower(params, tok, cache).compile().as_text()


def test_tp_decode_collectives_match_model():
    """GSPMD tp: the model says 2 partial-sum reduces per layer (wo, w2 —
    the reference's 2 broadcast + 2 gather pairs, SURVEY.md §3.4) plus one
    logits gather for the vocab-sharded wcls."""
    spec, params, _, _, _ = forward_entry_inputs("LLAMA")
    mesh = make_mesh(tp=2, dp=1)
    params = shard_params(params, mesh)
    hlo = _lowered_decode_hlo(spec, params, mesh)
    c = _collective_counts(hlo)

    est = estimate_decode_wire(spec, mesh)
    assert "tp_partial_sums" in est.breakdown
    # the modeled per-layer reduces appear as all-reduce (or an equivalent
    # reduce-scatter + all-gather split) — count reduce-ish ops. The
    # vocab-sharded wcls logits replication is one extra collective: an
    # all-gather, or an all-reduce if XLA folds it (then reduces = 2L + 1)
    reduces = c["all-reduce"] + c["reduce-scatter"]
    assert reduces in (2 * spec.n_layers, 2 * spec.n_layers + 1), c
    if reduces == 2 * spec.n_layers:
        assert c["all-gather"] >= 1, c


def test_sp_decode_collectives_match_model():
    """sp-sharded cache decode: one attention stat merge (psum) per layer
    (parallel/ring_attention.sp_cache_attention), plus the tp reduces when
    tp > 1 and the final logits gather."""
    spec, params, _, _, _ = forward_entry_inputs("LLAMA")
    mesh = make_mesh(tp=2, sp=2, dp=1)
    params = shard_params(params, mesh)
    hlo = _lowered_decode_hlo(spec, params, mesh, sp_cache_mesh=mesh)
    c = _collective_counts(hlo)

    est = estimate_decode_wire(spec, mesh)
    assert "sp_attn_merge" in est.breakdown
    # per layer: 2 tp reduces + 1 sp stat merge (the merge psums acc/m/l —
    # one fused all-reduce each if XLA keeps them separate; allow 1..3)
    reduces = c["all-reduce"] + c["reduce-scatter"]
    lo = 3 * spec.n_layers
    hi = 5 * spec.n_layers + 1
    assert lo <= reduces <= hi, (reduces, c)


def test_ep_decode_collectives_match_model():
    """ep x tp MoE decode: one (ep, tp)-group reduce per layer for the
    expert sum + the attention wo reduce per layer (parallel/ep_moe.py)."""
    spec, params, _, _, _ = forward_entry_inputs("MIXTRAL")
    mesh = make_mesh(ep=2, tp=2, dp=1)
    from distributed_llama_tpu.parallel.ep_moe import repack_moe_ep

    params = dict(params)
    params["layers"] = [repack_moe_ep(lw, 2) for lw in params["layers"]]
    params = shard_params(params, mesh)
    hlo = _lowered_decode_hlo(spec, params, mesh, tp_mesh=mesh)
    c = _collective_counts(hlo)

    est = estimate_decode_wire(spec, mesh)
    assert "ep_moe_reduce" in est.breakdown and "tp_partial_sums" in est.breakdown
    reduces = c["all-reduce"] + c["reduce-scatter"]
    # per layer: 1 wo tp reduce + 1 moe (ep,tp) group reduce; logits gather
    # may lower as a reduce too
    lo = 2 * spec.n_layers
    hi = 2 * spec.n_layers + 2
    assert lo <= reduces <= hi, (reduces, c)


def test_collective_counter_sees_known_program():
    """Meta-check: the counter actually sees collectives. (A data-dependent
    extra reduction is NOT a reliable probe — XLA's all-reduce combiner
    merges independent reduces into one variadic op — so probe with known
    standalone programs instead.)"""
    from distributed_llama_tpu.parallel.compat import shard_map

    mesh = make_mesh(tp=2, dp=1)

    @jax.jit
    def one_psum(x):
        return shard_map(lambda v: jax.lax.psum(v, "tp"), mesh=mesh,
                         in_specs=P("tp"), out_specs=P(), check_vma=False)(x)

    hlo = one_psum.lower(jnp.ones((2, 8))).compile().as_text()
    c = _collective_counts(hlo)
    assert c["all-reduce"] == 1, c

    @jax.jit
    def two_chained(x):
        def body(v):
            a = jax.lax.psum(v, "tp")
            return jax.lax.psum(a * a, "tp")  # data-dependent: no combining
        return shard_map(body, mesh=mesh, in_specs=P("tp"), out_specs=P(),
                         check_vma=False)(x)

    hlo2 = two_chained.lower(jnp.ones((2, 8))).compile().as_text()
    c2 = _collective_counts(hlo2)
    assert c2["all-reduce"] == 2, c2
