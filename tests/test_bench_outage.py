"""bench.py outage behavior: a dead TPU backend must yield a structured
JSON line, never a hang or a bare traceback (the round-3 driver artifact
was lost to exactly that — the axon plugin HANGS on init when its tunnel
is down, so the probe has to be a timeout-killed subprocess).

Also: the dryrun entry point must pin the CPU platform before any jax
call for the same reason (ref for the bar these protect:
src/apps/dllama/dllama.cpp benchmark output always prints)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env: dict, timeout: float = 300.0):
    env = dict(os.environ)
    env.update({
        # config-level pin: a sitecustomize hook may point jax.config at
        # the TPU plugin, so the env var alone would not keep the bench
        # (or its probe child) off the tunnel
        "BENCH_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "BENCH_MODEL": "tiny",
        "BENCH_TOKENS": "4",
        "BENCH_REPEATS": "1",
        "BENCH_VARIANTS": "0",
    })
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_probe_timeout_yields_structured_error():
    # a probe that hangs (the axon-tunnel-down signature) must be killed at
    # the bound and reported as a machine-readable error, rc 0
    r = _run_bench({
        "BENCH_PROBE_CODE": "import time; time.sleep(60)",
        "BENCH_PROBE_TIMEOUT": "2",
    }, timeout=60.0)
    assert r.returncode == 0, r.stderr
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["value"] is None
    assert "unavailable" in row["error"]
    assert row["metric"] == "tiny_llama_q40_decode_ms_per_token"


def test_probe_failure_yields_structured_error():
    # a probe that errors out (plugin import failure) is the same contract
    r = _run_bench({
        "BENCH_PROBE_CODE": "raise SystemExit(3)",
    }, timeout=60.0)
    assert r.returncode == 0, r.stderr
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["value"] is None and "unavailable" in row["error"]


def test_midrun_outage_keeps_completed_rows():
    # a failure AFTER the main row was measured must still print the final
    # JSON with the measured value plus the error annotation
    r = _run_bench({"BENCH_SIMULATE_OUTAGE": "1"})
    assert r.returncode == 0, r.stderr
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["value"] is not None and row["value"] > 0
    assert "simulated mid-run outage" in row["error"]
    # the completed main row was also flushed incrementally to stderr
    flushed = [json.loads(line) for line in r.stdout.splitlines()[:-1]] + [
        json.loads(line) for line in r.stderr.splitlines()
        if line.startswith("{")]
    assert any(x.get("metric") == row["metric"] and x.get("value")
               for x in flushed)


def test_healthy_run_emits_one_parseable_line():
    r = _run_bench({})
    assert r.returncode == 0, r.stderr
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    assert len(lines) == 1  # stdout carries exactly the one JSON line
    row = json.loads(lines[0])
    assert row["value"] > 0 and "error" not in row
    assert row["unit"] == "ms/token"


def test_serve_row_emits_valid_json():
    """BENCH_SERVE=1 adds the continuous-batching Poisson-arrival row
    (bench._serve_row) with the serving metrics the scheduler promises —
    aggregate tok/s, the static-batch ratio, TTFT/ITL percentiles — all
    as one valid JSON variant (a tiny trace keeps this smoke-fast; the
    default bench stays serve-free)."""
    r = _run_bench({
        "BENCH_SERVE": "1",
        "BENCH_SERVE_REQUESTS": "4",
        "BENCH_SERVE_BATCH": "2",
        "BENCH_SERVE_BUDGETS": "4,8",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    serve = [v for v in row.get("variants", [])
             if "continuous" in v["metric"]]
    assert len(serve) == 1, row
    s = serve[0]
    assert s["unit"] == "tok/s" and s["value"] > 0
    assert s["static_agg_tok_per_s"] > 0 and s["vs_static_batch"] > 0
    assert s["batch"] == 2 and s["requests"] >= 2
    assert s["ttft_p50_ms"] >= 0 and s["ttft_p99_ms"] >= s["ttft_p50_ms"]
    assert 0 < s["mean_slot_occupancy"] <= 2
    # ISSUE-10 satellite: every bench row carries the hbm ledger next to
    # step_timeline — exact allocated bytes, not estimates
    hbm = s["hbm"]
    assert hbm["kv_slot_bytes"] > 0 and hbm["weights_bytes"] > 0
    assert hbm["per_slot_bytes"] * s["batch"] == hbm["kv_slot_bytes"]
    assert s["step_timeline"], s  # the curve dlprof consumes below
    json.dumps(s)  # the row round-trips as machine-readable JSON

    # ISSUE-10 acceptance: tools/dlprof.py over this REAL BENCH_SERVE=1
    # artifact reproduces the batch-composition -> ms/step curve from
    # the step_timeline block and emits a non-null knee + --serve-batch
    # recommendation
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import dlprof

    report = dlprof.analyze([], [row] + row.get("variants", []))
    sc = report["step_curve"]
    assert sc["decode_points"], sc       # the curve reproduced
    assert sc["knee"] is not None and sc["knee"]["knee_rows"] >= 1
    rec = sc["recommendation"]
    assert rec is not None and rec["serve_batch"] >= 1
    assert report["hbm"] is not None     # the ledger rode the artifact


def test_kvx_row_emits_valid_json():
    """BENCH_KVX=1 adds the cross-replica KV block transfer row
    (bench._kvx_row). The DETERMINISTIC acceptance bars are exact here:
    greedy TOKEN PARITY transfer-on vs -off AND unified vs
    disaggregated, every cold request filled (hit rate 1.0 on this
    trace, zero fallbacks), the measured BLOCK_DATA wire bytes
    RECONCILED against the frame arithmetic at drift 0.0, and zero
    post-warmup compiles with the ledger frozen through the ON serve.
    The >= 30% cold-TTFT bar is pinned on the COMMITTED BENCH_r08.json
    row, not on CI timing."""
    r = _run_bench({
        "BENCH_KVX": "1",
        "BENCH_KVX_FAMILIES": "3",
        "BENCH_KVX_SYS": "48",
        "BENCH_KVX_BLOCK": "16",
        "BENCH_KVX_TOKENS": "6",
        "BENCH_KVX_STREAMS": "2",
        "BENCH_KVX_LONG": "64",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    rows = [v for v in row.get("variants", [])
            if "kv_transfer" in v["metric"]]
    assert len(rows) == 1, row
    v = rows[0]
    assert v["token_parity"] is True, v
    assert v["token_parity_disagg"] is True, v
    assert v["fills_ok"] == 3 and v["fill_fallbacks"] == 0, v
    assert v["fill_hit_rate"] == 1.0, v
    assert v["compiles_after_warmup"] == 0, v
    rec = v["reconcile"]
    assert rec["drift"] is False and rec["drift_frac"] == 0.0, rec
    assert v["bytes_rx"] > 0 and v["tokens_filled"] > 0
    assert v["unified"]["itl_p99_ms"] is not None
    assert v["disaggregated"]["itl_p99_ms"] is not None
    json.dumps(v)  # machine-readable round trip

    # the COMMITTED row carries the acceptance bars the CI run cannot
    # time-assert: >= 30% cold-replica TTFT p50 gain with fills on,
    # reconcile within the 25% bar, zero frozen-ledger compiles
    art = os.path.join(REPO, "BENCH_r08.json")
    committed = json.load(open(art))
    cv = [x for x in committed["variants"]
          if "kv_transfer" in x["metric"]][0]
    assert cv["value"] >= 30.0, cv["value"]
    assert cv["token_parity"] is True and cv["token_parity_disagg"] \
        is True
    assert cv["reconcile"]["drift"] is False
    assert cv["compiles_after_warmup"] == 0
    assert cv["fill_hit_rate"] == 1.0


def test_vocab_row_emits_valid_json():
    """BENCH_VOCAB=1 adds the vocab-sharding A/B row (bench._vocab_row):
    sharded vs replicated embedding+head served over a tp=2 CPU mesh on
    the SAME mixed greedy/sampled trace. The DETERMINISTIC acceptance
    bars are exact: greedy TOKEN PARITY sharded vs replicated, the
    per-chip embedding shard exactly halving the `vocab` HBM category,
    and ZERO post-warmup compiles per variant with the ledger frozen
    (head ms is reported, never time-asserted in CI). The committed
    BENCH_r09.json row pins the same bars."""
    r = _run_bench({
        "BENCH_VOCAB": "1",
        "BENCH_VOCAB_REQUESTS": "6",
        "BENCH_VOCAB_TOKENS": "6",
        "BENCH_VOCAB_STEPS": "6",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    rows = [v for v in row.get("variants", [])
            if "vocab_shard" in v["metric"]]
    assert len(rows) == 1, row
    v = rows[0]
    assert "error" not in v, v
    assert v["token_parity"] is True, v
    assert v["tp"] == 2
    assert v["compiles_after_warmup_sharded"] == 0, v
    assert v["compiles_after_warmup_replicated"] == 0, v
    # the freed bytes are real: the embedding shard is exactly 1/tp
    # (wcls was row-split already — both variants carry its half)
    on, off = (v["vocab_bytes_per_chip_sharded"],
               v["vocab_bytes_per_chip_replicated"])
    assert 0 < on < off, v
    assert v["value"] > 0 and v["head_sample_ms_replicated"] > 0
    assert v["sampled_via_candidates"] > 0
    json.dumps(v)

    # committed-row bars (BENCH_r09.json): parity + zero compiles +
    # the byte split — pinned on the artifact, not CI timing
    art = os.path.join(REPO, "BENCH_r09.json")
    committed = json.load(open(art))
    cv = [x for x in committed["variants"]
          if "vocab_shard" in x["metric"]][0]
    assert cv["token_parity"] is True
    assert cv["compiles_after_warmup_sharded"] == 0
    assert (cv["vocab_bytes_per_chip_sharded"]
            < cv["vocab_bytes_per_chip_replicated"])


def test_spec_row_emits_valid_json():
    """BENCH_SPEC=1 adds the REAL-draft speculative-decoding row
    (bench._spec_row): self-draft vs prompt-lookup vs plain greedy on a
    fixed-seed eval + the per-slot Poisson serving A/B. The
    DETERMINISTIC acceptance bars are exact here — bit-identical token
    streams across all paths AND zero post-warmup compiles with the
    ledger frozen through the speculative serve; the >1.5x single-stream
    and serving-gain bars are pinned on the COMMITTED BENCH_r07.json row
    (wall-clock ratios on a loaded CI box are not a regression signal).
    The accept rate and the repetitive/non-repetitive label must be ON
    the row — the VERDICT #6 reporting debt."""
    r = _run_bench({
        "BENCH_SPEC": "1",
        "BENCH_SPEC_TOKENS": "48",
        "BENCH_SPEC_REQUESTS": "6",
        "BENCH_SPEC_BATCH": "2",
        "BENCH_SPEC_REPEATS": "1",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    sp = [v for v in row.get("variants", [])
          if "selfdraft" in v["metric"]]
    assert len(sp) == 1, row
    s = sp[0]
    assert s["unit"] == "x" and s["value"] > 0
    assert s["token_parity"] is True          # bit-identical everywhere
    assert s["compiles_after_warmup"] == 0    # the frozen serve held
    # the honest-reporting bars: measured accept rate + regime label
    assert s["eval_label"] in ("repetitive", "non_repetitive")
    assert 0.0 <= s["selfdraft"]["accept_rate"] <= 1.0
    assert s["selfdraft"]["drafted"] >= s["selfdraft"]["accepted"]
    assert s["prompt_lookup"]["tokens_per_forward"] >= 1.0
    assert s["serving_ab"]["draft_on"]["spec"]["verify_forwards"] >= 1
    json.dumps(s)  # machine-readable round trip

    # the committed row's acceptance bars: >1.5x single-stream at exact
    # parity on a NON-repetitive eval, serving A/B gain, zero compiles
    committed = json.load(open(os.path.join(REPO, "BENCH_r07.json")))
    cs = [v for v in committed["variants"] if "selfdraft" in v["metric"]][0]
    assert cs["value"] > 1.5
    assert cs["eval_label"] == "non_repetitive"
    assert cs["repeated_3gram_frac"] <= 0.2
    assert cs["token_parity"] is True
    assert cs["compiles_after_warmup"] == 0
    assert cs["serving_ab"]["agg_speedup"] > 1.0
    # the control: prompt lookup proposes ~nothing on this trace — the
    # regime the lookup rows never covered is exactly where the real
    # draft generalizes the win
    assert cs["prompt_lookup"]["tokens_per_forward"] < 1.2


def test_autotune_row_emits_valid_json():
    """BENCH_AUTOTUNE=1 adds the closed batch-knee-loop row
    (bench._autotune_row): inline calibration -> auto-sized batch ->
    SLO-aware adaptive serve, A/B'd against static settings. The
    DETERMINISTIC acceptance bars ride the assertions — greedy token
    parity across every policy and ZERO post-warmup compiles across the
    adaptive run (the freeze held) — plus artifact structure; the
    beats-all-static goodput bar is pinned on the COMMITTED
    BENCH_r06.json row (a timing race on a loaded CI box is not a
    regression signal, the committed A/B is)."""
    r = _run_bench({
        "BENCH_AUTOTUNE": "1",
        "BENCH_AUTOTUNE_REQUESTS": "8",
        "BENCH_AUTOTUNE_TOKENS": "8",
        "BENCH_AUTOTUNE_BATCHES": "2,4",
        "BENCH_AUTOTUNE_STATIC": "2:16,2:8",
        "BENCH_AUTOTUNE_REPEATS": "1",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    at = [v for v in row.get("variants", [])
          if "autotune" in v["metric"]]
    assert len(at) == 1, row
    a = at[0]
    assert a["unit"] == "tok/s" and a["value"] > 0
    assert a["token_parity"] is True          # greedy outputs identical
    assert a["compiles_after_warmup"] == 0    # the ladder was all warmed
    assert a["freeze_compiles"] is True       # ...and the freeze held
    # the loop's decision record is complete and machine-readable
    assert a["calibration"]["knee"]["knee_rows"] >= 1
    assert a["calibration"]["decode_curve"], a["calibration"]
    assert a["autosize"]["serve_batch"] == a["serve_batch_auto"] >= 1
    assert a["adaptive"]["adaptive"] is True
    assert a["adaptive"]["admission"]["chunk_ladder"][0] == 32
    assert len(a["static"]) == 2
    assert a["best_static"]["goodput_tok_s"] > 0
    assert isinstance(a["beats_all_static"], bool)
    json.dumps(a)  # the row round-trips as machine-readable JSON

    # the committed artifact's acceptance bar: the self-tuned scheduler
    # met or beat every swept static setting on goodput-at-SLO there
    committed = json.load(open(os.path.join(REPO, "BENCH_r06.json")))
    cat = [v for v in committed["variants"] if "autotune" in v["metric"]][0]
    assert cat["beats_all_static"] is True
    assert cat["token_parity"] is True
    assert cat["compiles_after_warmup"] == 0

    # dlprof consumes the committed row + the committed calibration
    # artifact end to end (the drift machinery over real data)
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import dlprof

    art = dlprof.load_autotune(os.path.join(REPO, "AUTOTUNE.json"))
    report = dlprof.analyze([], [committed] + committed["variants"],
                            autotune=art)
    assert report["autotune"]["calibrated_knee_rows"] >= 1
    assert isinstance(report["autotune"]["drift"], bool)


def test_prefix_row_emits_valid_json():
    """BENCH_PREFIX=1 adds the radix prefix-cache row (bench._prefix_row):
    the shared-system-prompt Poisson trace served cache OFF vs ON. The
    acceptance bar rides the assertions: >= 50% of prefill tokens served
    from cache on this workload, and greedy outputs TOKEN-IDENTICAL to
    the cache-off run — all as one machine-readable JSON variant."""
    r = _run_bench({
        "BENCH_PREFIX": "1",
        "BENCH_PREFIX_REQUESTS": "4",
        "BENCH_PREFIX_BATCH": "2",
        "BENCH_PREFIX_SYS": "48",
        "BENCH_PREFIX_BLOCK": "16",
        "BENCH_PREFIX_TOKENS": "6",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    pfx = [v for v in row.get("variants", [])
           if "prefix_cache" in v["metric"]]
    assert len(pfx) == 1, row
    p = pfx[0]
    assert p["unit"] == "%" and p["value"] >= 50.0  # acceptance bar
    assert p["token_parity"] is True                # exact greedy parity
    assert p["requests"] == 4 and p["hit_rate"] > 0
    assert p["tokens_saved"] >= 48 * 3  # every replayed request seeded
    assert p["ttft_p50_ms_on"] >= 0 and p["ttft_p50_ms_off"] >= 0
    assert p["hbm"]["prefix_arena_bytes"] > 0  # the REAL arena's bytes
    json.dumps(p)  # the row round-trips as machine-readable JSON


def test_router_row_emits_valid_json():
    """BENCH_ROUTER=1 adds the 2-replica failover-router row
    (bench._router_row). The acceptance bars ride the assertions:
    cache-aware placement beats round-robin on prefix hit rate
    (deterministic closed-loop A/B), the open-loop chaos pass with one
    injected replica kill loses ZERO not-yet-streamed requests while
    service-level readiness never blinks, and every completed request is
    greedy token-identical across all three serves."""
    r = _run_bench({
        "BENCH_ROUTER": "1",
        "BENCH_ROUTER_PROCS": "0",   # thread row only (procs row below)
        "BENCH_ROUTER_REQUESTS": "10",
        "BENCH_ROUTER_GROUPS": "3",
        "BENCH_ROUTER_SYS": "32",
        "BENCH_ROUTER_BLOCK": "16",
        "BENCH_ROUTER_TOKENS": "6",
        "BENCH_ROUTER_KILL_AFTER": "4",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    rows = [v for v in row.get("variants", []) if "router" in v["metric"]]
    assert len(rows) == 1, row
    v = rows[0]
    assert v["unit"] == "%" and v["replicas"] == 2
    # cache-aware beats round-robin on the shared-prefix trace (the
    # ISSUE-6 acceptance bar; closed-loop => deterministic, no timing luck)
    assert v["hit_rate_gain_pct"] > 0, v
    assert v["cache_aware"]["hit_rate_pct"] > \
        v["round_robin"]["hit_rate_pct"], v
    assert v["value"] == v["cache_aware"]["hit_rate_pct"]
    # the chaos pass really killed a replica, and clients never saw an
    # unstreamed request fail — only structured mid-stream frames
    chaos = v["cache_aware_chaos"]
    assert chaos["crashes_injected"] >= 1, chaos
    assert chaos["unstreamed_failures"] == 0, chaos
    assert chaos["completed"] + chaos["midstream_failures"] == 10, chaos
    assert chaos["availability_pct"] is not None
    assert chaos["availability_pct"] >= 99.0, chaos  # readiness held
    assert v["token_parity"] is True
    assert v["hbm"]["kv_slot_bytes"] > 0  # one replica's exact shape
    json.dumps(v)  # the row round-trips as machine-readable JSON


def test_router_procs_row_emits_valid_json():
    """BENCH_ROUTER=1 also grows the PROCESS-mode row
    (bench._router_procs_row; BENCH_ROUTER_PROCS=only selects just it):
    two real replica worker OS processes behind the framed protocol, one
    delivered a genuine SIGKILL mid-Poisson-trace. The ISSUE-7 acceptance
    bars ride the assertions: ZERO unstreamed request failures (failover
    to the sibling), service availability held by the survivor, the
    supervisor classified the SIGKILL and respawned the worker to
    routable within the bound, and every completed serve of the same
    prompt is greedy token-identical — including post-respawn."""
    r = _run_bench({
        "BENCH_ROUTER": "1",
        "BENCH_ROUTER_PROCS": "only",
        "BENCH_PROCS_REQUESTS": "6",
        "BENCH_PROCS_TOKENS": "4",
        "BENCH_PROCS_KILL_AFTER": "3",
        "BENCH_PROCS_STEP_MS": "40",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    rows = [v for v in row.get("variants", [])
            if "router_procs" in v["metric"]]
    assert len(rows) == 1, row
    v = rows[0]
    assert v["unit"] == "ms" and v["mode"] == "process"
    # the kill really happened and was classified as a real SIGKILL
    assert v["exit_classes"].get("signal:SIGKILL") == 1, v
    assert v["respawns"] == 1, v
    # supervised respawn-to-routable within the configured bound
    assert v["within_bound"] is True, v
    assert v["value"] is not None and v["value"] > 0
    assert v["respawn_p50_ms"] is not None and v["respawn_p50_ms"] > 0
    # zero unstreamed failures; mid-stream casualties only, structured
    assert v["unstreamed_failures"] == 0, v
    assert v["completed"] + v["midstream_failures"] == 6 + 2, v
    # the surviving replica kept the service available throughout
    assert v["availability_pct"] is not None
    assert v["availability_pct"] >= 99.0, v
    assert v["token_parity"] is True, v
    # per-WORKER hbm ledgers merged off the stats replies (each process
    # owns its weights)
    assert any(k.startswith("r") and v["hbm"][k]["kv_slot_bytes"] > 0
               for k in v.get("hbm") or {}), v.get("hbm")
    json.dumps(v)  # the row round-trips as machine-readable JSON


def test_chaos_row_emits_valid_json():
    """BENCH_CHAOS=1 adds the fault-injection resilience row
    (bench._chaos_row): the Poisson trace replayed through the supervised
    scheduler with injected mid-trace crashes, reporting availability %,
    recovered vs failed request counts, and recovery p50 latency — all as
    one machine-readable JSON variant (matching the structured-error
    contract every other bench failure path follows)."""
    r = _run_bench({
        "BENCH_CHAOS": "1",
        "BENCH_CHAOS_REQUESTS": "4",
        "BENCH_CHAOS_BATCH": "2",
        "BENCH_CHAOS_CRASHES": "1",
        "BENCH_CLUSTER_REPEATS": "1",
        "BENCH_CLUSTER_TIMEOUT": "1.5",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    chaos = [v for v in row.get("variants", [])
             if "chaos" in v["metric"]]
    assert len(chaos) == 1, row
    c = chaos[0]
    # the cluster control-plane row rides the same BENCH_CHAOS flag:
    # two-process worker-loss detection, bounded by --worker-timeout
    cluster = [v for v in row.get("variants", [])
               if "cluster_detect" in v["metric"]]
    assert len(cluster) == 1, row
    cl = cluster[0]
    assert cl["unit"] == "ms" and cl["value"] > 0
    assert cl["within_bound"] is True, cl
    assert cl["value"] / 1e3 < cl["worker_timeout_s"], cl
    assert cl["stall_reason"] == "timeout", cl
    # dlwire (ISSUE 12): the cluster row's wire block is POPULATED — a
    # clean run's measured ledger from both ends, nonzero per-peer
    # bytes, heartbeat RTT, and the exact frame-arithmetic
    # reconciliation (drift 0.0 by construction)
    wire = cl["wire"]
    root_peer = wire["root"]["peers"]["1"]
    assert root_peer["tx"]["PING"]["bytes"] > 0, wire
    assert root_peer["rx"]["PONG"]["frames"] >= 1, wire
    assert root_peer["rtt_ms"]["n"] >= 1, wire
    assert wire["worker"]["peers"]["0"]["rx"]["RUN"]["bytes"] > 0, wire
    rec = wire["reconcile"]
    assert rec["drift_frac"] == 0.0 and rec["drift"] is False, rec
    assert rec["measured"] == rec["modeled"] > 0, rec
    # and the step_timeline is no longer empty-by-construction: the
    # control plane's "step" is one heartbeat round trip
    tl = cl["step_timeline"]
    assert tl.get("dec0_pre0_c0", {}).get("n", 0) >= 1, tl
    json.dumps(cl)  # machine-readable round-trip
    assert c["unit"] == "%" and 0.0 <= c["value"] <= 100.0
    assert c["requests"] == 4 and c["crashes_injected"] >= 1
    assert c["recoveries"] >= 1
    assert c["requests_failed_frames"] >= 1  # structured frames delivered
    # every request resolved one way or the other — nothing hung
    assert (c["ok_first_attempt"] + c["recovered_by_retry"]
            + c["unrecovered"]) == 4
    assert c["recovery_p50_ms"] is None or c["recovery_p50_ms"] >= 0
    json.dumps(c)  # the row round-trips as machine-readable JSON


def test_fleet_row_emits_valid_json():
    """BENCH_FLEET=1 adds the fleet-brain chaos row (bench._fleet_row):
    two tenants drive a process-replica tier through a 10x Poisson load
    spike with one replica SIGKILLed mid-spike, under the
    FleetController. The ISSUE-18 acceptance bars ride the assertions:
    the high-priority victim tenant's spike-phase p99 TTFT stays at SLO
    while the budgeted hog floods, the controller VISIBLY scaled the
    replica set up under the spike, zero not-yet-streamed requests were
    lost to the SIGKILL, and the respawn landed within the bound. The
    absolute-latency bars are pinned on the COMMITTED BENCH_r10.json
    row, not on CI timing."""
    r = _run_bench({
        "BENCH_FLEET": "1",
        "BENCH_FLEET_REQUESTS": "8",
        "BENCH_FLEET_VICTIM": "4",
        "BENCH_FLEET_TOKENS": "4",
        "BENCH_FLEET_STEP_MS": "30",
        "BENCH_FLEET_IAT": "0.4",
    }, timeout=560.0)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [line for line in r.stdout.strip().splitlines()
             if line.startswith("{")]
    row = json.loads(lines[-1])
    assert "error" not in row, row
    rows = [v for v in row.get("variants", []) if "fleet" in v["metric"]]
    assert len(rows) == 1, row
    v = rows[0]
    assert v["unit"] == "ms" and v["mode"] == "process"
    # the fairness bar: the victim's spike p99 TTFT held the SLO while
    # the hog flooded at 10x — WFQ + budget demotion did the isolation
    assert v["victim_within_slo"] is True, v
    assert v["value"] is not None and v["value"] > 0
    assert v["victim_base_p99_ttft_ms"] > 0, v
    # the autoscaling bar: the controller grew the set under the spike
    assert v["scale_ups"] >= 1, v
    assert v["actual_replicas_end"] >= 3, v
    # the chaos bar: SIGKILL mid-spike lost nothing unstreamed, and the
    # supervised respawn landed within the configured bound
    assert v["unstreamed_failures"] == 0, v
    assert v["within_bound"] is True, v
    assert v["completed"] >= 4, v
    # both tenants completed work — demotion, never starvation
    t = v["tenants"]
    assert t["victim"]["completed"] >= 4, t
    assert t["hog"]["completed"] >= 1, t
    json.dumps(v)  # the row round-trips as machine-readable JSON

    # the COMMITTED row carries the bars CI cannot time-assert: victim
    # p99 at SLO through the spike+kill, visible scale-up, zero
    # unstreamed losses
    art = os.path.join(REPO, "BENCH_r10.json")
    committed = json.load(open(art))
    cv = [x for x in committed["variants"] if "fleet" in x["metric"]][0]
    assert cv["victim_within_slo"] is True
    assert cv["value"] <= cv["slo_ms"]
    assert cv["scale_ups"] >= 1
    assert cv["unstreamed_failures"] == 0
    assert cv["within_bound"] is True
    assert cv["tenants"]["victim"]["completed"] > 0
    assert cv["tenants"]["hog"]["completed"] > 0


@pytest.mark.slow  # full dryrun compile in a subprocess (~100 s)
def test_dryrun_pins_cpu_before_any_jax_call():
    # dryrun_multichip must succeed with NO ambient cpu pin — the driver's
    # environment lets a sitecustomize hook point jax at the TPU plugin,
    # whose backend init hangs when the tunnel is down (the round-3
    # failure). The entry point's own config pin must land before any
    # backend initializes; if it doesn't, this either hangs into the
    # timeout (tunnel down) or comes up with 1 axon device (tunnel up) —
    # both fail the test
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    code = ("import __graft_entry__ as g; g.dryrun_multichip(2); "
            "print('DRYRUN_OK')")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=600.0, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DRYRUN_OK" in r.stdout


def test_sigterm_flushes_partial_json():
    """A driver-side `timeout` delivers SIGTERM mid-run; bench must flush
    the accumulated JSON line (partial rows kept) and exit 0 instead of
    dying silently — a ~25-min variant ladder must never lose its
    already-measured main row to a deadline."""
    import signal
    import time

    env = dict(os.environ)
    env.update({
        "BENCH_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu",
        "BENCH_MODEL": "tiny", "BENCH_TOKENS": "200",
        "BENCH_REPEATS": "200", "BENCH_VARIANTS": "0",
        "BENCH_PROBE_TIMEOUT": "30",  # don't let the probe eat the window
    })
    p = subprocess.Popen([sys.executable, os.path.join(REPO, "bench.py")],
                         stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                         text=True, env=env, cwd=REPO)
    try:
        time.sleep(18)  # past compile, mid-measurement (typical machines)
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
    finally:
        if p.poll() is None:  # never leak a decode-looping child
            p.kill()
            p.communicate(timeout=30)
    if p.returncode == -signal.SIGTERM:
        # the signal landed during module imports, before main() could
        # install the handler — an environment too slow for this probe,
        # not a product failure
        pytest.skip("SIGTERM landed before bench.py main() started")
    assert p.returncode == 0
    lines = [ln for ln in out.splitlines() if ln.startswith("{")]
    assert lines, out
    row = json.loads(lines[-1])
    # either the handler fired mid-run (error annotated) or the run beat
    # the signal (fast machine) — both must yield one parseable line
    assert "terminated" in row.get("error", "") or row.get("value")
