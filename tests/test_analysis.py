"""dlgrind analyzer tests: every AST rule has a tripping fixture and a
clean fixture; the jaxpr audit is exercised with planted violations
(host callback, f64 promotion, full-precision activation re-replication);
and the REAL gate — the committed baseline vs the current tree — runs as
a normal (non-slow) test so `pytest -m "not slow"` enforces it exactly
like CI's `python -m distributed_llama_tpu.analysis --check`.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.analysis.ast_lint import lint_source
from distributed_llama_tpu.analysis.entrypoints import (EntryPoint,
                                                        signature_fingerprint)
from distributed_llama_tpu.analysis.findings import (Finding, format_github,
                                                     load_baseline,
                                                     parse_suppressions,
                                                     split_by_baseline,
                                                     write_baseline)
from distributed_llama_tpu.analysis.jaxpr_audit import audit_entry


def rules_of(findings):
    return {f.rule for f in findings}


def lint(path, src):
    return lint_source(path, src)


# -- Level 1: one tripping + one clean fixture per rule ---------------------


def test_dlg101_host_sync_in_jit_trips():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)\n")
    assert "DLG101" in rules_of(lint("runtime/fx.py", src))


def test_dlg101_clean_on_host_values():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    table = np.asarray([1, 2, 3])\n"  # host constant: fine
        "    return x + table.shape[0]\n")
    assert "DLG101" not in rules_of(lint("runtime/fx.py", src))


def test_dlg101_item_and_device_get_trip():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = x.item()\n"
        "    b = jax.device_get(x)\n"
        "    return a, b\n")
    found = [f for f in lint("ops/fx.py", src) if f.rule == "DLG101"]
    assert len(found) == 2


def test_dlg102_numpy_on_traced_trips():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.dot(x, x)\n")
    assert "DLG102" in rules_of(lint("runtime/fx.py", src))


def test_dlg102_clean_numpy_on_host():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    scale = np.dot([1.0, 2.0], [3.0, 4.0])\n"
        "    return x * scale\n")
    assert "DLG102" not in rules_of(lint("runtime/fx.py", src))


def test_dlg103_branch_on_traced_trips():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.sum() > 0:\n"
        "        return x\n"
        "    return -x\n")
    assert "DLG103" in rules_of(lint("runtime/fx.py", src))


def test_dlg103_clean_on_static_shape_branch():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, layers):\n"
        "    if x.shape[0] > 2 and layers:\n"  # shapes + container
        "        return x\n"                   # truthiness are static
        "    if 'wqkv' in layers:\n"
        "        return x\n"
        "    return -x\n")
    assert "DLG103" not in rules_of(lint("runtime/fx.py", src))


def test_dlg103_while_on_traced_trips():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    while x > 0:\n"
        "        x = x - 1\n"
        "    return x\n")
    assert "DLG103" in rules_of(lint("runtime/fx.py", src))


def test_dlg104_bare_literal_in_ops_trips():
    src = (
        "import jax.numpy as jnp\n"
        "def act(x):\n"
        "    return x * 0.5\n")
    assert "DLG104" in rules_of(lint("ops/fx.py", src))


def test_dlg104_clean_with_explicit_dtype_and_outside_ops():
    clean = (
        "import jax.numpy as jnp\n"
        "def act(x):\n"
        "    return x * jnp.float32(0.5)\n")
    assert "DLG104" not in rules_of(lint("ops/fx.py", clean))
    bare = (
        "import jax.numpy as jnp\n"
        "def act(x):\n"
        "    return x * 0.5\n")
    # the rule is scoped to ops kernels; parallel code is exempt
    assert "DLG104" not in rules_of(lint("parallel/fx.py", bare))


def test_dlg105_missing_donate_trips():
    src = (
        "import jax\n"
        "class E:\n"
        "    def build(self):\n"
        "        def run(params, tok, pos, cache):\n"
        "            return tok, cache\n"
        "        return jax.jit(run)\n")
    assert "DLG105" in rules_of(lint("runtime/engine.py", src))


def test_dlg105_clean_with_donate_and_cacheless():
    src = (
        "import jax\n"
        "class E:\n"
        "    def build(self):\n"
        "        def run(params, tok, pos, cache):\n"
        "            return tok, cache\n"
        "        fn = jax.jit(run, donate_argnums=(3,))\n"
        "        amax = jax.jit(lambda l: l.argmax())\n"  # no cache: fine
        "        return fn, amax\n")
    assert "DLG105" not in rules_of(lint("runtime/engine.py", src))


def test_dlg106_debug_leftovers_trip():
    src = (
        "import jax\n"
        "def k(x):\n"
        "    jax.debug.print('x={}', x)\n"
        "    print('done')\n"
        "    return x\n")
    found = [f for f in lint("ops/fx.py", src) if f.rule == "DLG106"]
    assert len(found) == 2


def test_dlg106_scoped_to_kernel_dirs():
    src = "def main():\n    print('hello')\n"
    assert "DLG106" not in rules_of(lint("apps/cli.py", src))


def test_dlg107_host_boundary_sync_trips():
    src = (
        "import jax, numpy as np\n"
        "def fetch(logits: jax.Array):\n"
        "    return np.asarray(logits)\n")
    assert "DLG107" in rules_of(lint("runtime/fx.py", src))


def test_dlg107_numpy_params_are_not_device_values():
    src = (
        "import numpy as np\n"
        "def pack(x: np.ndarray):\n"
        "    return np.ascontiguousarray(x.swapaxes(-1, -2))\n")
    assert "DLG107" not in rules_of(lint("quants/fx.py", src))


def test_dlg107_taint_through_jitted_step_handle():
    src = (
        "import jax, numpy as np\n"
        "class E:\n"
        "    def step(self):\n"
        "        fn = self._compiled_step(1)\n"
        "        logits, cache = fn(self.params, self.cache)\n"
        "        return np.asarray(logits)\n")
    assert "DLG107" in rules_of(lint("runtime/fx.py", src))


def test_dlg101_rebinding_to_host_clears_taint_inside_branch():
    """Regression: the sink scan must see in-branch re-bindings — a
    pre-walk of the whole subtree with pre-branch taint flagged the second
    call here even though `x` is a host constant by then."""
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x, flag=True):\n"
        "    if flag:\n"
        "        x = np.asarray([1.0])\n"
        "        y = np.asarray(x)\n"
        "    return x\n")
    assert "DLG101" not in rules_of(lint("runtime/fx.py", src))


# -- suppression + baseline mechanics ---------------------------------------


def test_inline_suppression():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)  # dlgrind: ignore[DLG101]\n")
    assert "DLG101" not in rules_of(lint("runtime/fx.py", src))
    # the ignore is rule-specific: other rules on the line still fire
    supp = parse_suppressions(src)
    assert supp[4] == {"DLG101"}


def test_bare_suppression_covers_all_rules():
    supp = parse_suppressions("x = 1  # dlgrind: ignore\n")
    assert supp[1] is None


def test_baseline_roundtrip_and_split(tmp_path):
    f1 = Finding("DLG107", "info", "runtime/engine.py", 10, "sync A")
    f2 = Finding("DLG107", "info", "runtime/engine.py", 99, "sync B")
    path = str(tmp_path / "baseline.json")
    write_baseline(path, [f1], {"decode_step": "abc"})
    base = load_baseline(path)
    new, accepted = split_by_baseline([f1, f2], base)
    assert [f.message for f in accepted] == ["sync A"]
    assert [f.message for f in new] == ["sync B"]
    # line moves must not invalidate the baseline (keys are line-free)
    f1_moved = Finding("DLG107", "info", "runtime/engine.py", 42, "sync A")
    new2, _ = split_by_baseline([f1_moved], base)
    assert new2 == []


def test_baseline_counts_occurrences_per_key(tmp_path):
    """Multiset semantics: one allowlisted `int(n)` sync must not mask a
    reintroduced second copy with the identical message."""
    f = Finding("DLG107", "info", "runtime/engine.py", 10, "`int(n)` sync")
    path = str(tmp_path / "baseline.json")
    write_baseline(path, [f, f], {})  # two accepted sites
    base = load_baseline(path)
    assert base["findings"].count(f.key()) == 2
    trio = [Finding("DLG107", "info", "runtime/engine.py", ln,
                    "`int(n)` sync") for ln in (10, 99, 150)]
    new, accepted = split_by_baseline(trio, base)
    assert len(accepted) == 2 and len(new) == 1


def test_github_format():
    f = Finding("DLG101", "error", "runtime/engine.py", 7, "bad sync")
    out = format_github([f])
    assert out == "::error file=runtime/engine.py,line=7::DLG101: bad sync"


# -- Level 2: jaxpr audit with planted violations ---------------------------


def _ep(name, fn, args, act=4):
    return EntryPoint(name, fn, args, {"activation_elems": act})


def test_jaxpr_audit_detects_planted_f64():
    def promoted(x):
        return x * np.float64(1.5)  # the planted f64 promotion

    findings, _ = audit_entry(_ep("planted_f64", promoted,
                                  (jnp.ones((4,), jnp.float32),)))
    assert "DLG202" in rules_of(findings)


def test_jaxpr_audit_clean_on_pinned_dtypes():
    def pinned(x):
        return x * jnp.float32(1.5) + 0.25  # weak literal: no promotion

    findings, _ = audit_entry(_ep("pinned", pinned,
                                  (jnp.ones((4,), jnp.float32),)))
    assert rules_of(findings) == set()


def test_jaxpr_audit_detects_host_callback():
    def chatty(x):
        jax.debug.print("x = {}", x)
        return x + 1

    findings, _ = audit_entry(_ep("chatty", chatty,
                                  (jnp.ones((4,), jnp.float32),)))
    assert "DLG201" in rules_of(findings)


def test_jaxpr_audit_detects_replication_leak():
    from jax.sharding import PartitionSpec as P

    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.parallel.compat import shard_map

    mesh = make_mesh(tp=2, dp=1)
    x = jnp.ones((2, 8), jnp.float32)

    def leaky(x):
        # f32 all_gather re-replicates the tp-sharded activation — the
        # exact pattern the q80 exchange exists to avoid
        def body(v):
            return jax.lax.all_gather(v, "tp", tiled=True)
        return shard_map(body, mesh=mesh, in_specs=P("tp"), out_specs=P(),
                         check_vma=False)(x)

    findings, _ = audit_entry(_ep("leaky", leaky, (x,), act=16))
    assert "DLG203" in rules_of(findings)

    def compressed(x):
        # int8 payload (the q80 wire) must NOT trip the rule
        def body(v):
            q = v.astype(jnp.int8)
            return jax.lax.all_gather(q, "tp", tiled=True).astype(jnp.float32)
        return shard_map(body, mesh=mesh, in_specs=P("tp"), out_specs=P(),
                         check_vma=False)(x)

    findings2, _ = audit_entry(_ep("compressed", compressed, (x,), act=16))
    assert "DLG203" not in rules_of(findings2)


def test_audit_reports_unauditable_entry_points(monkeypatch):
    """A backend too small for the tp/ep entries must FAIL the gate
    (DLG200), not skip them silently — a vacuous pass is the worst
    outcome for a correctness gate."""
    from distributed_llama_tpu.analysis import jaxpr_audit

    monkeypatch.setattr(jax, "device_count", lambda *a, **k: 1)
    findings, fingerprints = jaxpr_audit.audit_all({})
    skipped = [f for f in findings if f.rule == "DLG200"]
    assert skipped, "short mesh produced no DLG200 findings"
    assert "tp_q80_col" in {f.file.strip("<>").split(":")[1]
                            for f in skipped}
    assert "tp_q80_col" not in fingerprints


def test_callback_finding_message_is_stable():
    """DLG201 messages are baseline keys — they must not embed object
    reprs (memory addresses change every process)."""
    def chatty(x):
        jax.debug.print("x = {}", x)
        return x

    f1, _ = audit_entry(_ep("c", chatty, (jnp.ones((2,), jnp.float32),)))
    f2, _ = audit_entry(_ep("c", chatty, (jnp.ones((2,), jnp.float32),)))
    m1 = [f.message for f in f1 if f.rule == "DLG201"]
    m2 = [f.message for f in f2 if f.rule == "DLG201"]
    assert m1 and m1 == m2
    assert "0x" not in m1[0]


def test_fingerprint_detects_signature_drift():
    def f(x):
        return x + 1

    a = signature_fingerprint(_ep("e", f, (jnp.ones((4,), jnp.float32),)))
    same = signature_fingerprint(_ep("e", f, (jnp.ones((4,), jnp.float32),)))
    wider = signature_fingerprint(_ep("e", f,
                                      (jnp.ones((4,), jnp.bfloat16),)))
    reshaped = signature_fingerprint(_ep("e", f,
                                         (jnp.ones((8,), jnp.float32),)))
    assert a == same
    assert len({a, wider, reshaped}) == 3
    # weak-typed scalars are a distinct compilation key from pinned ones —
    # the classic accidental-retrace source
    strong = signature_fingerprint(_ep("e", f, (jnp.float32(1.0),)))
    weak = signature_fingerprint(_ep("e", f, (jnp.asarray(1.0),)))
    assert strong != weak


def test_stale_pinned_fingerprint_reported_as_dlg108():
    """A baseline fingerprint for an entry point that no longer exists is
    baseline staleness (DLG108), distinct from live drift (DLG204) — a
    dead pin would otherwise shadow a future entry of the same name."""
    from distributed_llama_tpu.analysis.jaxpr_audit import audit_all

    findings, fingerprints = audit_all({"no_such_entry": "deadbeef"})
    stale = [f for f in findings if f.rule == "DLG108"]
    assert any(f.file == "<entry:no_such_entry>" for f in stale)
    assert all(f.rule != "DLG204" or "no_such_entry" not in f.file
               for f in findings)
    assert "no_such_entry" not in fingerprints


# -- the real gate: current tree vs committed baseline ----------------------


def test_analyzer_gate_repo_is_clean():
    """The CI gate, pytest-collected: the package's own source plus the
    traced entry points must produce NO findings beyond the committed
    baseline. A new host sync / f64 promotion / debug leftover anywhere in
    the package fails this test with the finding list in the message."""
    from distributed_llama_tpu.analysis.__main__ import (DEFAULT_BASELINE,
                                                         gather_findings,
                                                         hygiene_findings)

    baseline = load_baseline(DEFAULT_BASELINE)
    # same collection the CLI gate runs (L1 + dlrace + DLG206 + jaxpr),
    # so this test and `--check` cannot drift
    findings, fingerprints = gather_findings(baseline)
    new, _ = split_by_baseline(findings, baseline)
    new.extend(hygiene_findings(findings, baseline))
    assert not new, "\n".join(f"{f.anchor()}: {f.rule} {f.message}"
                              for f in new)
    # every audited entry point must have a pinned fingerprint — a NEW
    # entry point must be baselined deliberately, not silently accepted
    missing = set(fingerprints) - set(baseline.get("fingerprints", {}))
    assert not missing, f"entry points without baseline fingerprints: {missing}"
