"""Streamed sharded weight loading (models/loader.py — VERDICT r1 #5).

The reference root streams the mmap'd file and pushes shards as it walks
(ref: src/transformer.cpp:562-621); here each tensor must go host -> sharded
device placement one at a time, with peak host memory bounded by a fusion
group, never the model size.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.io.model_file import write_model
from distributed_llama_tpu.models import ArchType, ModelSpec
from distributed_llama_tpu.models.loader import load_params_streamed
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.models.transformer import KVCache, forward
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.parallel.mesh import TP_AXIS
from distributed_llama_tpu.quants.types import FloatType
from distributed_llama_tpu.runtime import Engine

from test_model_forward import make_spec, dense_weights


def _write_file(tmp_path, spec, seed=3):
    host, _ = dense_weights(spec, seed=seed)
    dense = {name: t.to_f32() for name, t in host.items()}
    path = str(tmp_path / "model.m")
    write_model(path, spec, dense)
    return path, host


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL])
@pytest.mark.parametrize("mode", ["dense", "q40"])
def test_streamed_matches_bulk_load(tmp_path, arch, mode):
    """Streamed single-device load produces the same logits as the bulk
    read_model + load_params path (incl. the fused wqkv/w13 layout)."""
    spec = make_spec(arch, dim=64, n_heads=8, n_kv_heads=4,
                     weights_float_type=FloatType.Q40 if mode == "q40"
                     else FloatType.F32)
    path, host = _write_file(tmp_path, spec)

    params, stats = load_params_streamed(spec, path, mode=mode,
                                         dtype=jnp.float32)
    assert stats.total_bytes > 0
    # streamed default fuses like the engine's tp==1 fast path
    assert "wqkv" in params["layers"][0]

    ref_params = load_params(spec, host, mode=mode, dtype=jnp.float32)
    tok = jnp.array([[7]], jnp.int32)
    want, _ = forward(ref_params, spec, tok, jnp.int32(0), KVCache.create(spec, 1))
    got, _ = forward(params, spec, tok, jnp.int32(0), KVCache.create(spec, 1))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=2e-4)


def test_streamed_peak_host_memory_bounded(tmp_path):
    """Peak resident file-tensor bytes stay one fusion-group-sized — far
    below the whole file (the 70B guarantee, measured not assumed)."""
    spec = make_spec(ArchType.LLAMA, dim=64, n_heads=8, n_kv_heads=4,
                     n_layers=8, weights_float_type=FloatType.Q40)
    path, _ = _write_file(tmp_path, spec)

    _, stats = load_params_streamed(spec, path, mode="q40")
    # the biggest resident set is the w1|w3 fusion pair plus the tensor in
    # flight; the whole file is ~8 layers of everything
    assert stats.peak_host_bytes < stats.total_bytes / 4
    per_layer = stats.total_bytes / spec.n_layers
    assert stats.peak_host_bytes < per_layer * 1.5


def test_streamed_sharded_placement(tmp_path):
    """With a tp mesh each weight lands pre-sharded (row/col split), and an
    Engine built from the streamed pytree matches the bulk-path engine."""
    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256, weights_float_type=FloatType.Q40)
    path, host = _write_file(tmp_path, spec)
    mesh = make_mesh(tp=4)

    params, _ = load_params_streamed(spec, path, mesh, mode="q40",
                                     dtype=jnp.float32)
    lw = params["layers"][0]
    assert lw["wq"].packed.sharding.spec[0] == TP_AXIS      # row split
    assert lw["wo"].packed.sharding.spec[-1] == TP_AXIS     # col split
    assert "wqkv" not in lw                                  # no fuse under tp

    eng = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    ref_params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    ref = Engine(spec, ref_params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    a = np.asarray(eng.step(np.array([[7]], np.int32), 0))
    b = np.asarray(ref.step(np.array([[7]], np.int32), 0))
    np.testing.assert_allclose(a, b, rtol=0, atol=2e-4)


def test_streamed_q80_collective_layout(tmp_path):
    """q80_collectives=True pre-repacks col weights into TpColWeight stacks
    host-side; the engine detects and keeps them, and the q80 forward runs."""
    from distributed_llama_tpu.parallel.tp_q80 import TpColWeight

    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256, weights_float_type=FloatType.Q40)
    path, host = _write_file(tmp_path, spec)
    mesh = make_mesh(tp=4)

    params, _ = load_params_streamed(spec, path, mesh, mode="q40",
                                     dtype=jnp.float32, q80_collectives=True)
    lw = params["layers"][0]
    assert isinstance(lw["wo"], TpColWeight)
    assert lw["wo"].w.packed.sharding.spec[0] == TP_AXIS    # stack axis on tp

    eng = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, q80_collectives=True)
    assert isinstance(eng.params["layers"][0]["wo"], TpColWeight)

    # numerics: matches the engine-side repack route within quant tolerance
    ref_params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    ref = Engine(spec, ref_params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, q80_collectives=True)
    a = np.asarray(eng.step(np.array([[7]], np.int32), 0))
    b = np.asarray(ref.step(np.array([[7]], np.int32), 0))
    np.testing.assert_allclose(a, b, rtol=0, atol=1e-4)
