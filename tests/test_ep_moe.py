"""Expert-parallel MoE tests on the virtual 8-device CPU mesh.

Placement-EP is net-new vs the reference, which only TP-slices every expert
(ref: src/grok1-tasks.cpp:56-143; SURVEY.md §2.5). The invariants: (1) the
ep-sharded engine reproduces the single-device greedy token stream for both
MoE archs, (2) each device actually stores only E/ep experts (the memory
claim), (3) ep composes with tp, the Pallas kernels, and the q80 reduce.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.parallel.ep_moe import EpColWeight, EpRowWeight
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights

PROMPT = [2, 5, 8, 1]


def greedy():
    return Sampler(256, temperature=0.0, topp=0.9, seed=1)


def moe_params(arch, mode="q40", seed=11):
    spec = make_spec(arch, dim=128, n_heads=8, n_kv_heads=4, hidden_dim=256)
    host, _ = dense_weights(spec, seed=seed)
    return spec, load_params(spec, host, mode=mode, dtype=jnp.float32)


def baseline_tokens(spec, params, n=6):
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False)
    return eng.generate(PROMPT, max_tokens=n, sampler=greedy()).tokens


@pytest.mark.parametrize("arch", [ArchType.MIXTRAL, ArchType.GROK1])
@pytest.mark.parametrize("ep,tp", [(2, 1), (4, 2)])
def test_ep_decode_matches_single_device(arch, ep, tp):
    spec, params = moe_params(arch)
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(ep=ep, tp=tp, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_ep_expert_placement_shards_memory():
    """Each device must hold only n_experts/ep experts' bytes — the point of
    placement-EP (per-device expert memory = total/ep)."""
    spec, params = moe_params(ArchType.MIXTRAL)
    ep = 4
    eng = Engine(spec, params, make_mesh(ep=ep, tp=2, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    lw = eng.params["layers"][0]
    assert isinstance(lw["moe_up"], EpRowWeight)
    assert isinstance(lw["moe_down"], EpColWeight)
    up = lw["moe_up"].w.packed
    assert up.sharding.spec[0] == "ep" and up.sharding.spec[1] == "tp"
    # local shard = (E/ep) experts x (d/tp) rows
    shard_shape = up.sharding.shard_shape(up.shape)
    assert shard_shape[0] == spec.n_experts // ep
    assert shard_shape[1] == up.shape[1] // 2
    down = lw["moe_down"].w.packed  # (tp, E, d, ...) stack
    assert down.sharding.spec[0] == "tp" and down.sharding.spec[1] == "ep"


def test_ep_with_pallas_and_q80():
    """ep + tp + Pallas kernels + quantized tp reduce compose; logits stay
    within block-quant tolerance of the exact ep path."""
    spec, params = moe_params(ArchType.MIXTRAL)
    mesh = make_mesh(ep=2, tp=2, dp=1)
    exact = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                   cache_dtype=jnp.float32, use_pallas=True,
                   pallas_interpret=True)
    q80 = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=True,
                 pallas_interpret=True, activation_q80=True,
                 q80_collectives=True)
    tok = np.asarray([PROMPT], np.int32)
    le = np.asarray(exact.step(tok, 0))
    lq = np.asarray(q80.step(tok, 0))
    assert np.isfinite(le).all() and np.isfinite(lq).all()
    np.testing.assert_allclose(lq, le, atol=0.05, rtol=0)
    # and the exact+pallas ep path still matches the greedy baseline
    want = baseline_tokens(spec, params)
    exact.reset()
    got = exact.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


@pytest.mark.parametrize("q80", [False, True])
def test_ep_streamed_loader_places_experts(tmp_path, q80):
    """The streamed loader must place E/ep experts per device directly (no
    full-E transient) and its output must feed the engine unchanged — incl.
    the q80 mode where col weights arrive pre-repacked as TpColWeight (a
    crash regression: repack_moe_ep must re-mark, not re-repack)."""
    import dataclasses

    from distributed_llama_tpu.io.model_file import write_model
    from distributed_llama_tpu.models.loader import load_params_streamed
    from distributed_llama_tpu.quants.types import FloatType

    spec, _ = moe_params(ArchType.MIXTRAL)
    host, _ = dense_weights(spec, seed=11)
    q40_spec = dataclasses.replace(spec, weights_float_type=FloatType.Q40)
    mpath = str(tmp_path / "tiny_moe.m")
    write_model(mpath, q40_spec, {n: t.to_f32() for n, t in host.items()})

    mesh = make_mesh(ep=2, tp=2, dp=1)
    params, _ = load_params_streamed(q40_spec, mpath, mesh, mode="q40",
                                     dtype=jnp.float32, q80_collectives=q80)
    lw = params["layers"][0]
    assert isinstance(lw["moe_up"], EpRowWeight)
    assert isinstance(lw["moe_down"], EpColWeight)
    up = lw["moe_up"].w.packed
    assert up.sharding.spec[0] == "ep"
    assert up.sharding.shard_shape(up.shape)[0] == spec.n_experts // 2

    eng = Engine(spec, params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False,
                 activation_q80=q80, q80_collectives=q80)
    if q80:
        logits = eng.step(np.asarray([PROMPT], np.int32), 0)
        assert np.isfinite(np.asarray(logits)).all()
    else:
        base = load_params(spec, host, mode="q40", dtype=jnp.float32)
        want = baseline_tokens(spec, base)
        got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
        assert got == want, (got, want)


def test_ep_requires_moe():
    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256)
    host, _ = dense_weights(spec, seed=3)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    with pytest.raises(AssertionError, match="MoE"):
        Engine(spec, params, make_mesh(ep=2, tp=1, dp=1),
               compute_dtype=jnp.float32, cache_dtype=jnp.float32)
