"""Serving resilience (runtime/faults.py + runtime/resilience.py).

The chaos contract under test: with an injected step crash mid-decode,
in-flight requests receive STRUCTURED error frames, the supervisor
rebuilds the engine, readiness flips unready -> ready, and a subsequent
request completes with output token-identical to a sequential
Engine.generate run. The watchdog detects an injected stall within its
configured bound (seconds — not the 600 s client timeout); queue overflow
and deadlines return fast structured rejections. Everything runs on CPU
with count-deterministic fault injection, so every failure shape the TPU
platform has produced (crash, hang, slow step) is reproducible in CI.

f32 on the CPU mesh so the parity assertions compare bit-exactly against
the single-row oracle (same discipline as tests/test_scheduler.py).
"""

import threading
import time

import pytest

jnp = pytest.importorskip("jax.numpy")

from distributed_llama_tpu.models import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.models.params import load_params, random_tensors
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.faults import FAULTS, FaultError, FaultRegistry
from distributed_llama_tpu.runtime.resilience import (
    BROKEN, READY, RECOVERING, EngineSupervisor, EngineUnready)
from distributed_llama_tpu.runtime.scheduler import (
    QueueFull, RequestError, Scheduler, SchedulerClosed)
from distributed_llama_tpu.sampler import Sampler

SEQ = 64


@pytest.fixture(scope="module")
def tiny():
    spec = ModelSpec(arch=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=SEQ,
                     hidden_act=HiddenAct.SILU)
    host = random_tensors(spec, seed=3, scale=0.05)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    return spec, params


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _factory(tiny, batch=2):
    spec, params = tiny

    def make():
        return Engine(spec, params, batch=batch, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32)

    return make


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)


def _oracle(spec, params, prompt, max_tokens):
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    return eng.generate(prompt, max_tokens,
                        Sampler(spec.vocab_size, temperature=0.0, topp=0.9,
                                seed=1)).tokens


def _wait(pred, timeout=30.0, poll=0.01):
    end = time.perf_counter() + timeout
    while time.perf_counter() < end:
        if pred():
            return True
        time.sleep(poll)
    return False


# -- the fault registry itself ------------------------------------------


def test_fault_registry_count_deterministic():
    r = FaultRegistry()
    r.arm("step_raise", after=2, times=2)
    r.fire("step_raise")  # hit 1: skipped
    r.fire("step_raise")  # hit 2: skipped
    with pytest.raises(FaultError):
        r.fire("step_raise")  # hit 3: fires
    with pytest.raises(FaultError):
        r.fire("step_raise")  # hit 4: fires (times=2 spent)
    r.fire("step_raise")  # hit 5: disarmed by times
    assert r.fired("step_raise") == 2
    r.clear()
    r.fire("step_raise")  # cleared: no-op


def test_fault_registry_env_parsing():
    r = FaultRegistry()
    r.load_env({"DLLAMA_FAULTS": "step_raise:after=1;times=3, slow_step:ms=5;times=0"})
    assert r.armed("step_raise") and r.armed("slow_step")
    r.fire("step_raise")  # after=1: first hit skipped
    with pytest.raises(FaultError):
        r.fire("step_raise")
    t0 = time.perf_counter()
    r.fire("slow_step")
    assert time.perf_counter() - t0 >= 0.004
    with pytest.raises(ValueError):
        FaultRegistry().load_env({"DLLAMA_FAULTS": "step_raise:bogus=1"})
    with pytest.raises(ValueError):
        FaultRegistry().load_env({"DLLAMA_FAULTS": "no_such_site"})


def test_fault_stall_releasable():
    r = FaultRegistry()
    r.arm("step_stall", ms=60_000)
    done = threading.Event()

    def stallee():
        r.fire("step_stall")
        done.set()

    t = threading.Thread(target=stallee, daemon=True)
    t.start()
    assert not done.wait(0.1)
    r.release()
    assert done.wait(5.0)


# -- scheduler-level: close(), deadlines, queue bound -------------------


def test_scheduler_close_fails_queued_waiters(tiny):
    """Regression (ISSUE 3 satellite): close() must fail queued AND
    in-flight requests so no waiter outlives close — pre-fix, queued
    submitters hung in tokens() until the 600 s timeout."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8)
    FAULTS.arm("slow_step", times=0, ms=30.0)  # keep work in flight so
    # close() provably lands while requests are live
    sched.start()
    # one in-flight + two queued beyond the single slot
    reqs = [sched.submit([1, 9, 23], 200, _greedy(spec)) for _ in range(3)]
    results: dict = {}

    def waiter(i, req):
        try:
            results[i] = ("ok", list(req.tokens(timeout=30.0)))
        except RequestError as e:
            results[i] = ("error", e.code)

    threads = [threading.Thread(target=waiter, args=(i, r), daemon=True)
               for i, r in enumerate(reqs)]
    for t in threads:
        t.start()
    _wait(lambda: any(s.req is not None for s in sched.slots), 30.0)
    t0 = time.perf_counter()
    sched.close()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "a waiter outlived close()"
    assert time.perf_counter() - t0 < 10.0
    assert len(results) == 3
    for i, req in enumerate(reqs):
        assert req.finished.is_set()
        assert results[i][0] == "error" and req.finish_reason == "error", \
            (i, results[i])
    with pytest.raises(SchedulerClosed):
        sched.submit([1], 1, _greedy(spec))


def test_scheduler_queue_bound_rejects_fast(tiny):
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8, max_queue=2)
    # no step loop running: everything stays queued
    sched.submit([1, 2], 4, _greedy(spec))
    sched.submit([1, 3], 4, _greedy(spec))
    with pytest.raises(QueueFull) as ei:
        sched.submit([1, 4], 4, _greedy(spec))
    assert ei.value.retry_after > 0
    assert sched.stats.requests_rejected == 1
    sched.close()


def test_scheduler_request_deadline_structured_frame(tiny):
    """A request over its deadline is failed mid-decode with the
    structured 'deadline' frame instead of draining its whole budget
    (the step loop is slowed so the deadline provably lands mid-run)."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8)
    FAULTS.arm("slow_step", times=0, ms=30.0)
    sched.start()
    req = sched.submit([1, 9, 23], 10_000, _greedy(spec),
                       deadline=time.perf_counter() + 0.3)
    got = []
    with pytest.raises(RequestError) as ei:
        for t in req.tokens(timeout=30.0):
            got.append(t)
    assert ei.value.code == "deadline" and not ei.value.retryable
    assert req.finish_reason == "error"
    assert sched.stats.requests_expired == 1
    assert len(got) < 60  # it did NOT drain the budget/context
    sched.close()


def test_scheduler_queue_timeout_expires_queued(tiny):
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8, queue_timeout=0.25)
    r0 = sched.submit([1, 9], 2, _greedy(spec))
    sched.step()  # admits r0 (inside its queue budget) — slot now busy
    r1 = sched.submit([1, 8], 2, _greedy(spec))  # queued behind it
    time.sleep(0.3)  # r1's queue-time budget expires while waiting
    for _ in range(100):
        if r0.finished.is_set() and r1.finished.is_set():
            break
        sched.step()
    assert r0.finish_reason == "length"  # admitted in time, unaffected
    with pytest.raises(RequestError) as ei:
        list(r1.tokens(timeout=5.0))
    assert ei.value.code == "queue_timeout"
    sched.close()


# -- supervisor: crash recovery, watchdog, breaker ----------------------


def test_step_crash_recovers_and_stays_token_identical(tiny):
    """The headline chaos test: a crash mid-decode fails in-flight
    requests with structured frames, the supervisor rebuilds, readiness
    flips unready -> ready, and the next request is oracle-identical."""
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0,
                           backoff_base=0.01, breaker_threshold=5)
    try:
        p = [1, 9, 23, 54]
        # pace the loop so the request provably cannot FINISH before the
        # crash is armed (a warm compile cache makes bare steps sub-ms)
        FAULTS.arm("slow_step", times=0, ms=25.0)
        req = sup.submit(p, 40, _greedy(spec))
        it = req.tokens(timeout=30.0)
        got = [next(it)]  # decoding is live
        FAULTS.arm("step_raise")  # next step crashes mid-decode
        with pytest.raises(RequestError) as ei:
            for t in it:
                got.append(t)
        assert ei.value.code == "engine_error"
        assert "injected step_raise" in str(ei.value)
        assert req.finish_reason == "error"
        # recovery: unready (briefly) then ready again
        assert _wait(lambda: sup.ready, 30.0), sup.state
        assert sup.sup_stats.crashes == 1
        assert sup.sup_stats.recoveries == 1
        # the rebuilt engine serves the SAME prompt oracle-identically
        FAULTS.clear()
        req2 = sup.submit(p, 6, _greedy(spec))
        assert list(req2.tokens(timeout=60.0)) == _oracle(spec, params, p, 6)
        s = sup.summary()
        assert s["state"] == READY
        assert s["requests_failed"] >= 1  # carried across the rebuild
        assert s["resilience"]["recoveries"] == 1
    finally:
        sup.close()


def test_watchdog_detects_stall_within_bound(tiny):
    """An injected step stall (the axon-hang signature raises nothing) is
    detected by the watchdog within the configured bound and recovery
    proceeds: frames delivered, engine rebuilt, ready again — in seconds,
    not the 600 s client timeout."""
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=0.5,
                           backoff_base=0.01, breaker_threshold=5)
    try:
        FAULTS.arm("slow_step", times=0, ms=25.0)  # pace: see crash test
        req = sup.submit([1, 9, 23], 40, _greedy(spec))
        FAULTS.arm("step_stall", ms=60_000)  # next step wedges "forever"
        t0 = time.perf_counter()
        with pytest.raises(RequestError) as ei:
            list(req.tokens(timeout=30.0))
        detected = time.perf_counter() - t0
        assert detected < 10.0, f"stall took {detected:.1f}s to surface"
        assert "stalled" in str(ei.value)
        assert sup.sup_stats.watchdog_trips == 1
        assert _wait(lambda: sup.ready, 30.0), sup.state
        # the wedged generation is abandoned; release it so its thread
        # exits now rather than at the 60 s stall bound
        FAULTS.clear()
        req2 = sup.submit([2, 40, 77], 4, _greedy(spec))
        assert list(req2.tokens(timeout=60.0)) == _oracle(
            spec, params, [2, 40, 77], 4)
    finally:
        FAULTS.clear()
        sup.close()


def test_supervisor_unready_rejects_submit(tiny):
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0,
                           backoff_base=0.5, breaker_threshold=5)
    try:
        FAULTS.arm("slow_step", times=0, ms=25.0)  # pace: see crash test
        req = sup.submit([1, 9], 40, _greedy(spec))
        FAULTS.arm("step_raise")  # after submit: EngineUnready must not
        # race the enqueue — the crash lands on a later iteration
        with pytest.raises(RequestError):
            list(req.tokens(timeout=30.0))
        # backoff_base 0.5 leaves a visible RECOVERING window
        assert _wait(lambda: sup.state == RECOVERING, 10.0)
        with pytest.raises(EngineUnready) as ei:
            sup.submit([1, 9], 4, _greedy(spec))
        assert ei.value.retry_after > 0
        assert sup.sup_stats.rejected_unready == 1
        assert _wait(lambda: sup.ready, 30.0)
    finally:
        sup.close()


def test_circuit_breaker_opens_and_resets(tiny):
    """N consecutive failures open the breaker: the supervisor STAYS
    unready (no rebuild churn against a dead backend) until an operator
    reset, which restores service when the fault is gone."""
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0,
                           backoff_base=0.01, breaker_threshold=2)
    try:
        # every WORKING step crashes (idle iterations never fire a fault
        # site): each submitted request crashes its generation, and the
        # second consecutive failure opens the breaker
        FAULTS.arm("step_raise", times=0)
        for _ in range(6):
            if sup.state == BROKEN:
                break
            assert _wait(lambda: sup.state in (READY, BROKEN), 30.0)
            try:
                req = sup.submit([1, 9], 8, _greedy(spec))
                with pytest.raises(RequestError):
                    list(req.tokens(timeout=30.0))
            except EngineUnready:
                time.sleep(0.05)  # raced a recovery window; try again
        assert sup.state == BROKEN, sup.state
        assert not sup.ready
        with pytest.raises(EngineUnready) as ei:
            sup.submit([1, 9], 4, _greedy(spec))
        assert ei.value.retry_after >= 30.0  # "come back much later"
        trips = sup.sup_stats.consecutive_failures
        assert trips >= 2
        time.sleep(0.2)  # breaker open: NO further rebuild attempts
        assert sup.sup_stats.consecutive_failures == trips
        FAULTS.clear()  # fault gone; operator closes the breaker
        sup.reset_breaker()
        assert _wait(lambda: sup.ready, 30.0), sup.state
        req2 = sup.submit([2, 40, 77], 4, _greedy(spec))
        assert list(req2.tokens(timeout=60.0)) == _oracle(
            spec, params, [2, 40, 77], 4)
    finally:
        FAULTS.clear()
        sup.close()


def test_supervisor_drain_finishes_inflight_then_refuses(tiny):
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0)
    try:
        req = sup.submit([1, 9, 23], 5, _greedy(spec))
        assert sup.drain(timeout=60.0)  # in-flight work completes
        assert list(req.tokens(timeout=5.0)) == _oracle(
            spec, params, [1, 9, 23], 5)
        with pytest.raises(EngineUnready):  # admissions stopped
            sup.submit([1, 9], 2, _greedy(spec))
        assert not sup.ready
    finally:
        sup.close()


def test_supervisor_exclusive_borrows_current_engine(tiny):
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0)
    try:
        r = sup.submit([1, 9, 23], 3, _greedy(spec))
        with sup.exclusive() as eng:
            assert eng is sup.engine
            assert r.finished.is_set()  # exclusive() drained it first
        assert list(r.tokens(timeout=5.0)) == _oracle(
            spec, params, [1, 9, 23], 3)
    finally:
        sup.close()


def test_prefill_raise_site_recovers(tiny):
    """The engine-entry fault site: a crash during slot prefill (not
    decode) takes the same recovery path."""
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=4, stall_timeout=60.0,
                           backoff_base=0.01, breaker_threshold=5)
    try:
        FAULTS.arm("prefill_raise")
        req = sup.submit([1, 9, 23, 54, 7], 4, _greedy(spec))
        with pytest.raises(RequestError) as ei:
            list(req.tokens(timeout=30.0))
        assert "injected prefill_raise" in str(ei.value)
        assert _wait(lambda: sup.ready, 30.0)
        req2 = sup.submit([1, 9, 23, 54, 7], 4, _greedy(spec))
        assert list(req2.tokens(timeout=60.0)) == _oracle(
            spec, params, [1, 9, 23, 54, 7], 4)
    finally:
        sup.close()


def test_slow_step_still_serves_under_deadline_pressure(tiny):
    """slow_step degrades throughput without failing; requests with
    generous deadlines complete, requests with tight deadlines get the
    fast structured 'deadline' frame instead of waiting."""
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0)
    try:
        FAULTS.arm("slow_step", times=0, ms=30.0)
        tight = sup.submit([1, 9], 10_000, _greedy(spec),
                           deadline=time.perf_counter() + 0.25)
        with pytest.raises(RequestError) as ei:
            list(tight.tokens(timeout=30.0))
        assert ei.value.code == "deadline"
        FAULTS.clear()
        ok = sup.submit([2, 40, 77], 4, _greedy(spec))
        assert list(ok.tokens(timeout=60.0)) == _oracle(
            spec, params, [2, 40, 77], 4)
        assert sup.ready  # slowness is not a failure: no recovery churn
        assert sup.sup_stats.recoveries == 0
    finally:
        FAULTS.clear()
        sup.close()


def test_terminal_delivery_exactly_once(tiny):
    """Concurrent failure paths (a dying generation's abort racing the
    supervisor's failed-during-submit fallback) may both try to finish a
    request: exactly ONE terminal event is delivered and counted."""
    spec, params = tiny
    eng = Engine(spec, params, batch=1, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32)
    sched = Scheduler(eng, chunk=8)
    req = sched.submit([1, 2], 2, _greedy(spec))
    frame = {"code": "engine_error", "message": "x", "retryable": True}
    assert sched._fail_req(req, frame) is True
    assert sched._fail_req(req, frame) is False  # second claim loses
    assert sched.stats.requests_failed == 1
    assert sched.stats.requests_finished == 1
    with pytest.raises(RequestError):
        list(req.tokens(timeout=5.0))
    assert req.events.empty()  # ONE error event, not two
    sched.close()


def test_exclusive_borrow_crash_triggers_recovery(tiny):
    """A crash inside the exclusive() borrow must not bypass supervision:
    the same recovery as a step crash (rebuild, ready again) runs and the
    exception still reaches the borrower."""
    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0,
                           backoff_base=0.01, breaker_threshold=5)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            with sup.exclusive():
                raise RuntimeError("boom")
        assert _wait(lambda: sup.ready, 30.0), sup.state
        assert sup.sup_stats.crashes == 1
        assert sup.sup_stats.recoveries == 1
        req = sup.submit([1, 9, 23], 4, _greedy(spec))
        assert list(req.tokens(timeout=60.0)) == _oracle(
            spec, params, [1, 9, 23], 4)
    finally:
        sup.close()


def test_trip_cluster_opens_breaker_with_structured_frames(tiny):
    """EngineSupervisor.trip_cluster (the api-mode mapping of a multihost
    ClusterPeerLost): in-flight requests get a NON-retryable
    cluster_peer_lost frame immediately — never a hang to their deadline
    in an orphaned collective — and the circuit opens without burning
    rebuild attempts (a local rebuild cannot resurrect a remote worker).
    reset_breaker() stays the operator's half-open."""
    from distributed_llama_tpu.parallel.multihost import ClusterPeerLost

    spec, params = tiny
    sup = EngineSupervisor(_factory(tiny), chunk=8, stall_timeout=60.0,
                           backoff_base=0.01)
    try:
        req = sup.submit([1, 2, 3], 48, _greedy(spec))
        assert _wait(lambda: req.stats.t_first is not None, 30.0)
        recoveries_before = sup.sup_stats.recoveries
        sup.trip_cluster(ClusterPeerLost(2, 10.1, "run", "timeout"))
        assert sup.state == BROKEN
        assert sup.sup_stats.cluster_losses == 1
        with pytest.raises(RequestError) as ei:
            list(req.tokens(timeout=10.0))
        assert ei.value.code == "cluster_peer_lost"
        assert ei.value.retryable is False
        assert "node 2" in str(ei.value)
        # no rebuild was attempted: BROKEN means operator intervention
        time.sleep(0.2)
        assert sup.sup_stats.recoveries == recoveries_before
        # idempotent: a second detection does not double-count
        sup.trip_cluster(ClusterPeerLost(2, 11.0, "run", "timeout"))
        assert sup.sup_stats.cluster_losses == 1
        # admission while broken is a structured fast rejection
        with pytest.raises(EngineUnready):
            sup.submit([1, 2, 3], 4, _greedy(spec))
        # operator half-open: the replica recovers once reset
        sup.reset_breaker()
        assert _wait(lambda: sup.ready, 60.0), sup.state
        req2 = sup.submit([1, 9, 23], 4, _greedy(spec))
        assert list(req2.tokens(timeout=60.0)) == _oracle(
            spec, params, [1, 9, 23], 4)
        assert sup.summary()["resilience"]["cluster_losses"] == 1
    finally:
        sup.close()
