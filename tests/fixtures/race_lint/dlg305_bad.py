"""Bad fixture: DLG305 — the scan crash, as it shipped: the stats
endpoint iterated the live window while the step loop appended.
`RuntimeError: deque mutated during iteration`, once every few thousand
requests under load."""
import threading
from collections import deque


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._window = deque(maxlen=512)  # dlrace: guarded-by(self._lock)
        self._by_key = {}  # dlrace: guarded-by(self._lock)

    def snapshot(self):
        out = [r for r in self._window]  # DLG305: comprehension, no lock
        for key, val in self._by_key.items():  # DLG305: for over .items()
            out.append((key, val))
        return sorted(self._window)  # DLG305: consuming call, no lock
