"""Clean fixture: DLG303 — both accepted shapes: acquire immediately
followed by try/finally release, and the context-manager form."""
import threading


class Breaker:
    def __init__(self):
        self._lock = threading.Lock()
        self.open_until = 0.0

    def probe(self, client):
        self._lock.acquire()
        try:
            ok = client.ping()
            if ok:
                self.open_until = 0.0
        finally:
            self._lock.release()

    def probe_with(self, client):
        with self._lock:
            return client.ping()
