"""Clean fixture: DLG302 — the slot is claimed under the lock, the slow
work runs outside it. Also shows the dedicated-I/O-mutex shape (a lock
that exists precisely to serialize a blocking send) which is deliberately
un-annotated and must not trip the rule."""
import threading
import time


class Profiler:
    def __init__(self):
        self._lock = threading.Lock()
        self._busy = False  # dlrace: guarded-by(self._lock)

    def capture(self, ms):
        with self._lock:
            if self._busy:
                return None
            self._busy = True
        try:
            time.sleep(ms / 1000.0)  # outside the critical section
        finally:
            with self._lock:
                self._busy = False
        return ms


class Client:
    def __init__(self, sock):
        self.sock = sock
        self._send_lock = threading.Lock()  # dedicated I/O mutex: no guard

    def send(self, payload):
        with self._send_lock:
            self.sock.sendall(payload)  # serializing the send is the POINT
