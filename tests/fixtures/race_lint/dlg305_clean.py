"""Clean fixture: DLG305 — snapshot under the lock, iterate the local."""
import threading
from collections import deque


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._window = deque(maxlen=512)  # dlrace: guarded-by(self._lock)
        self._by_key = {}  # dlrace: guarded-by(self._lock)

    def snapshot(self):
        with self._lock:
            window = list(self._window)
            items = list(self._by_key.items())
        out = [r for r in window]
        out.extend(items)
        return sorted(out, key=str)
