"""Bad fixture: DLG306 — deadlines on the wall clock: an NTP step during
a long spawn makes every in-flight deadline jump, classifying healthy
workers as timed out."""
import time


def wait_ready(proc, timeout):
    deadline = time.time() + timeout  # DLG306: deadline on the wall clock
    while proc.poll() is None:
        time.sleep(0.01)
    return deadline


def elapsed_ms(t0):
    return (time.time() - t0) * 1000.0  # DLG306: interval on the wall clock


class Monitor:
    def busy_for(self):
        t0 = time.time()
        self.work()
        return time.time() - t0  # DLG306: duration on the wall clock

    def work(self):
        pass
