"""Clean fixture: DLG301 — the same scheduler, lock-disciplined. Also
exercises the two caller-holds conventions (`_locked` suffix, def-line
`# dlrace: holds(...)`) that must NOT trip the rule."""
import threading
from collections import deque


class Scheduler:
    def __init__(self):
        self._mutex = threading.Lock()
        self._queue = deque()  # dlrace: guarded-by(self._mutex)
        self._closed = False  # dlrace: guarded-by(self._mutex)

    def submit(self, req):
        with self._mutex:
            if self._closed:
                raise RuntimeError("closed")
            self._queue.append(req)

    def close(self):
        with self._mutex:
            self._closed = True
            drained = list(self._queue)
            self._queue.clear()
        for req in drained:  # local snapshot: iteration needs no lock
            req.abort()

    def _admit_locked(self, req):
        # `_locked` suffix: the caller owns every class guard
        self._queue.append(req)

    def _drain(self):  # dlrace: holds(self._mutex)
        while self._queue:
            self._queue.popleft()
