"""Bad fixture: DLG302 — the watchdog-vs-capture stall shape: a
multi-second sleep inside the critical section every health probe and
stats reader also needs."""
import threading
import time


class Profiler:
    def __init__(self):
        self._lock = threading.Lock()
        self._busy = False  # dlrace: guarded-by(self._lock)

    def capture(self, ms):
        with self._lock:
            self._busy = True
            time.sleep(ms / 1000.0)  # DLG302: every reader stalls behind it
            self._busy = False
