"""Bad fixture: DLG301 — the close/submit TOCTOU, as it shipped.

close() flips the flag and drains the queue without the mutex; submit()
checks the flag lock-free and appends after the check. A request admitted
between close()'s flag write and its drain is never aborted — the caller
waits forever on a future nobody will complete.
"""
import threading
from collections import deque


class Scheduler:
    def __init__(self):
        self._mutex = threading.Lock()
        self._queue = deque()  # dlrace: guarded-by(self._mutex)
        self._closed = False  # dlrace: guarded-by(self._mutex)

    def submit(self, req):
        if self._closed:
            raise RuntimeError("closed")
        self._queue.append(req)  # DLG301: append outside the mutex

    def close(self):
        self._closed = True  # DLG301: flag write outside the mutex
        while self._queue:
            self._queue.popleft().abort()  # DLG301: drain outside the mutex
