"""Clean fixture: DLG306 — monotonic clocks for intervals; the wall clock
survives only as a timestamp (no arithmetic)."""
import time


def wait_ready(proc, timeout):
    deadline = time.perf_counter() + timeout
    while proc.poll() is None:
        if time.perf_counter() > deadline:
            raise TimeoutError
        time.sleep(0.01)


def elapsed_ms(t0):
    return (time.perf_counter() - t0) * 1000.0


def wall_stamp():
    return time.time()  # a timestamp, not an interval — fine


class Monitor:
    def busy_for(self):
        t0 = time.perf_counter()
        self.work()
        return time.perf_counter() - t0

    def work(self):
        pass
