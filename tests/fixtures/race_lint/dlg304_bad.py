"""Bad fixture: DLG304 — the unjoined `_rebuild` thread: close() joined
the watchdog but forgot the in-flight rebuild; interpreter teardown ran
the rebuild callback into a half-destroyed module dict."""
import threading


class Supervisor:
    def __init__(self):
        self._watchdog_thread = threading.Thread(target=self._watch,
                                                 daemon=True)
        self._watchdog_thread.start()
        self._rebuild_thread = None

    def kick_rebuild(self):
        self._rebuild_thread = threading.Thread(target=self._rebuild,
                                                daemon=True)
        self._rebuild_thread.start()

    def _watch(self):
        pass

    def _rebuild(self):
        pass

    def close(self):
        self._watchdog_thread.join(timeout=5.0)  # rebuild never joined
