"""Bad fixture: DLG303 — the half-open probe leak, as it shipped: the
probe acquired the breaker lock, `ping()` raised, and the release on the
fall-through line never ran — every later submit spun on a lock nobody
held."""
import threading


class Breaker:
    def __init__(self):
        self._lock = threading.Lock()
        self.open_until = 0.0

    def probe(self, client):
        self._lock.acquire()  # DLG303: ping() below can raise
        ok = client.ping()
        if ok:
            self.open_until = 0.0
        self._lock.release()
