"""Clean fixture: DLG304 — both accepted join shapes (direct receiver and
local snapshot taken under the lock), plus a fire-and-forget LOCAL thread
which is out of the rule's scope by design."""
import threading


class Supervisor:
    def __init__(self):
        self._lock = threading.Lock()
        self._watchdog_thread = threading.Thread(target=self._watch,
                                                 daemon=True)
        self._watchdog_thread.start()
        self._rebuild_thread = None  # dlrace: guarded-by(self._lock)

    def kick_rebuild(self):
        with self._lock:
            self._rebuild_thread = threading.Thread(target=self._rebuild,
                                                    daemon=True)
            self._rebuild_thread.start()

    def flash(self):
        t = threading.Thread(target=self._watch, daemon=True)
        t.start()  # local fire-and-forget: not an instance attribute

    def _watch(self):
        pass

    def _rebuild(self):
        pass

    def close(self):
        self._watchdog_thread.join(timeout=5.0)
        with self._lock:
            rebuild = self._rebuild_thread
        if rebuild is not None:
            rebuild.join(timeout=5.0)  # snapshot alias join counts
