"""Pipeline-parallelism tests on the virtual 8-device CPU mesh.

PP is net-new vs the reference, where every node runs every layer in
lock-step (ref: src/llama2-tasks.cpp:214-220; SURVEY.md §2.5). Invariants:
(1) the pp engine reproduces the single-device greedy token stream (dense,
q40, MoE), (2) each device actually stores only L/pp layers' weights and
cache (the placement claim), (3) pp composes with tp and with dp-batched
ragged generation.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models import ArchType
from distributed_llama_tpu.models.params import load_params
from distributed_llama_tpu.parallel import make_mesh
from distributed_llama_tpu.parallel.pp import PpWeight
from distributed_llama_tpu.runtime import Engine
from distributed_llama_tpu.sampler import Sampler

from test_model_forward import make_spec, dense_weights

# compile-heavy SPMD meshes / subprocess clusters: the slow tier (pytest.ini)
pytestmark = pytest.mark.slow

PROMPT = [3, 9, 1, 4]


def greedy():
    return Sampler(256, temperature=0.0, topp=0.9, seed=1)


def make_params(arch=ArchType.LLAMA, mode="q40", seed=7):
    spec = make_spec(arch, dim=128, n_heads=8, n_kv_heads=4, hidden_dim=256,
                     n_layers=4)
    host, _ = dense_weights(spec, seed=seed)
    return spec, load_params(spec, host, mode=mode, dtype=jnp.float32)


def baseline_tokens(spec, params, prompt=PROMPT, n=6):
    eng = Engine(spec, params, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False)
    return eng.generate(prompt, max_tokens=n, sampler=greedy()).tokens


@pytest.mark.parametrize("arch,mode", [
    (ArchType.LLAMA, "q40"),
    (ArchType.LLAMA, "dense"),
    (ArchType.MIXTRAL, "q40"),
])
@pytest.mark.parametrize("pp,tp", [(2, 1), (4, 2)])
def test_pp_decode_matches_single_device(arch, mode, pp, tp):
    spec, params = make_params(arch, mode)
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(pp=pp, tp=tp, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


@pytest.mark.parametrize("pp,tp", [(2, 2), (2, 1)])
def test_pp_runs_the_fused_kernels(pp, tp):
    """--pp must run the SAME fused Pallas hot path as --tp (VERDICT r2
    weak #1: the old partial-manual region silently fell back to the
    2.1x-slower XLA dequant). tp is manual inside the pp region, so the
    kernels see shard-local operands; greedy tokens must match the
    single-device stream (kernels in interpret mode on CPU)."""
    spec, params = make_params(ArchType.LLAMA, "q40")
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(pp=pp, tp=tp, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=True, pallas_interpret=True)
    assert eng.use_pallas  # not silently downgraded
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_pp_moe_runs_the_fused_kernels():
    """MoE under pp x tp with the kernels on: the manual expert gather path
    (TpRow/TpCol expert stacks) must match the single-device stream."""
    spec, params = make_params(ArchType.MIXTRAL, "q40")
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(pp=2, tp=2, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=True, pallas_interpret=True)
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_pp_stage_placement_shards_memory():
    """Each device must hold only n_layers/pp layers' weights and cache —
    the point of pipeline placement."""
    spec, params = make_params()
    pp = 4
    eng = Engine(spec, params, make_mesh(pp=pp, tp=2, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    # layers restacked into n_layers/pp slots with a pp-sharded stage axis
    assert len(eng.params["layers"]) == spec.n_layers // pp
    lw = eng.params["layers"][0]
    assert isinstance(lw["wq"], PpWeight)
    pk = lw["wq"].w.packed
    assert pk.sharding.spec[0] == "pp"
    assert pk.sharding.shard_shape(pk.shape)[0] == 1  # one stage per device
    # and the tp row split still applies within the stage
    assert pk.sharding.spec[1] == "tp"
    # cache: n_layers/pp leaves of (pp, B, KVH, S, hs), stage axis on pp
    assert len(eng.cache.k) == spec.n_layers // pp
    ck = eng.cache.k[0]
    assert ck.shape[0] == pp
    assert ck.sharding.spec[0] == "pp"
    assert ck.sharding.shard_shape(ck.shape)[0] == 1


def test_pp_dp_batched_ragged_generation():
    spec, params = make_params()
    want_a = baseline_tokens(spec, params, PROMPT, n=5)
    want_b = baseline_tokens(spec, params, PROMPT[:2], n=5)
    eng = Engine(spec, params, make_mesh(pp=2, tp=2, dp=2), batch=2,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    outs = eng.generate_batch([PROMPT, PROMPT[:2]], max_tokens=5,
                              sampler=greedy())
    assert outs == [want_a, want_b], (outs, [want_a, want_b])


def _long_prompt(n=64, seed=3):
    return np.random.default_rng(seed).integers(1, 120, n).tolist()


@pytest.mark.parametrize("arch,mode,pallas", [
    (ArchType.LLAMA, "q40", True),
    (ArchType.LLAMA, "dense", False),
    (ArchType.MIXTRAL, "q40", False),
])
@pytest.mark.parametrize("pp,tp", [(2, 2), (4, 1)])
def test_pp_gpipe_prefill_matches_single_device(arch, mode, pallas, pp, tp):
    """A 64+-token prompt takes the GPipe sequence-microbatch schedule
    (gpipe_microbatches > 1 at t >= 32*pp) — prefill logits, the cache it
    leaves behind, AND the decode steps that attend it must reproduce the
    single-device stream (VERDICT r3 weak #4)."""
    from distributed_llama_tpu.parallel.pp import gpipe_microbatches

    n = 32 * pp  # exactly at the engage threshold for this pp
    assert gpipe_microbatches(n, pp) > 1
    spec = make_spec(arch, dim=128, n_heads=8, n_kv_heads=4, hidden_dim=256,
                     n_layers=4, seq_len=n + 16)
    host, _ = dense_weights(spec, seed=7)
    params = load_params(spec, host, mode=mode, dtype=jnp.float32)
    prompt = _long_prompt(n)
    want = baseline_tokens(spec, params, prompt, n=6)
    eng = Engine(spec, params, make_mesh(pp=pp, tp=tp, dp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=pallas, pallas_interpret=pallas)
    got = eng.generate(prompt, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_pp_gpipe_batched_ragged_prefill():
    """GPipe under pp x dp with ragged right-padded prompts: per-row
    logit_index reads and per-row cache positions survive the microbatch
    rotation."""
    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256, n_layers=4, seq_len=96)
    host, _ = dense_weights(spec, seed=7)
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    long, short = _long_prompt(64), _long_prompt(37, seed=5)
    want_a = baseline_tokens(spec, params, long, n=5)
    want_b = baseline_tokens(spec, params, short, n=5)
    eng = Engine(spec, params, make_mesh(pp=2, tp=2, dp=2), batch=2,
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    outs = eng.generate_batch([long, short], max_tokens=5, sampler=greedy())
    assert outs == [want_a, want_b], (outs, [want_a, want_b])


def test_pp_gpipe_prefill_cost_and_wall():
    """The point of the schedule: prefill work per device drops from the
    all-stages scheme's full-model compute to ~(M+pp-1)/(M*pp) of it.
    Checked two ways: (a) XLA's own cost model on the compiled prefill
    step (deterministic), (b) wall clock on the CPU mesh (devices
    timeshare host cores, so wall tracks TOTAL flops — generous margin
    for noise). Also the VERDICT bar: pp=2 prefill must be in the same
    league as tp=2 prefill on the same device count, not 2x worse."""
    import time

    import jax

    spec = make_spec(ArchType.LLAMA, dim=256, n_heads=8, n_kv_heads=8,
                     hidden_dim=512, n_layers=4, seq_len=512)
    host, _ = dense_weights(spec, seed=7)
    params = load_params(spec, host, mode="dense", dtype=jnp.float32)
    prompt = _long_prompt(256)

    def build(**kw):
        return Engine(spec, params, compute_dtype=jnp.float32,
                      cache_dtype=jnp.float32, use_pallas=False, **kw)

    engines = {
        "gpipe": build(mesh=make_mesh(pp=2, tp=1), pp_gpipe=True),
        "allstages": build(mesh=make_mesh(pp=2, tp=1), pp_gpipe=False),
        "tp2": build(mesh=make_mesh(tp=2)),
    }

    flops, wall = {}, {}
    for name, eng in engines.items():
        for i in range(3):  # run 0 compiles; best-of-2 after
            eng.reset()
            t0 = time.perf_counter()
            np.asarray(eng.prefill(prompt))
            dt = time.perf_counter() - t0
            wall[name] = dt if i == 1 else min(dt, wall.get(name, dt))
        fn = eng._steps[len(prompt)]  # the compiled 256-token segment
        cost = fn.lower(eng.params, jnp.zeros((1, 256), jnp.int32),
                        jnp.int32(0), eng.cache).compile().cost_analysis()
        flops[name] = cost.get("flops", 0.0) if cost else 0.0

    # (a) compiled-flops: M=8, pp=2 -> ideal 9/16 = 0.56 of all-stages
    if flops["allstages"] and flops["gpipe"]:
        assert flops["gpipe"] < 0.75 * flops["allstages"], flops
    # (b) wall: schedule must show up end-to-end, margins loose for CI
    assert wall["gpipe"] < 0.85 * wall["allstages"], wall
    assert wall["gpipe"] < 1.8 * wall["tp2"], wall


def test_pp_bf16_engine_runs():
    """bf16 compute/cache under pp (the CLI's defaults): regression for an
    XLA CPU miscompile of a bf16 all-reduce inside the manual region — the
    stage handoff transits in f32 (parallel/pp.py)."""
    spec, params = make_params(mode="q40")
    eng = Engine(spec, params, make_mesh(pp=2, tp=2, dp=2), batch=2,
                 compute_dtype=jnp.bfloat16, cache_dtype=jnp.bfloat16,
                 use_pallas=False)
    outs = eng.generate_batch([PROMPT, PROMPT[:2]], max_tokens=3,
                              sampler=greedy())
    assert len(outs) == 2 and all(len(o) == 3 for o in outs)


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL])
def test_pp_streamed_loader_places_stages(tmp_path, arch):
    """The streamed loader must build the stage-stacked leaves directly
    (one stage row at a time into pp-sharded buffers) so per-device load
    memory is the final L/pp share — and its output must feed the engine
    unchanged, matching the single-device greedy stream."""
    import dataclasses

    from distributed_llama_tpu.io.model_file import write_model
    from distributed_llama_tpu.models.loader import load_params_streamed
    from distributed_llama_tpu.quants.types import FloatType

    spec, params = make_params(arch)
    host, _ = dense_weights(spec, seed=7)
    want = baseline_tokens(spec, params)
    q40_spec = dataclasses.replace(spec, weights_float_type=FloatType.Q40)
    mpath = str(tmp_path / "tiny.m")
    write_model(mpath, q40_spec, {n: t.to_f32() for n, t in host.items()})

    mesh = make_mesh(pp=2, tp=2, dp=1)
    loaded, _ = load_params_streamed(q40_spec, mpath, mesh, mode="q40",
                                     dtype=jnp.float32)
    lw0 = loaded["layers"][0]
    assert isinstance(lw0["wq"], PpWeight)
    pk = lw0["wq"].w.packed
    assert pk.sharding.spec[0] == "pp"
    assert pk.sharding.shard_shape(pk.shape)[0] == 1
    eng = Engine(spec, loaded, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False)
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_unfuse_inverts_fuse_exactly():
    """Engines built at tp > 1 from a dict a tp == 1 engine fused in place
    must see the original per-projection weights byte-for-byte (a manual
    row split of the fused [q|k|v] dim crosses projection boundaries)."""
    import numpy as np

    from distributed_llama_tpu.models.params import (
        fuse_layer_weights, unfuse_layer_weights)

    spec, params = make_params(mode="q40")
    lw0 = params["layers"][0]
    orig = {k: (np.asarray(lw0[k].packed), np.asarray(lw0[k].scales))
            for k in ("wq", "wk", "wv", "w1", "w3")}
    fused = fuse_layer_weights(params)  # mutates in place
    assert "wqkv" in fused["layers"][0] and "wq" not in fused["layers"][0]
    back = unfuse_layer_weights(fused, spec)
    for k, (pk, sc) in orig.items():
        np.testing.assert_array_equal(np.asarray(back["layers"][0][k].packed), pk)
        np.testing.assert_array_equal(np.asarray(back["layers"][0][k].scales), sc)
    # no-op on an unfused dict
    assert unfuse_layer_weights(back, spec) is back


@pytest.mark.parametrize("pp,ep,tp", [(2, 2, 2), (2, 2, 1), (2, 4, 1)])
def test_pp_ep_moe_matches_single_device(pp, ep, tp):
    """ep composes with pp (the Grok-class scaling layout: L/pp stages x
    E/ep experts x tp per device): decode AND the GPipe microbatch prefill
    must reproduce the single-device stream, and each device must hold
    only its stage's local experts."""
    spec, params = make_params(ArchType.MIXTRAL, "q40")
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(pp=pp, ep=ep, tp=tp),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    up = eng.params["layers"][0]["moe_up"].w.w
    shard = up.packed.sharding.shard_shape(up.packed.shape)
    assert shard[0] == 1 and shard[1] == spec.n_experts // ep, shard
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)

    # GPipe prefill through the ep x pp region (long prompt engages it)
    long = _long_prompt(64)
    spec_l = make_spec(ArchType.MIXTRAL, dim=128, n_heads=8, n_kv_heads=4,
                       hidden_dim=256, n_layers=4, seq_len=96)
    host, _ = dense_weights(spec_l, seed=7)
    params_l = load_params(spec_l, host, mode="q40", dtype=jnp.float32)
    want_l = baseline_tokens(spec_l, params_l, long, n=4)
    eng_l = Engine(spec_l, params_l, make_mesh(pp=pp, ep=ep, tp=tp),
                   compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                   use_pallas=False)
    got_l = eng_l.generate(long, max_tokens=4, sampler=greedy()).tokens
    assert got_l == want_l, (got_l, want_l)


def test_pp_ep_streamed_loader_places_expert_stages(tmp_path):
    """The streamed loader builds PpWeight(Ep...) leaves directly for
    ep x pp meshes — per-device load memory is the L/pp x E/ep share —
    and the loaded engine reproduces the single-device stream."""
    import dataclasses

    from distributed_llama_tpu.io.model_file import write_model
    from distributed_llama_tpu.models.loader import load_params_streamed
    from distributed_llama_tpu.quants.types import FloatType

    spec = make_spec(ArchType.MIXTRAL, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256, n_layers=4, seq_len=64)
    host, _ = dense_weights(spec, seed=7)
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    want = baseline_tokens(spec, params)

    qspec = dataclasses.replace(spec, weights_float_type=FloatType.Q40)
    mp = str(tmp_path / "m.m")
    write_model(mp, qspec, {n: t.to_f32() for n, t in host.items()})
    mesh = make_mesh(pp=2, ep=2, tp=2)
    sp_params, lstats = load_params_streamed(qspec, mp, mesh, mode="q40",
                                             dtype=jnp.float32)
    assert lstats.peak_host_bytes < lstats.total_bytes
    eng = Engine(spec, sp_params, mesh, compute_dtype=jnp.float32,
                 cache_dtype=jnp.float32, use_pallas=False)
    up = eng.params["layers"][0]["moe_up"].w.w
    shard = up.packed.sharding.shard_shape(up.packed.shape)
    assert shard[:2] == (1, spec.n_experts // 2), shard
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


@pytest.mark.parametrize("pp,sp,tp", [(2, 2, 2), (2, 4, 1)])
def test_pp_sp_cache_matches_single_device(pp, sp, tp):
    """sp composes with pp: the KV cache's sequence dim shards over sp
    inside the manual region (per-device cache = seq_len/sp — the
    long-context axis now stacks with stage placement). Decode AND GPipe
    prefill must reproduce the single-device stream."""
    from distributed_llama_tpu.parallel.mesh import SP_AXIS

    spec = make_spec(ArchType.LLAMA, dim=128, n_heads=8, n_kv_heads=4,
                     hidden_dim=256, n_layers=4, seq_len=128)
    host, _ = dense_weights(spec, seed=7)
    params = load_params(spec, host, mode="q40", dtype=jnp.float32)
    want = baseline_tokens(spec, params)
    long = _long_prompt(64)
    want_l = baseline_tokens(spec, params, long, n=4)

    eng = Engine(spec, params, make_mesh(pp=pp, sp=sp, tp=tp),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    leaf = eng.cache.k[0]
    assert leaf.sharding.spec[3] == SP_AXIS
    assert leaf.sharding.shard_shape(leaf.shape)[3] == spec.seq_len // sp
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)
    eng.reset()
    got_l = eng.generate(long, max_tokens=4, sampler=greedy()).tokens
    assert got_l == want_l, (got_l, want_l)


def test_pp_all_axes_moe():
    """The full five-axis story on 8 devices: a MoE model over
    pp=2 x sp=2 x ep=2 — layers in stages, cache sequence-sharded,
    experts placed — must still reproduce the single-device stream."""
    spec, params = make_params(ArchType.MIXTRAL, "q40")
    want = baseline_tokens(spec, params)
    eng = Engine(spec, params, make_mesh(pp=2, sp=2, ep=2, tp=1),
                 compute_dtype=jnp.float32, cache_dtype=jnp.float32,
                 use_pallas=False)
    got = eng.generate(PROMPT, max_tokens=6, sampler=greedy()).tokens
    assert got == want, (got, want)


def test_pp_rejects_unsupported_combos():
    spec, params = make_params()
    with pytest.raises(AssertionError, match="n_layers"):
        Engine(spec, params, make_mesh(pp=3, tp=1, dp=1),
               compute_dtype=jnp.float32, cache_dtype=jnp.float32)
